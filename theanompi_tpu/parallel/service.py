"""DCN transport for the async rules — a parameter service over TCP.

The reference's EASGD/ASGD servers were dedicated MPI ranks and GOSGD
used point-to-point MPI sends; all of that rode the cluster fabric
(SURVEY.md §2.3/§3.3/§5.8 — mount empty, no file:line).  The TPU-native
split keeps ICI for what XLA schedules (BSP collectives) and gives the
async rules what MPI p2p gave the reference: a host-level transport
that crosses machines.

Design: ONE rule-agnostic service process hosts the same stores the
in-process path uses (``parallel/server.py`` — EASGDServer, ASGDServer,
GossipHub); stores are created lazily by the first ``*_init`` request,
so the service needs no model code or rule flag at launch.  Clients
mirror the stores' duck-type APIs, so a rule session is pointed at a
remote server by a single ``server_addr=`` argument — the in-process
store remains the fast local path.  When one service process becomes
the ceiling, ``parallel/shards.py`` partitions the center across K of
them (``server_addr`` becomes a comma-separated fleet; see
:class:`ShardedServiceClient` and docs/DESIGN.md "Sharded parameter
service").

Transport: the shared RPC substrate (``parallel/rpc.py``, docs/
DESIGN.md "RPC substrate") — a selector event loop by default
(``THEANOMPI_TPU_RPC_LOOP``), ``multiprocessing.connection``-framed
chunks with HMAC challenge/response auth under a handshake deadline,
speaking one of two protocols negotiated per connection at handshake
time (docs/DESIGN.md "Wire protocol v2"):

* **v2 framed** (default) — ``parallel/wire.py``: a fixed binary
  header + JSON skeleton per message with every ndarray sent as its
  own raw buffer via memoryview (zero-copy, never pickled), with
  per-payload options: ``none``/``zlib`` compression and an
  ``f32``/``bf16`` wire dtype (f32 leaves travel as bf16 and are
  restored to f32 on receive, so accumulation at the center stores
  stays f32).  The decoder is hardened: truncated/corrupt/oversized
  frames raise a typed ``WireDecodeError`` — never a hang — and the
  server drains + survives them.
* **v1 pickle** (legacy fallback) — length-prefixed pickled tuples; a
  client whose ``wire_hello`` is refused stays here, so old peers keep
  working.

The authkey gates access either way: the server REQUIRES
``THEANOMPI_TPU_SERVICE_KEY`` (auto-generating and printing a random
one when unset), and clients refuse to connect without it — there is
no default key, because the v1 fallback is pickle and a
publicly-known secret would be remote code execution for anyone who
can reach the port.  Even with auth, run the service on a trusted
network: the v1 path (and the v2 structural-escape decode, see
``wire.WireOptions.allow_pickle``) is not safe against a peer that
legitimately holds the key; v2's ARRAY path is pickle-free in both
directions.

Client-side env knobs (all also settable per-client):
``THEANOMPI_TPU_WIRE_PROTOCOL`` (``v2``/``v1``),
``THEANOMPI_TPU_WIRE_COMPRESSION`` (``none``/``zlib``),
``THEANOMPI_TPU_WIRE_DTYPE`` (``f32``/``bf16``).

Launch:  ``python -m theanompi_tpu.parallel.service --port 45800``
"""

from __future__ import annotations

import argparse
import os
import threading
import time
import uuid
from multiprocessing.connection import Client
from typing import Any

import jax
import numpy as np

from theanompi_tpu import monitor
from theanompi_tpu.analysis.lockgraph import make_lock
from theanompi_tpu.monitor import trace
from theanompi_tpu.parallel import rpc, shm, wire
from theanompi_tpu.resilience import faults
from theanompi_tpu.resilience.retry import CONNECTION_ERRORS, RetryPolicy

PyTree = Any

DEFAULT_PORT = 45800


def _authkey(generate: bool = False) -> bytes:
    """Shared secret for the wire protocol — NO hard-coded fallback
    (VERDICT r2 #6): the transport is pickle, so a publicly-known
    default key would hand remote code execution to anyone who can
    reach the port.  Servers pass ``generate=True`` to mint a random
    per-session key when none is set (printed once, and exported into
    this process's environment so same-process clients — tests, a local
    service thread — inherit it); clients refuse outright."""
    key = os.environ.get("THEANOMPI_TPU_SERVICE_KEY")
    if key:
        return key.encode()
    if generate:
        import secrets

        key = secrets.token_hex(16)
        os.environ["THEANOMPI_TPU_SERVICE_KEY"] = key
        print(f"[service] THEANOMPI_TPU_SERVICE_KEY not set — generated "
              f"session key {key}; export it to every worker host",
              flush=True)
        return key.encode()
    raise RuntimeError(
        "THEANOMPI_TPU_SERVICE_KEY is not set — refusing to connect. "
        "The service transport is pickle; a default shared key would be "
        "publicly known and equivalent to no auth. Set the same key in "
        "the server and every worker environment (see docs/SCALING.md).")


def _np(tree: PyTree) -> PyTree:
    return jax.tree.map(np.asarray, tree)


from theanompi_tpu.utils.helper_funcs import build_optimizer


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------


class ParamService:
    """Dispatches wire ops onto lazily-created parameter stores.

    Stores are scoped by a ``session_id``: the first ``*_init`` of a
    new session id replaces the previous session's store, so a
    long-lived ``tmserver`` serves consecutive training sessions
    without inheriting stale state (a finished GOSGD session leaves its
    hub fully deactivated; EASGD/ASGD would otherwise resume a dead
    run's center).  Workers of ONE session — including other hosts —
    must share the id (the rule generates one and hands it to every
    worker client; multi-host operators pass ``--session-id``)."""

    def __init__(self):
        from theanompi_tpu.parallel.server import (
            ASGDServer,
            EASGDServer,
            GossipHub,
        )

        self._classes = {"easgd": EASGDServer, "asgd": ASGDServer,
                         "gosgd": GossipHub}
        self._stores: dict[str, Any] = {}
        self._sessions: dict[str, str] = {}
        self._init_lock = threading.Lock()

    def _fresh(self, kind: str, session_id: str) -> bool:
        """True if the caller's init should (re)create the store —
        first init of this session id wins; same-session peers join."""
        if self._sessions.get(kind) == session_id:
            return False
        self._sessions[kind] = session_id
        return True

    def easgd_init(self, params: PyTree, alpha: float, session_id: str):
        with self._init_lock:
            if self._fresh("easgd", session_id):
                self._stores["easgd"] = self._classes["easgd"](
                    params, alpha=alpha)

    def asgd_init(self, params: PyTree, opt_cfg: dict,
                  opt_state: PyTree | None, session_id: str):
        with self._init_lock:
            if self._fresh("asgd", session_id):
                tx = build_optimizer(**opt_cfg)
                store = self._classes["asgd"](params, tx)
                if opt_state is not None:  # resume
                    store.set_opt_state(opt_state)
                self._stores["asgd"] = store

    def gosgd_init(self, n_workers: int, session_id: str):
        with self._init_lock:
            if self._fresh("gosgd", session_id):
                self._stores["gosgd"] = self._classes["gosgd"](n_workers)

    def rejoin(self, kind: str, session_id: str, payload):
        """Session fencing for a worker reconnecting after a transport
        failure (docs/RESILIENCE.md).  Three cases:

        * the service never lost the session → plain join;
        * the session was DISPLACED by a newer one → refuse (same
          fail-fast as ``_store`` — a rejoined worker must not train
          against a stranger's center);
        * the service itself restarted (fresh process, no sessions) →
          rebuild the store from the surviving worker's payload —
          EASGD: (params, alpha) re-seeds the center from the worker's
          last good params; ASGD: (params, opt_cfg) re-seeds center +
          a FRESH optimizer state (server momentum is lost across a
          service restart — documented); GOSGD: (n_workers,) — the hub
          holds only in-flight gossip, which dies with the service.
        A client with no rebuild payload yet (a joiner before its
        first exchange) raises; its retry loop keeps rejoining until a
        payload-bearing peer has rebuilt the store."""
        with self._init_lock:
            cur = self._sessions.get(kind)
            if cur == session_id:
                return "joined"
            if cur is not None:
                raise SessionDisplaced(
                    f"{kind} session {session_id!r} was displaced by "
                    f"{cur!r}; refusing rejoin (this training session "
                    "is stale)")
            if payload is None:
                raise RuntimeError(
                    f"{kind} session {session_id!r} is gone (service "
                    "restart) and this client has no rebuild payload; "
                    "waiting for a peer that does")
            if kind == "easgd":
                params, alpha = payload
                self._stores["easgd"] = self._classes["easgd"](
                    params, alpha=float(alpha))
            elif kind == "asgd":
                params, opt_cfg = payload
                self._stores["asgd"] = self._classes["asgd"](
                    params, build_optimizer(**opt_cfg))
            elif kind == "gosgd":
                (n_workers,) = payload
                self._stores["gosgd"] = self._classes["gosgd"](
                    int(n_workers))
            else:
                raise ValueError(f"unknown store kind {kind!r}")
            self._sessions[kind] = session_id
            monitor.inc("service/session_rebuilds_total", kind=kind)
            print(f"[service] rebuilt {kind} session {session_id!r} "
                  "from a rejoining worker's payload", flush=True)
            return "rebuilt"

    def join(self, kind: str, session_id: str):
        """Cheap membership check for non-creator workers: validates
        the session exists WITHOUT re-shipping the init payload (N
        workers x full param tree would be redundant wire traffic)."""
        with self._init_lock:
            if self._sessions.get(kind) != session_id:
                raise RuntimeError(
                    f"{kind} session {session_id!r} is not active on this "
                    "service; the session creator must init first")

    def _store(self, kind: str, session_id: str):
        """Fail FAST when the caller's session was displaced by a newer
        init — silently serving the replacement store would corrupt
        both trainings."""
        store = self._stores.get(kind)
        if store is None:
            raise RuntimeError(f"{kind} store not initialized; a worker "
                               f"must send {kind}_init first")
        if self._sessions.get(kind) != session_id:
            raise RuntimeError(
                f"{kind} session {session_id!r} was displaced by session "
                f"{self._sessions.get(kind)!r}; this training session is "
                "stale (two sessions are sharing one service store)")
        return store

    # -- dispatch: store ops carry (op, session_id, *args) --

    def handle(self, op: str, *args):
        if op in ("easgd_init", "asgd_init", "gosgd_init", "join",
                  "rejoin"):
            return getattr(self, op)(*args)
        if op == "stats":
            out = {}
            if "easgd" in self._stores:
                out["n_exchanges"] = self._stores["easgd"].n_exchanges
            if "asgd" in self._stores:
                out["n_updates"] = self._stores["asgd"].n_updates
            return out
        if op == "ping":
            return "pong"
        if op not in self.SESSION_OPS:
            raise ValueError(f"unknown op {op!r}")
        if not args or not isinstance(args[0], str):
            raise ValueError(
                f"{op} requires (session_id, ...) — got {len(args)} args "
                "with no session id; the client may predate the "
                "session-scoped protocol")
        sid, *rest = args
        if op == "easgd_exchange":
            return _np(self._store("easgd", sid).exchange(*rest))
        if op == "easgd_exchange_n":
            return _np(self._store("easgd", sid).exchange_n(*rest))
        if op == "easgd_get_center":
            return _np(self._store("easgd", sid).get_center())
        if op == "asgd_push_pull":
            return _np(self._store("asgd", sid).push_pull(*rest))
        if op == "asgd_push_pull_n":
            return _np(self._store("asgd", sid).push_pull_n(*rest))
        if op == "asgd_set_lr":
            return self._store("asgd", sid).set_lr(*rest)
        if op == "asgd_get_center":
            return _np(self._store("asgd", sid).get_center())
        if op == "asgd_get_opt_state":
            return _np(self._store("asgd", sid).get_opt_state())
        if op == "gosgd_push":
            return self._store("gosgd", sid).push(*rest)
        if op == "gosgd_drain":
            return self._store("gosgd", sid).drain(*rest)
        if op == "gosgd_deactivate":
            return self._store("gosgd", sid).deactivate(*rest)
        raise AssertionError(f"op {op!r} in SESSION_OPS but unhandled")

    #: ops that carry (session_id, *args) — validated before unpacking
    SESSION_OPS = frozenset({
        "easgd_exchange", "easgd_exchange_n", "easgd_get_center",
        "asgd_push_pull", "asgd_push_pull_n",
        "asgd_set_lr", "asgd_get_center", "asgd_get_opt_state",
        "gosgd_push", "gosgd_drain", "gosgd_deactivate",
    })

    #: latency-critical ops the RPC substrate routes to its control
    #: pool (parallel/rpc.py): a session rejoin during a restart storm
    #: must not queue behind a pool full of parked exchanges
    RPC_CONTROL_OPS = frozenset({"join", "rejoin", "stats"})


class _ServiceRpcHooks(rpc.RpcHooks):
    """The param-service plane's seams into the shared RPC substrate
    (``parallel/rpc.py``): literal ``service/*`` series names so the
    TM403/404 docs-coverage lint keeps seeing every emission, and the
    request-driven progress heartbeat."""

    plane = "service"

    def on_connect(self) -> None:
        monitor.add_gauge("service/clients", 1.0)

    def on_disconnect(self) -> None:
        monitor.add_gauge("service/clients", -1.0)

    def on_request(self, op: str, ms: float) -> None:
        monitor.inc("service/requests_total", op=op)
        monitor.observe("service/rpc_ms", ms, op=op)
        # served work IS this process's progress
        monitor.progress(phase="serving")

    def on_error(self, op: str) -> None:
        monitor.inc("service/errors_total", op=op)

    def on_negotiate(self, opts: wire.WireOptions) -> None:
        monitor.inc("service/wire_negotiations_total",
                    compression=opts.compression, dtype=opts.dtype)


def serve(host: str = "0.0.0.0", port: int = DEFAULT_PORT,
          ready_event: threading.Event | None = None,
          stop_event: threading.Event | None = None,
          authkey: bytes | None = None,
          service: ParamService | None = None,
          loop: str | None = None,
          max_workers: int | None = None) -> None:
    """Run the service until a ``shutdown`` op (or ``stop_event``) —
    the param-service plane of the shared RPC substrate
    (``parallel/rpc.py``; ``loop=None`` reads
    ``THEANOMPI_TPU_RPC_LOOP``, default the selector event loop).

    ``authkey=None`` reads ``THEANOMPI_TPU_SERVICE_KEY`` — generating,
    printing, and exporting a random key into this process's environment
    when unset (the export is how a same-process client or spawned
    worker inherits it).  Pass ``authkey`` explicitly to avoid the env
    mutation, e.g. when embedding a service thread in a worker that also
    talks to OTHER services under different keys.

    ``service`` overrides the dispatcher — ``parallel/shards.py`` runs
    this same loop over a ``ShardParamService`` (version-fenced shard
    of a partitioned center), ``ingest/reader.py`` over an
    ``IngestReader``, ``ingest/coordinator.py`` over a coordinator.
    ``max_workers`` caps the selector loop's executor pool; a service
    that knows its admission bound exposes it as ``RPC_MAX_WORKERS``
    (in-flight work, never connection count, bounds thread count)."""
    if service is None:
        service = ParamService()
    if authkey is None:
        authkey = _authkey(generate=True)
    if max_workers is None:
        max_workers = getattr(service, "RPC_MAX_WORKERS", None)
    # backlog=64: the stdlib default is 1, and on Linux a connect that
    # overflows the accept queue looks ESTABLISHED to the client while
    # the server never saw it — a burst of legitimate connects (an
    # ingest trainer fleet, K shard clients, a reconnecting worker
    # pool) must queue, not wedge.
    rpc.serve(service, host, port, ready_event=ready_event,
              stop_event=stop_event, authkey=authkey,
              hooks=_ServiceRpcHooks(), loop=loop,
              max_workers=max_workers, backlog=64)


# ---------------------------------------------------------------------------
# Clients — duck-type the in-process stores (parallel/server.py)
# ---------------------------------------------------------------------------


def _default_wire_retry() -> RetryPolicy:
    """The client reconnect policy (env-tunable): enough patience for
    a parameter-service restart (process relaunch ~seconds), bounded
    so a permanently-gone service still fails in finite time."""
    return RetryPolicy(
        max_attempts=int(os.environ.get(
            "THEANOMPI_TPU_SERVICE_RETRIES", "8")),
        base_delay=0.1, max_delay=2.0, multiplier=2.0, jitter=0.5,
        deadline_s=float(os.environ.get(
            "THEANOMPI_TPU_SERVICE_RETRY_DEADLINE_S", "30")),
        name="service_client")


class ServiceError(RuntimeError):
    """A server-side 'err' reply — the op reached the service and was
    rejected there, so reconnecting cannot fix it (never retried)."""


class SessionDisplaced(RuntimeError):
    """A rejoin refused because a NEWER session owns the store.  Its
    class name rides the wire in the err reply (the service prefixes
    every error with ``type(e).__name__``), giving the client a typed
    marker to classify on instead of prose."""


class FenceBusy(RuntimeError):
    """A ``shard_freeze`` refused because another reader's fence holds
    the shard (``parallel/shards.py``).  Like :class:`SessionDisplaced`
    the class name rides the wire in the err reply, so the fence loop
    can classify it as retryable without matching prose."""


class ShardNotReady(RuntimeError):
    """A ``shard_freeze`` hit a shard whose session store is not (yet)
    live — typically the freeze raced a shard restart, before any
    worker's rejoin has rebuilt that shard's leaf range.  Retryable
    (the fence loop backs off while a payload-bearing worker rebuilds
    the store); a genuinely dead session exhausts the fence's bounded
    attempts instead of failing on the first race."""


#: sentinel: "no reply received yet" in ServiceClient.call's retry loop
_PENDING = object()

#: ops whose server-side effect is a destructive one-shot (a drain
#: pops inboxes; a push deposits gossip weight): once the request has
#: been SENT, a lost reply must NOT trigger a re-send — re-applying
#: would double-deliver weight or silently discard a drained payload,
#: breaking GOSGD's sum-of-weights conservation.  These ops get
#: at-MOST-once delivery across transport failures; everything else
#: (elastic exchanges, grad pushes, reads, inits) tolerates
#: at-least-once.
AT_MOST_ONCE_OPS = frozenset({"gosgd_push", "gosgd_drain"})


class ServiceClient:
    """One persistent authenticated connection; thread-safe call()
    with reconnect-with-backoff (resilience.retry): a transport
    failure mid-call closes the connection, backs off, reconnects,
    lets the subclass re-establish its session (``_rejoin`` — see
    ``ParamService.rejoin`` on service-restart semantics), and
    re-sends.  Delivery is AT-LEAST-ONCE across transport failures
    for ops whose double-application the rules' arithmetic tolerates
    (one extra elastic pull / duplicate grad push), but AT-MOST-ONCE
    for ``AT_MOST_ONCE_OPS`` (gossip push/drain): once such a request
    has been sent, a lost reply raises instead of re-sending — the
    server may have applied the destructive op already, and a silent
    re-apply would corrupt GOSGD's gossip-weight conservation
    (docs/RESILIENCE.md).  Server-side errors (``ServiceError``) are
    never retried.  ``authkey=None`` requires
    ``THEANOMPI_TPU_SERVICE_KEY`` (raising BEFORE any network touch
    when unset — there is no default key)."""

    def __init__(self, address: str, authkey: bytes | None = None,
                 retry: RetryPolicy | None = None,
                 protocol: str | None = None,
                 wire_opts: wire.WireOptions | None = None,
                 transport: "rpc.MuxConnection | None" = None):
        p = rpc.unix_path(address)
        if p is not None:
            # a str address IS the AF_UNIX form the stdlib Client
            # understands; everything else is host:port TCP
            self.address: Any = p
        else:
            host, _, port = address.rpartition(":")
            self.address = (host or "127.0.0.1", int(port))
        self._authkey = authkey if authkey is not None else _authkey()
        self._retry = retry if retry is not None else _default_wire_retry()
        protocol = protocol or os.environ.get(
            "THEANOMPI_TPU_WIRE_PROTOCOL", "v2")
        if protocol not in ("v1", "v2"):
            raise ValueError(f"protocol must be 'v1' or 'v2', "
                             f"got {protocol!r}")
        self._want_v2 = protocol == "v2"
        self._wire_opts = (wire_opts if wire_opts is not None
                           else wire.WireOptions.from_env())
        #: negotiated per-connection: None = v1 pickle
        self._wire: wire.WireOptions | None = None
        #: trace grant from the hello: only then does _call_once wrap
        #: requests in the wire.TRACE_OP context envelope
        self._trace = False
        #: offer the shared-memory payload lane at hello time; a typed
        #: ShmRefusal flips this off and the client silently retries
        #: in-band (the lane's degradation contract)
        self._shm_on = True
        #: the lane channel THIS client negotiated (None when riding a
        #: mux transport, whose shared channel the transport owns)
        self._own_shm: "shm.ShmChannel | None" = None
        self._lock = threading.Lock()
        #: optional shared multiplexed transport (parallel/rpc.py):
        #: this client becomes one logical stream on the transport's
        #: socket instead of owning a socket — K clients to one peer
        #: then cost one fd and ONE reader thread between them.  The
        #: transport already negotiated wire options per-connection;
        #: against a non-mux server it silently hands back dedicated
        #: sockets and this client behaves exactly as before.
        self._transport = transport
        self._connect()

    def _connect(self) -> None:
        """(Re)establish the underlying conn + negotiated options."""
        if self._transport is not None:
            with self._lock:
                self._conn, pre = self._transport.connect_stream()
            if pre is not None:  # mux stream: negotiation is inherited
                if not self._want_v2:
                    raise ValueError(
                        "protocol='v1' cannot ride a multiplexed "
                        "transport — mux streams are wire-v2 framed")
                self._wire = pre
                self._trace = self._transport.trace
                return
        else:
            with self._lock:
                self._conn = Client(self.address,  # guarded_by: self._lock
                                    authkey=self._authkey)
                rpc.set_nodelay(self._conn)
        self._negotiate()

    # -- transport -----------------------------------------------------

    @property
    def wire_protocol(self) -> str:
        """The protocol this connection actually negotiated."""
        return "v2" if self._wire is not None else "v1"

    def _negotiate(self) -> None:
        """Version negotiation at handshake time: one v1-pickled
        ``wire_hello`` round-trip.  A v2 server confirms and the
        connection switches to framed mode; a legacy server answers
        "unknown op" and the connection stays on v1 pickle — the
        fallback is silent by design (old tmservers keep working)."""
        self._wire = None
        self._trace = False
        self._own_shm = None
        if not self._want_v2:
            return
        offer = shm.client_offer() if self._shm_on else None
        with self._lock:
            self._conn.send((wire.HELLO_OP,
                             wire.hello_payload(self._wire_opts,
                                                shm_offer=offer)))
            status, payload = self._conn.recv()
        if (status == "ok" and isinstance(payload, dict)
                and payload.get("version") == wire.WIRE_VERSION):
            # a legacy server's reply simply omits "shm" and the lane
            # stays off — the same silent degradation as trace below
            self._own_shm = shm.client_channel(offer, payload)
            self._wire = wire.WireOptions(
                compression=payload.get("compression", "none"),
                dtype=payload.get("dtype", "f32"),
                allow_pickle=self._wire_opts.allow_pickle,
                shm=self._own_shm)
            # absent from a legacy server's reply — trace propagation
            # degrades silently, like compression/dtype
            self._trace = bool(payload.get("trace"))

    def _reconnect(self) -> None:
        ch, self._own_shm = self._own_shm, None
        if ch is not None:
            # leases of the dying connection must not wait out the
            # timeout; a shared mux channel is NOT ours to close
            ch.close()
        with self._lock:
            try:
                self._conn.close()
            except OSError:
                pass
        # the negotiation is per-connection (or per-transport) state —
        # _connect redoes it; a dead mux transport is re-established
        # by connect_stream inside
        self._connect()

    def _rejoin(self) -> None:
        """Subclass hook: re-establish server-side session state after
        a reconnect (the base client is session-less)."""

    def _call_once(self, op: str, *args):
        """One send/recv on the current connection; raises transport
        errors (retryable) or ServiceError (not).  Transport errors
        are tagged with whether the request had already been SENT —
        the retry loop needs it to keep AT_MOST_ONCE_OPS from being
        re-applied after a lost reply."""
        msg = (op, *args)
        if self._trace:
            # the caller's open span (or attached remote context)
            # becomes the server-side parent; nothing open -> plain
            # message, and the envelope is never sent without the
            # hello grant, so legacy servers never see TRACE_OP
            ctx = trace.inject()
            if ctx is not None:
                msg = (wire.TRACE_OP, ctx, *msg)
        with self._lock:
            sent = False
            try:
                if self._wire is not None:
                    wire.send_msg(self._conn, msg, self._wire)
                    sent = True
                    status, payload = wire.recv_msg(self._conn,
                                                    self._wire)
                else:
                    self._conn.send(msg)
                    sent = True
                    status, payload = self._conn.recv()
            except CONNECTION_ERRORS as e:
                # WireDecodeError lands here too (it subclasses
                # ConnectionError): a garbled reply stream is recovered
                # exactly like a dropped connection — reconnect,
                # renegotiate, re-send (at-most-once ops excepted)
                e._tm_sent = sent
                raise
        if status != "ok":
            raise ServiceError(f"service error for {op}: {payload}")
        return payload

    def call(self, op: str, *args):
        # fault plane (no-op without a plan): 'drop' synthesizes a
        # transport failure below so the Kth RPC exercises the real
        # reconnect path; 'delay' sleeps in fire(); 'raise' propagates
        fault = faults.fire("service_call", op=op)
        # byte/latency accounting only when telemetry is live: the
        # tree walk is cheap but not free, and the disabled path must
        # stay a pure transport
        mon = monitor.enabled()
        if mon:
            t0 = time.monotonic()
            monitor.inc("service/client_bytes_sent",
                        monitor.tree_bytes(args), op=op)
        t_start = time.monotonic()
        last: BaseException | None = None
        needs_rejoin = False
        payload = _PENDING
        for attempt in range(self._retry.max_attempts):
            if attempt:
                deadline = self._retry.deadline_s
                if (deadline is not None
                        and time.monotonic() - t_start > deadline):
                    break
                time.sleep(self._retry.delay(attempt - 1))
            try:
                if needs_rejoin:
                    # re-establish transport AND session before
                    # re-sending; a failure here (service still down,
                    # or the store not rebuilt yet — a payload-bearing
                    # peer may rebuild it any moment) re-enters the
                    # retry loop rather than sending an op the server
                    # must reject
                    self._reconnect()
                    self._rejoin()
                    needs_rejoin = False
                if fault == "drop":
                    fault = None  # drop once, then the retry proceeds
                    raise ConnectionResetError(
                        "injected service_call drop (fault plan)")
                payload = self._call_once(op, *args)
                break
            except ServiceError as e:
                if wire.ShmRefusal.__name__ in str(e):
                    # the server refused shm content in OUR frame (its
                    # lane state is gone — restart, swept lease, ...):
                    # the op never dispatched, so re-sending is safe
                    # even for at-most-once ops.  Disable the lane and
                    # reconnect in-band — silent degradation, never a
                    # caller-visible failure.
                    self._disable_shm()
                    last = e
                    needs_rejoin = True
                    monitor.inc("service/client_reconnects_total",
                                op=op)
                    continue
                if needs_rejoin:
                    # typed marker: the service prefixes every err
                    # reply with the exception class name, so this
                    # matches SessionDisplaced, not prose wording
                    if SessionDisplaced.__name__ in str(e):
                        # permanent: this session is stale (a newer
                        # one owns the store) — retrying would only
                        # dress a session error up as a network one
                        raise
                    last = e  # store not rebuilt yet — keep rejoining
                    continue
                if mon:
                    monitor.inc("service/client_errors_total", op=op)
                raise
            except CONNECTION_ERRORS as e:
                if isinstance(e, wire.ShmRefusal):
                    # the REPLY carried shm content this side must
                    # refuse — drop the lane before reconnecting so
                    # the re-negotiation omits the offer
                    self._disable_shm()
                if (op in AT_MOST_ONCE_OPS
                        and getattr(e, "_tm_sent", False)):
                    # the request reached the wire and the REPLY was
                    # lost: the server may have applied this
                    # destructive op already — surfacing beats
                    # silently corrupting gossip-weight conservation
                    raise ConnectionError(
                        f"reply lost for non-idempotent {op}; not "
                        "re-sending (the server may have applied it "
                        f"already): {e}") from e
                last = e
                needs_rejoin = True
                monitor.inc("service/client_reconnects_total", op=op)
        if payload is _PENDING:  # attempts or deadline exhausted
            elapsed = time.monotonic() - t_start
            if isinstance(last, ServiceError):
                # the TRANSPORT recovered; what never came back was
                # the session store — name the real problem
                raise ServiceError(
                    f"session not re-established for {op} after "
                    f"{elapsed:.1f}s: {last}") from last
            raise ConnectionError(
                f"service at {self.address} unreachable for {op} "
                f"after {elapsed:.1f}s: {last}") from last
        if mon:
            monitor.inc("service/client_bytes_recv",
                        monitor.tree_bytes(payload), op=op)
            monitor.observe("service/client_rpc_ms",
                            (time.monotonic() - t0) * 1e3, op=op)
        return payload

    def _disable_shm(self) -> None:
        """Silently degrade to in-band frames: the next (re)connect
        omits the shm offer.  A shared mux transport drops its lane
        for every sibling stream — it cannot renegotiate per stream —
        and their owners reconnect through their own retry loops."""
        self._shm_on = False
        if self._transport is not None:
            self._transport.disable_shm()

    def close(self) -> None:
        ch, self._own_shm = self._own_shm, None
        if ch is not None:
            ch.close()  # release leases the peer never acked
        # Deliberately does NOT take self._lock: an RPC thread wedged
        # in a blocking v1 recv holds the lock indefinitely, and
        # closing the fd out from under it is the only way another
        # thread can unstick it (the recv raises OSError/EOFError and
        # the retry loop surfaces it).  Liveness beats tidiness here.
        try:
            self._conn.close()  # lint: ok TM101
        except OSError:
            pass


class ShardedServiceClient:
    """Client-side shard router (ISSUE 8, docs/DESIGN.md "Sharded
    parameter service"): K per-shard session clients — each its own
    authenticated connection, :class:`RetryPolicy`, and rejoin state,
    so a single shard's restart is recovered exactly like the tested
    single-server restart matrix, re-seeding ONLY that shard's leaf
    range — plus the concurrency plumbing the subclasses
    (``parallel/shards.py`` ShardedEASGD / ShardedASGD, which own the
    tree partitioning) build on:

    * :meth:`_scatter` issues one sub-call per shard on dedicated
      exchange threads (``parallel/pipe.py`` — the same thread
      discipline the async rules' overlap plane uses) and collects ALL
      K results before re-raising the first failure, so a dead shard
      can never leave a sibling's sub-exchange dangling on the pipes'
      bounded-staleness barrier;
    * :meth:`fenced_read` is the cross-shard version fence — the
      two-phase consistent cut checkpoint/export reads through:
      **freeze** every shard (each blocks new exchanges and drains its
      in-flight one, returning its per-client vector clock), compare
      the clocks, and only **read + release** when they all agree.  A
      mismatch means some worker's full-tree exchange straddled the
      freeze (applied on one shard, still pending on another); the
      fence releases everything, backs off, and retries, so a
      checkpoint can never capture shard A after exchange E and shard
      B before it.

    Mutating sub-calls carry a ``(client_id, seq)`` tag — one ``seq``
    per FULL-tree operation, shared by all K sub-calls — which is what
    makes the vector clocks comparable across shards.  Delivery
    semantics are unchanged from the single-center client: elastic
    exchanges and grad pushes stay at-least-once across transport
    failures (a re-sent duplicate re-applies, exactly as documented
    for :class:`ServiceClient`), and the vector clock's per-client max
    keeps a duplicate from reading as a new exchange."""

    def __init__(self, shard_clients: list, kind: str, session_id: str,
                 transports: list | None = None):
        if not shard_clients:
            raise ValueError("need at least one shard client")
        self._shard_clients = list(shard_clients)
        self._kind = kind
        self._sid = str(session_id)
        #: optional per-shard rpc.MuxConnection transports shared by
        #: the data client and the fence client of each shard — one
        #: socket per PEER where granted.  Safe precisely because the
        #: selector loop routes shard_freeze/release (and the fenced
        #:  read/write ops) to its control pool: a freeze-parked
        #: mutation parks an executor worker, never the connection's
        #: read loop, so the fence no longer needs its own SOCKET to
        #: dodge head-of-line blocking — only its own stream.
        self._transports = list(transports) if transports else None
        #: tags this router's mutations in every shard's vector clock
        self._client_id = uuid.uuid4().hex
        self._router_lock = make_lock("ShardedServiceClient._router_lock")
        self._seq = 0        # guarded_by: self._router_lock
        self._pipes = None   # guarded_by: self._router_lock
        # the fence runs over its OWN control connections: a mutation
        # blocked by the freeze parks its connection's server handler
        # thread in fence admission, so freeze/read/release sharing
        # that connection would queue BEHIND the very exchange the
        # fence is holding back — head-of-line deadlock until the
        # fence auto-expires, and a read that then observes post-
        # freeze state (caught by the test suite's torn-cut pin)
        self._fence_clients: list[ServiceClient | None] = \
            [None] * len(shard_clients)  # guarded_by: self._router_lock

    @property
    def n_shards(self) -> int:
        return len(self._shard_clients)

    @property
    def wire_protocol(self) -> str:
        """Negotiated protocol (shards negotiate independently but
        from one env/default, so shard 0 speaks for the fleet)."""
        return self._shard_clients[0].wire_protocol

    # -- concurrent scatter/gather ------------------------------------

    def _next_seq(self) -> int:
        with self._router_lock:
            self._seq += 1
            return self._seq

    def _ensure_pipes(self) -> list:
        from theanompi_tpu.parallel.pipe import _ExchangePipe

        with self._router_lock:
            if self._pipes is None:
                # lazily: a client used only for fenced reads (the
                # EASGD orchestrator) never spins exchange threads
                self._pipes = [
                    _ExchangePipe(lambda thunk: thunk(), "shard", i,
                                  span="shard_exchange")
                    for i in range(len(self._shard_clients))]
            return self._pipes

    def _reset_pipes(self) -> None:
        """Drop the exchange threads after a scatter failure: the
        pipes' sticky-error discipline is right for a worker loop (the
        supervisor rebuilds the whole client) but this router object
        may outlive the failure (the rule's creator handle does), so
        the next scatter gets fresh pipes instead of a poisoned
        barrier."""
        with self._router_lock:
            pipes, self._pipes = self._pipes, None
        for p in pipes or ():
            p.close()

    def _scatter(self, thunks: list):
        """Run one thunk per shard concurrently (each on its shard's
        exchange thread); returns results in shard order.  Collects
        every in-flight sub-call before re-raising the first failure."""
        pipes = self._ensure_pipes()
        for pipe, thunk in zip(pipes, thunks):
            pipe.submit(thunk)
        outs: list = []
        first_err: BaseException | None = None
        for pipe in pipes:
            try:
                _, out = pipe.collect()
                outs.append(out)
            except BaseException as e:
                outs.append(None)
                if first_err is None:
                    first_err = e
        if first_err is not None:
            self._reset_pipes()
            raise first_err
        return outs

    # -- the cross-shard version fence --------------------------------

    def _fence_client(self, i: int) -> "ServiceClient":
        """The shard's dedicated control connection (lazy — a client
        that never fences opens no extra sockets)."""
        with self._router_lock:
            c = self._fence_clients[i]
        if c is None:
            addr = self._shard_clients[i].address
            # reconstruct the address string the client parses: the
            # str form is an AF_UNIX path, the tuple form host:port
            addr = (f"{rpc.UNIX_PREFIX}{addr}" if isinstance(addr, str)
                    else f"{addr[0]}:{addr[1]}")
            c = ServiceClient(addr,
                              transport=(self._transports[i]
                                         if self._transports else None))
            with self._router_lock:
                if self._fence_clients[i] is None:
                    self._fence_clients[i] = c
                else:  # lost a benign race; keep the first
                    c.close()
                    c = self._fence_clients[i]
        return c

    def fenced_read(self, read_op: str, max_attempts: int = 100):
        """Two-phase consistent cut over ``read_op`` (see
        :meth:`fenced_op`)."""
        return self.fenced_op(read_op, max_attempts=max_attempts)

    def fenced_op(self, op: str, *args, max_attempts: int = 100):
        """Two-phase consistent cut (class docstring): freeze all →
        compare vector clocks → run ``op`` on every shard →
        RE-VALIDATE → release, retrying on a straddling exchange, a
        concurrent reader's fence, or a shard mid-restart.  Returns
        ``(per-shard results in shard order, the cut's vector
        clock)``.

        ``op`` may also be a fleet-wide WRITE that must not interleave
        with any client's K-way scatter (ShardedASGD's ``set_lr``: a
        mid-broadcast push would apply with the old lr on some leaf
        ranges and the new lr on others — the single-center store
        serializes the two under one lock, and the fence is that
        lock's distributed form).  Such an op must be idempotent: a
        failed validation re-runs it on the next attempt.

        Two hardening rules beyond the happy path:

        * **Post-read validation.**  A fence the reader held too long
          auto-expires server-side (a dead reader must not wedge
          training), which could let a mutation slip onto a shard read
          later in the loop — a torn cut presented as consistent.  So
          after the reads, every shard is re-frozen with the SAME
          token and BOTH its vector clock and its applied-mutation
          counter compared to the pre-read ones; any drift discards
          the attempt.  The counter matters because the clock alone is
          blind to an at-least-once DUPLICATE re-apply (recorded as
          per-client max seq) slipping through an expired fence.  A
          cut is returned only when no mutation landed anywhere
          between first freeze and validation.
        * **Stable-divergence acceptance.**  Exact clock equality can
          become permanently unreachable: a client that died mid-
          scatter leaves its (client, seq) on some shards forever, and
          a restarted shard loses entries for clients that never
          exchange again.  A PENDING straddler applies within the
          release window between attempts (admission is notified on
          release), so clocks that stay bitwise-identical across 3
          consecutive frozen observations — with released windows
          between — are dead history, not in-flight work: the cut is
          accepted (``service/shard_fence_divergence_total``) with the
          per-client max clock.  The frozen state itself is still
          validated mutation-free; what is lost is only the claim that
          the dead client's partial op never happened — the system
          state already includes it, permanently.
        """
        token = uuid.uuid4().hex
        t0 = time.monotonic()
        last: BaseException | None = None
        n = self.n_shards
        prev_clocks: list | None = None
        stable = 0

        def freeze(i: int):
            return self._fence_client(i).call(
                "shard_freeze", self._kind, self._sid, token)

        for attempt in range(max_attempts):
            if attempt:
                # jittered to de-synchronize from a fixed exchange
                # cadence; short because the straddler completes as
                # soon as the release lands
                time.sleep(min(0.25, 0.005 * (1 << min(attempt, 5)))
                           * (0.5 + (hash((token, attempt)) % 100) / 100))
            err, infos = self._fanout(freeze)
            if err is not None:
                self._release(token)
                if self._fence_retryable(err):
                    last = err  # another reader's fence, a shard mid-
                    continue    # restart, or a connect refused while
                                # the process group relaunches it
                raise err
            clocks = [info["vclock"] for info in infos]
            applied = [info.get("applied") for info in infos]
            consistent = all(vc == clocks[0] for vc in clocks)
            if not consistent:
                stable = stable + 1 if clocks == prev_clocks else 0
                prev_clocks = clocks
                if stable < 2:
                    self._release(token)
                    monitor.inc("service/shard_fence_retries_total")
                    continue
                monitor.inc("service/shard_fence_divergence_total")
            try:
                op_err, outs = self._fanout(
                    lambda i: self._fence_client(i).call(op, self._sid,
                                                         *args))
                if op_err is None:
                    # post-op validation: re-freeze with the same
                    # token; drifted clocks OR applied counters mean an
                    # expired fence let a mutation (possibly a
                    # clock-invisible duplicate) through mid-op —
                    # discard the torn cut
                    op_err, post = self._fanout(freeze)
            finally:
                self._release(token)
            if op_err is not None:
                if self._fence_retryable(op_err):
                    last = op_err
                    continue
                raise op_err
            if ([p["vclock"] for p in post] != clocks
                    or [p.get("applied") for p in post] != applied):
                prev_clocks, stable = None, 0  # live mutator: not dead
                monitor.inc("service/shard_fence_retries_total")
                last = RuntimeError("fence expired mid-operation")
                continue
            monitor.observe("service/shard_fence_ms",
                            (time.monotonic() - t0) * 1e3)
            if consistent:
                return outs, clocks[0]
            merged: dict = {}
            for vc in clocks:
                for cid, seq in vc.items():
                    merged[cid] = max(seq, merged.get(cid, 0))
            return outs, merged
        raise RuntimeError(
            f"no consistent cut across {n} shards after "
            f"{max_attempts} freeze attempts "
            f"({time.monotonic() - t0:.1f}s): {last}")

    @staticmethod
    def _fence_retryable(e: BaseException) -> bool:
        """Fence-loop errors worth another attempt: another reader's
        fence, a shard whose store is mid-rejoin, or a transport
        failure (incl. a connect refused while the process group is
        relaunching the shard — ServiceClient construction has no
        retry of its own)."""
        if isinstance(e, ServiceError):
            return (FenceBusy.__name__ in str(e)
                    or ShardNotReady.__name__ in str(e))
        return isinstance(e, CONNECTION_ERRORS)

    def _fanout(self, fn) -> tuple[BaseException | None, list]:
        """Run ``fn(i)`` for every shard concurrently; returns (first
        error or None, per-shard results).  Used for the freeze /
        read / validate sweeps so the fence-hold time — during which
        every shard's mutations are parked — is ONE shard's latency,
        not the sum, and so a worker's K-way scatter has the smallest
        possible window to straddle the freeze."""
        n = self.n_shards
        outs: list = [None] * n
        errs: list = [None] * n
        # captured on the calling thread so every per-shard RPC stays
        # inside the caller's trace instead of rooting its own
        ctx = trace.capture()

        def run(i: int) -> None:
            try:
                with trace.attach_wire(ctx):
                    outs[i] = fn(i)
            except BaseException as e:
                errs[i] = e

        threads = [threading.Thread(target=run, args=(i,), daemon=True,
                                    name=f"shard-fence-{i}")
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return next((e for e in errs if e is not None), None), outs

    def _release(self, token: str) -> None:
        """Best-effort concurrent release of every shard: releasing a
        token a shard never froze is a server-side no-op, and an
        unreachable shard auto-expires its fence (ShardParamService
        fence timeout)."""
        def rel(i: int):
            try:
                return self._fence_client(i).call(
                    "shard_release", self._kind, self._sid, token)
            except Exception:
                return None

        self._fanout(rel)

    def close(self) -> None:
        self._reset_pipes()
        with self._router_lock:
            fence, self._fence_clients = (list(self._fence_clients),
                                          [None] * self.n_shards)
        for c in fence:
            if c is not None:
                c.close()
        for c in self._shard_clients:
            c.close()
        for t in self._transports or ():
            if t is not None:
                t.close()


class RemoteEASGD(ServiceClient):
    """EASGDServer API over the wire (rules/async_rules.py EASGD).

    ``session_id`` scopes the server-side store: the session CREATOR
    passes host-numpy ``params`` (first init of a new id creates the
    center; a later id replaces a finished session's store); additional
    worker clients of the same session pass ``params=None`` to join
    without re-shipping the tree.  Every subsequent op carries the id —
    a displaced session fails fast instead of training against a
    stranger's center.
    """

    def __init__(self, address: str, params: PyTree | None, alpha: float,
                 session_id: str = "default", transport=None):
        super().__init__(address, transport=transport)
        self._sid = str(session_id)
        self._alpha = float(alpha)
        # rebuild payload for a rejoin after a SERVICE restart: the
        # creator's init params, refreshed with every exchange result
        # (a joiner has none until its first exchange — its rejoin
        # waits for a payload-bearing peer, see ParamService.rejoin)
        self._rebuild = None if params is None \
            else _np(jax.device_get(params))
        if params is None:
            self.call("join", "easgd", self._sid)
        else:
            self.call("easgd_init", self._rebuild, self._alpha, self._sid)

    def _rejoin(self) -> None:
        self._call_once(
            "rejoin", "easgd", self._sid,
            None if self._rebuild is None
            else (self._rebuild, self._alpha))

    def exchange(self, worker_params: PyTree) -> PyTree:
        out = self.call("easgd_exchange", self._sid,
                        _np(jax.device_get(worker_params)))
        self._rebuild = out
        return out

    def exchange_n(self, worker_mean: PyTree, n: int) -> PyTree:
        """Aggregated exchange (parallel/aggregate.py): one wire round
        trip for n co-located workers; returns the PRE-update center
        (see ``EASGDServer.exchange_n``) — a legitimate rebuild
        payload, so a post-aggregate rejoin re-seeds from it."""
        out = self.call("easgd_exchange_n", self._sid,
                        _np(jax.device_get(worker_mean)), int(n))
        self._rebuild = out
        return out

    def get_center(self) -> PyTree:
        return self.call("easgd_get_center", self._sid)

    @property
    def n_exchanges(self) -> int:
        return int(self.call("stats").get("n_exchanges", 0))


class RemoteASGD(ServiceClient):
    """ASGDServer API over the wire (see RemoteEASGD on sessions)."""

    def __init__(self, address: str, params: PyTree | None, opt_cfg: dict,
                 opt_state: PyTree | None = None,
                 session_id: str = "default", transport=None):
        super().__init__(address, transport=transport)
        self._sid = str(session_id)
        self._opt_cfg = dict(opt_cfg)
        # rebuild payload: latest known CENTER (init params, refreshed
        # by every push_pull reply).  A rejoin after a service restart
        # re-seeds the center from it with a fresh optimizer state —
        # server momentum does not survive a service restart.
        self._rebuild = None if params is None \
            else _np(jax.device_get(params))
        if params is None:
            self.call("join", "asgd", self._sid)
        else:
            self.call("asgd_init", self._rebuild, self._opt_cfg,
                      None if opt_state is None
                      else _np(jax.device_get(opt_state)), self._sid)

    def _rejoin(self) -> None:
        self._call_once(
            "rejoin", "asgd", self._sid,
            None if self._rebuild is None
            else (self._rebuild, self._opt_cfg))

    def push_pull(self, grads: PyTree) -> PyTree:
        out = self.call("asgd_push_pull", self._sid,
                        _np(jax.device_get(grads)))
        self._rebuild = out
        return out

    def push_pull_n(self, grad_sum: PyTree, n: int) -> PyTree:
        """Aggregated grad push (parallel/aggregate.py): the delta-sum
        of n co-located workers' pushes in one wire round trip; the
        reply is the fresh center (see ``ASGDServer.push_pull_n``)."""
        out = self.call("asgd_push_pull_n", self._sid,
                        _np(jax.device_get(grad_sum)), int(n))
        self._rebuild = out
        return out

    def set_lr(self, lr: float) -> None:
        self.call("asgd_set_lr", self._sid, float(lr))

    def get_center(self) -> PyTree:
        return self.call("asgd_get_center", self._sid)

    def get_opt_state(self) -> PyTree:
        return self.call("asgd_get_opt_state", self._sid)

    @property
    def n_updates(self) -> int:
        return int(self.call("stats").get("n_updates", 0))


class RemoteGossipHub(ServiceClient):
    """GossipHub API over the wire.  ``rank_offset`` maps this host's
    local worker ranks onto the global gossip rank space when several
    hosts share one hub (see RemoteEASGD on sessions; gosgd_init is
    payload-free so every client may send it)."""

    def __init__(self, address: str, n_workers: int, rank_offset: int = 0,
                 session_id: str = "default", transport=None):
        super().__init__(address, transport=transport)
        self._sid = str(session_id)
        self.n_workers = n_workers
        self.rank_offset = rank_offset
        self.call("gosgd_init", int(n_workers), self._sid)

    def _rejoin(self) -> None:
        # always rebuildable: the hub holds only in-flight gossip,
        # which legitimately dies with the service
        self._call_once("rejoin", "gosgd", self._sid,
                        (int(self.n_workers),))

    def push(self, dst: int, params: PyTree, weight: float) -> bool:
        return self.call("gosgd_push", self._sid, int(dst),
                         _np(jax.device_get(params)), float(weight))

    def drain(self, rank: int):
        return self.call("gosgd_drain", self._sid,
                         int(rank + self.rank_offset))

    def deactivate(self, rank: int) -> None:
        self.call("gosgd_deactivate", self._sid,
                  int(rank + self.rank_offset))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="theanompi-tpu async-rule parameter service (DCN)")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=DEFAULT_PORT)
    ap.add_argument("--platform", default=None,
                    help="jax platform for the service's merge arithmetic "
                         "(e.g. 'cpu' so the service never claims a chip)")
    ap.add_argument("--loop", default=None,
                    choices=("selector", "threaded"),
                    help="RPC substrate (parallel/rpc.py; default "
                         "$THEANOMPI_TPU_RPC_LOOP or 'selector')")
    args = ap.parse_args(argv)
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    print(f"[service] listening on {args.host}:{args.port}", flush=True)
    # telemetry for a standalone service process: request counters,
    # per-op latency, connected-client gauge, heartbeat — activated by
    # $THEANOMPI_TPU_MONITOR (no-op otherwise).  The stall watchdog is
    # disabled (inf): a server's progress is request-driven, and an
    # idle service is healthy, not stuck — progress_age_s in the
    # heartbeat still shows time since the last served request.
    # distinct file suffix: a tmserver sharing THEANOMPI_TPU_MONITOR
    # with a trainer on the same host must not clobber rank0's files
    with monitor.session(stall_after=float("inf"),
                         name=f"service{os.getpid()}"):
        monitor.progress(phase="serving")
        serve(args.host, args.port, loop=args.loop)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
