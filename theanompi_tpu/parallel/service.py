"""DCN transport for the async rules — a parameter service over TCP.

The reference's EASGD/ASGD servers were dedicated MPI ranks and GOSGD
used point-to-point MPI sends; all of that rode the cluster fabric
(SURVEY.md §2.3/§3.3/§5.8 — mount empty, no file:line).  The TPU-native
split keeps ICI for what XLA schedules (BSP collectives) and gives the
async rules what MPI p2p gave the reference: a host-level transport
that crosses machines.

Design: ONE rule-agnostic service process hosts the same stores the
in-process path uses (``parallel/server.py`` — EASGDServer, ASGDServer,
GossipHub); stores are created lazily by the first ``*_init`` request,
so the service needs no model code or rule flag at launch.  Clients
mirror the stores' duck-type APIs, so a rule session is pointed at a
remote server by a single ``server_addr=`` argument — the in-process
store remains the fast local path.

Transport: ``multiprocessing.connection`` (stdlib) — length-prefixed
pickled messages with HMAC challenge/response auth.  Parameter pytrees
travel as numpy trees (the reference shipped flattened GPU buffers over
MPI; ``utils/helper_funcs.tree_to_vector`` remains available for
byte-exact wire framing, but pickle protocol 5 already moves numpy
buffers without copies).  The authkey gates access: the server REQUIRES
``THEANOMPI_TPU_SERVICE_KEY`` (auto-generating and printing a random
one when unset), and clients refuse to connect without it — there is no
default key, because pickle + a publicly-known secret would be remote
code execution for anyone who can reach the port.  Even with auth, run
the service on a trusted network: pickle is not safe against a peer
that legitimately holds the key.

Launch:  ``python -m theanompi_tpu.parallel.service --port 45800``
"""

from __future__ import annotations

import argparse
import os
import threading
import time
from multiprocessing.connection import Client, Connection, Listener
from typing import Any

import jax
import numpy as np

from theanompi_tpu import monitor

PyTree = Any

DEFAULT_PORT = 45800


def _authkey(generate: bool = False) -> bytes:
    """Shared secret for the wire protocol — NO hard-coded fallback
    (VERDICT r2 #6): the transport is pickle, so a publicly-known
    default key would hand remote code execution to anyone who can
    reach the port.  Servers pass ``generate=True`` to mint a random
    per-session key when none is set (printed once, and exported into
    this process's environment so same-process clients — tests, a local
    service thread — inherit it); clients refuse outright."""
    key = os.environ.get("THEANOMPI_TPU_SERVICE_KEY")
    if key:
        return key.encode()
    if generate:
        import secrets

        key = secrets.token_hex(16)
        os.environ["THEANOMPI_TPU_SERVICE_KEY"] = key
        print(f"[service] THEANOMPI_TPU_SERVICE_KEY not set — generated "
              f"session key {key}; export it to every worker host",
              flush=True)
        return key.encode()
    raise RuntimeError(
        "THEANOMPI_TPU_SERVICE_KEY is not set — refusing to connect. "
        "The service transport is pickle; a default shared key would be "
        "publicly known and equivalent to no auth. Set the same key in "
        "the server and every worker environment (see docs/SCALING.md).")


def _np(tree: PyTree) -> PyTree:
    return jax.tree.map(np.asarray, tree)


from theanompi_tpu.utils.helper_funcs import build_optimizer


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------


class ParamService:
    """Dispatches wire ops onto lazily-created parameter stores.

    Stores are scoped by a ``session_id``: the first ``*_init`` of a
    new session id replaces the previous session's store, so a
    long-lived ``tmserver`` serves consecutive training sessions
    without inheriting stale state (a finished GOSGD session leaves its
    hub fully deactivated; EASGD/ASGD would otherwise resume a dead
    run's center).  Workers of ONE session — including other hosts —
    must share the id (the rule generates one and hands it to every
    worker client; multi-host operators pass ``--session-id``)."""

    def __init__(self):
        from theanompi_tpu.parallel.server import (
            ASGDServer,
            EASGDServer,
            GossipHub,
        )

        self._classes = {"easgd": EASGDServer, "asgd": ASGDServer,
                         "gosgd": GossipHub}
        self._stores: dict[str, Any] = {}
        self._sessions: dict[str, str] = {}
        self._init_lock = threading.Lock()

    def _fresh(self, kind: str, session_id: str) -> bool:
        """True if the caller's init should (re)create the store —
        first init of this session id wins; same-session peers join."""
        if self._sessions.get(kind) == session_id:
            return False
        self._sessions[kind] = session_id
        return True

    def easgd_init(self, params: PyTree, alpha: float, session_id: str):
        with self._init_lock:
            if self._fresh("easgd", session_id):
                self._stores["easgd"] = self._classes["easgd"](
                    params, alpha=alpha)

    def asgd_init(self, params: PyTree, opt_cfg: dict,
                  opt_state: PyTree | None, session_id: str):
        with self._init_lock:
            if self._fresh("asgd", session_id):
                tx = build_optimizer(**opt_cfg)
                store = self._classes["asgd"](params, tx)
                if opt_state is not None:  # resume
                    store.set_opt_state(opt_state)
                self._stores["asgd"] = store

    def gosgd_init(self, n_workers: int, session_id: str):
        with self._init_lock:
            if self._fresh("gosgd", session_id):
                self._stores["gosgd"] = self._classes["gosgd"](n_workers)

    def join(self, kind: str, session_id: str):
        """Cheap membership check for non-creator workers: validates
        the session exists WITHOUT re-shipping the init payload (N
        workers x full param tree would be redundant wire traffic)."""
        with self._init_lock:
            if self._sessions.get(kind) != session_id:
                raise RuntimeError(
                    f"{kind} session {session_id!r} is not active on this "
                    "service; the session creator must init first")

    def _store(self, kind: str, session_id: str):
        """Fail FAST when the caller's session was displaced by a newer
        init — silently serving the replacement store would corrupt
        both trainings."""
        store = self._stores.get(kind)
        if store is None:
            raise RuntimeError(f"{kind} store not initialized; a worker "
                               f"must send {kind}_init first")
        if self._sessions.get(kind) != session_id:
            raise RuntimeError(
                f"{kind} session {session_id!r} was displaced by session "
                f"{self._sessions.get(kind)!r}; this training session is "
                "stale (two sessions are sharing one service store)")
        return store

    # -- dispatch: store ops carry (op, session_id, *args) --

    def handle(self, op: str, *args):
        if op in ("easgd_init", "asgd_init", "gosgd_init", "join"):
            return getattr(self, op)(*args)
        if op == "stats":
            out = {}
            if "easgd" in self._stores:
                out["n_exchanges"] = self._stores["easgd"].n_exchanges
            if "asgd" in self._stores:
                out["n_updates"] = self._stores["asgd"].n_updates
            return out
        if op == "ping":
            return "pong"
        if op not in self.SESSION_OPS:
            raise ValueError(f"unknown op {op!r}")
        if not args or not isinstance(args[0], str):
            raise ValueError(
                f"{op} requires (session_id, ...) — got {len(args)} args "
                "with no session id; the client may predate the "
                "session-scoped protocol")
        sid, *rest = args
        if op == "easgd_exchange":
            return _np(self._store("easgd", sid).exchange(*rest))
        if op == "easgd_get_center":
            return _np(self._store("easgd", sid).get_center())
        if op == "asgd_push_pull":
            return _np(self._store("asgd", sid).push_pull(*rest))
        if op == "asgd_set_lr":
            return self._store("asgd", sid).set_lr(*rest)
        if op == "asgd_get_center":
            return _np(self._store("asgd", sid).get_center())
        if op == "asgd_get_opt_state":
            return _np(self._store("asgd", sid).get_opt_state())
        if op == "gosgd_push":
            return self._store("gosgd", sid).push(*rest)
        if op == "gosgd_drain":
            return self._store("gosgd", sid).drain(*rest)
        if op == "gosgd_deactivate":
            return self._store("gosgd", sid).deactivate(*rest)
        raise AssertionError(f"op {op!r} in SESSION_OPS but unhandled")

    #: ops that carry (session_id, *args) — validated before unpacking
    SESSION_OPS = frozenset({
        "easgd_exchange", "easgd_get_center", "asgd_push_pull",
        "asgd_set_lr", "asgd_get_center", "asgd_get_opt_state",
        "gosgd_push", "gosgd_drain", "gosgd_deactivate",
    })


def serve(host: str = "0.0.0.0", port: int = DEFAULT_PORT,
          ready_event: threading.Event | None = None,
          stop_event: threading.Event | None = None,
          authkey: bytes | None = None) -> None:
    """Run the service until a ``shutdown`` op (or ``stop_event``).
    One handler thread per connection; each worker thread keeps its own
    persistent connection, so worker exchanges proceed concurrently up
    to the store's own lock.

    ``authkey=None`` reads ``THEANOMPI_TPU_SERVICE_KEY`` — generating,
    printing, and exporting a random key into this process's environment
    when unset (the export is how a same-process client or spawned
    worker inherits it).  Pass ``authkey`` explicitly to avoid the env
    mutation, e.g. when embedding a service thread in a worker that also
    talks to OTHER services under different keys."""
    service = ParamService()
    if stop_event is None:
        stop_event = threading.Event()  # so the shutdown op works
    if authkey is None:
        authkey = _authkey(generate=True)
    listener = Listener((host, port), authkey=authkey)
    if ready_event is not None:
        ready_event.set()

    def handle_conn(conn: Connection):
        # connected-client gauge: one handler thread per connection, so
        # inc/dec here IS the live connection count
        monitor.add_gauge("service/clients", 1.0)
        try:
            with conn:
                while True:
                    try:
                        msg = conn.recv()
                    except (EOFError, OSError):
                        return
                    if not isinstance(msg, tuple) or not msg:
                        monitor.inc("service/errors_total", op="malformed")
                        conn.send(("err", "malformed request"))
                        continue
                    op, *args = msg
                    if op == "shutdown":
                        conn.send(("ok", None))
                        if stop_event is not None:
                            stop_event.set()
                        # unblock accept() so the serve loop exits
                        try:
                            Client((host if host != "0.0.0.0"
                                    else "127.0.0.1",
                                    port), authkey=authkey).close()
                        except OSError:
                            pass
                        return
                    t0 = time.monotonic()
                    try:
                        result = service.handle(op, *args)
                    except Exception as e:  # surfaced client-side
                        monitor.inc("service/errors_total", op=op)
                        conn.send(("err", f"{type(e).__name__}: {e}"))
                        continue
                    try:
                        conn.send(("ok", result))
                    except (EOFError, OSError):
                        return  # peer gone; nothing to tell it
                    except Exception as e:
                        # reply failed to SERIALIZE (send pickles before
                        # writing, so no bytes hit the wire yet) — the
                        # client must still get a diagnostic, not a bare
                        # EOFError
                        monitor.inc("service/errors_total", op=op)
                        conn.send(("err", f"{type(e).__name__}: {e}"))
                        continue
                    monitor.inc("service/requests_total", op=op)
                    monitor.observe("service/rpc_ms",
                                    (time.monotonic() - t0) * 1e3,
                                    op=op)
                    # served work IS this process's progress
                    monitor.progress(phase="serving")
        finally:
            monitor.add_gauge("service/clients", -1.0)

    from multiprocessing import AuthenticationError

    with listener:
        while stop_event is None or not stop_event.is_set():
            try:
                conn = listener.accept()
            except AuthenticationError:
                continue  # a bad-key peer must not kill the service
            except OSError:
                if stop_event is not None and stop_event.is_set():
                    return
                raise
            threading.Thread(target=handle_conn, args=(conn,),
                             daemon=True).start()


# ---------------------------------------------------------------------------
# Clients — duck-type the in-process stores (parallel/server.py)
# ---------------------------------------------------------------------------


class ServiceClient:
    """One persistent authenticated connection; thread-safe call().
    ``authkey=None`` requires ``THEANOMPI_TPU_SERVICE_KEY`` (raising
    BEFORE any network touch when unset — there is no default key)."""

    def __init__(self, address: str, authkey: bytes | None = None):
        host, _, port = address.rpartition(":")
        self.address = (host or "127.0.0.1", int(port))
        self._conn = Client(self.address,
                            authkey=authkey if authkey is not None
                            else _authkey())
        self._lock = threading.Lock()

    def call(self, op: str, *args):
        # byte/latency accounting only when telemetry is live: the
        # tree walk is cheap but not free, and the disabled path must
        # stay a pure transport
        mon = monitor.enabled()
        if mon:
            t0 = time.monotonic()
            monitor.inc("service/client_bytes_sent",
                        monitor.tree_bytes(args), op=op)
        with self._lock:
            self._conn.send((op, *args))
            status, payload = self._conn.recv()
        if status != "ok":
            if mon:
                monitor.inc("service/client_errors_total", op=op)
            raise RuntimeError(f"service error for {op}: {payload}")
        if mon:
            monitor.inc("service/client_bytes_recv",
                        monitor.tree_bytes(payload), op=op)
            monitor.observe("service/client_rpc_ms",
                            (time.monotonic() - t0) * 1e3, op=op)
        return payload

    def close(self) -> None:
        try:
            self._conn.close()
        except OSError:
            pass


class RemoteEASGD(ServiceClient):
    """EASGDServer API over the wire (rules/async_rules.py EASGD).

    ``session_id`` scopes the server-side store: the session CREATOR
    passes host-numpy ``params`` (first init of a new id creates the
    center; a later id replaces a finished session's store); additional
    worker clients of the same session pass ``params=None`` to join
    without re-shipping the tree.  Every subsequent op carries the id —
    a displaced session fails fast instead of training against a
    stranger's center.
    """

    def __init__(self, address: str, params: PyTree | None, alpha: float,
                 session_id: str = "default"):
        super().__init__(address)
        self._sid = str(session_id)
        if params is None:
            self.call("join", "easgd", self._sid)
        else:
            self.call("easgd_init", _np(jax.device_get(params)),
                      float(alpha), self._sid)

    def exchange(self, worker_params: PyTree) -> PyTree:
        return self.call("easgd_exchange", self._sid,
                         _np(jax.device_get(worker_params)))

    def get_center(self) -> PyTree:
        return self.call("easgd_get_center", self._sid)

    @property
    def n_exchanges(self) -> int:
        return int(self.call("stats").get("n_exchanges", 0))


class RemoteASGD(ServiceClient):
    """ASGDServer API over the wire (see RemoteEASGD on sessions)."""

    def __init__(self, address: str, params: PyTree | None, opt_cfg: dict,
                 opt_state: PyTree | None = None,
                 session_id: str = "default"):
        super().__init__(address)
        self._sid = str(session_id)
        if params is None:
            self.call("join", "asgd", self._sid)
        else:
            self.call("asgd_init", _np(jax.device_get(params)),
                      dict(opt_cfg),
                      None if opt_state is None
                      else _np(jax.device_get(opt_state)), self._sid)

    def push_pull(self, grads: PyTree) -> PyTree:
        return self.call("asgd_push_pull", self._sid,
                         _np(jax.device_get(grads)))

    def set_lr(self, lr: float) -> None:
        self.call("asgd_set_lr", self._sid, float(lr))

    def get_center(self) -> PyTree:
        return self.call("asgd_get_center", self._sid)

    def get_opt_state(self) -> PyTree:
        return self.call("asgd_get_opt_state", self._sid)

    @property
    def n_updates(self) -> int:
        return int(self.call("stats").get("n_updates", 0))


class RemoteGossipHub(ServiceClient):
    """GossipHub API over the wire.  ``rank_offset`` maps this host's
    local worker ranks onto the global gossip rank space when several
    hosts share one hub (see RemoteEASGD on sessions; gosgd_init is
    payload-free so every client may send it)."""

    def __init__(self, address: str, n_workers: int, rank_offset: int = 0,
                 session_id: str = "default"):
        super().__init__(address)
        self._sid = str(session_id)
        self.n_workers = n_workers
        self.rank_offset = rank_offset
        self.call("gosgd_init", int(n_workers), self._sid)

    def push(self, dst: int, params: PyTree, weight: float) -> bool:
        return self.call("gosgd_push", self._sid, int(dst),
                         _np(jax.device_get(params)), float(weight))

    def drain(self, rank: int):
        return self.call("gosgd_drain", self._sid,
                         int(rank + self.rank_offset))

    def deactivate(self, rank: int) -> None:
        self.call("gosgd_deactivate", self._sid,
                  int(rank + self.rank_offset))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="theanompi-tpu async-rule parameter service (DCN)")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=DEFAULT_PORT)
    ap.add_argument("--platform", default=None,
                    help="jax platform for the service's merge arithmetic "
                         "(e.g. 'cpu' so the service never claims a chip)")
    args = ap.parse_args(argv)
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    print(f"[service] listening on {args.host}:{args.port}", flush=True)
    # telemetry for a standalone service process: request counters,
    # per-op latency, connected-client gauge, heartbeat — activated by
    # $THEANOMPI_TPU_MONITOR (no-op otherwise).  The stall watchdog is
    # disabled (inf): a server's progress is request-driven, and an
    # idle service is healthy, not stuck — progress_age_s in the
    # heartbeat still shows time since the last served request.
    # distinct file suffix: a tmserver sharing THEANOMPI_TPU_MONITOR
    # with a trainer on the same host must not clobber rank0's files
    with monitor.session(stall_after=float("inf"),
                         name=f"service{os.getpid()}"):
        monitor.progress(phase="serving")
        serve(args.host, args.port)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
