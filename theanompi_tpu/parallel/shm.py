"""Shared-memory payload lane — zero-copy same-host frames (ISSUE 20).

Every fleet this repo runs under ``tmlocal`` — shard processes, ingest
readers, prefill/decode replicas, the front-door router — is a
SAME-HOST process group whose hottest payloads (the 22.8M-param
exchange tree, uint8 ingest pixel batches, KV-page ships) cross a
loopback socket with at least two in-band copies per array.  The
sendmsg scatter-gather work in ``parallel/rpc.py`` removed the
*serialization* copies; the kernel socket copy in and out remained.

This module is the out-of-band half of the wire-v2 shm lane
(docs/DESIGN.md "Shared-memory lane"):

* **Arena** (one per process) — allocates one ``/dev/shm`` segment per
  outgoing frame via ``multiprocessing.shared_memory``, stamps a
  header (magic + generation), and tracks the lease under a deadline.
  An ACKED segment is RECYCLED — parked on a freelist and reissued to
  a later frame under a bumped generation, so a steady-state exchange
  costs one warm ``memcpy`` per direction instead of a
  create/zero-fill/unlink cycle (on one host core that cycle is ~4x
  the memcpy).  Recycling is safe precisely because of when the ack
  fires (below): an ack proves every receiver view of that segment is
  already dead.  Every OTHER release path — lease expiry, channel
  close, freelist overflow — unlinks instead of recycling, because
  those cannot prove the receiver is done; and since the receiver's
  ``mmap`` pins the inode, an unlink can never tear surviving views.
* **Lease** — one per frame: every shm-eligible leaf of the frame is
  packed into the same segment at 64-byte-aligned offsets, and the
  frame's skeleton carries ``(segment, offset, length, generation)``
  descriptors instead of in-band buffers.
* **ShmChannel** — per-connection lane state, hung off the negotiated
  ``wire.WireOptions``.  The sender side allocates leases (any failure
  degrades silently to in-band bytes); the receiver side maps
  segments read-only and queues the decref **ack** when the mapping
  DIES — a ``weakref.finalize`` on the ``mmap`` fires once the last
  decoded view is garbage; the ack then piggybacks on the
  connection's next outgoing frame.  The refcount IS the view
  lifetime: a consumer that retains views (a KV cache pinning pages)
  simply never acks, so that segment is never recycled and its data
  stays valid forever, while drop-promptly consumers (the exchange
  loop, the ingest stream) recycle every round.  Stale generations,
  foreign decrefs, double decrefs, and expired leases are TYPED
  refusals (:class:`ShmLeaseError` subclasses) that ride the wire's
  typed-error discipline.
* **Negotiation** — the client offers ``"shm": {boot_id, uid, nonce}``
  inside the wire-v2 hello (already under the HMAC session); the
  server grants only when the proof matches its own boot-id + uid
  (same host, same user) and echoes the nonce.  Silent fallback
  everywhere: a remote peer, a legacy server, or a broken ``/dev/shm``
  all land on in-band v2 with no caller-visible difference.

Trust model: the grant requires the shared HMAC authkey (the hello
rides the authenticated session) AND a matching uid, so a peer that
can read a segment could already read the process memory it came
from.  Receivers map ``PROT_READ`` — decoded views are read-only.

A peer dying mid-lease is swept by the arena owner: unacked leases
expire after ``THEANOMPI_TPU_SHM_LEASE_S`` and are unlinked; an OWNER
killed outright leaves ``tmshm_<pid>_*`` files that
:func:`sweep_orphans` reclaims by liveness-probing the embedded pid
(run at arena creation, by the conftest segment fence, and by the
bench kill leg).
"""

from __future__ import annotations

import atexit
import mmap
import os
import secrets
import struct
import threading
import time
import weakref
from typing import Any

from theanompi_tpu import monitor
from theanompi_tpu.analysis.lockgraph import make_lock

__all__ = [
    "Arena", "Lease", "ShmChannel", "ShmError", "ShmLeaseError",
    "StaleGeneration", "ForeignSegment", "DoubleDecref", "LeaseExpired",
    "arena", "available", "boot_id", "client_offer", "client_channel",
    "server_grant", "enabled", "min_bytes", "release_all",
    "segment_names", "sweep_orphans",
]

#: every segment this lane creates is named tmshm_<pid>_<uid>_<n> — the
#: pid prefix is what makes orphans of a killed owner identifiable
SEG_PREFIX = "tmshm"

#: in-segment header: magic(4) pad(4) generation(8); payload starts at
#: the first 64-byte boundary after it
HEADER_MAGIC = b"TMSH"
_HEADER = struct.Struct(">4sIQ")
PAYLOAD_OFFSET = 64
_ALIGN = 64

_SHM_DIR = "/dev/shm"


def enabled() -> bool:
    """The lane's master switch (default ON, like mux): a client only
    OFFERS and a server only GRANTS when this is set."""
    return os.environ.get("THEANOMPI_TPU_WIRE_SHM", "1") == "1"


def min_bytes() -> int:
    """Leaves smaller than this stay in-band (descriptor + mmap
    overhead would outweigh the saved copy)."""
    return int(os.environ.get("THEANOMPI_TPU_SHM_MIN_BYTES",
                              str(64 << 10)))


def lease_timeout_s() -> float:
    """How long an unacked lease may live before the owner sweeps it.
    Generous by default: a receiver legitimately retains decoded views
    across an exchange period (unlink-on-sweep cannot tear them — see
    module docstring — but a sweep before the receiver MAPS reads as a
    typed :class:`LeaseExpired`)."""
    return float(os.environ.get("THEANOMPI_TPU_SHM_LEASE_S", "120"))


def max_bytes() -> int:
    """Total bytes the arena may hold leased at once; an alloc past
    the cap degrades that frame to in-band (counted)."""
    return int(os.environ.get("THEANOMPI_TPU_SHM_MAX_BYTES",
                              str(2 << 30)))


def boot_id() -> str | None:
    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            return f.read().strip()
    except OSError:
        return None


_AVAILABLE: bool | None = None


def available() -> bool:
    """Platform probe, computed once: POSIX shared memory + a readable
    boot id.  False anywhere silently disables the lane."""
    global _AVAILABLE
    if _AVAILABLE is None:
        try:
            from multiprocessing import shared_memory  # noqa: F401

            _AVAILABLE = (os.path.isdir(_SHM_DIR)
                          and os.access(_SHM_DIR, os.W_OK)
                          and boot_id() is not None
                          and hasattr(os, "getuid"))
        except Exception:
            _AVAILABLE = False
    return _AVAILABLE


# ---------------------------------------------------------------------------
# Typed refusals
# ---------------------------------------------------------------------------


class ShmError(RuntimeError):
    """Base class for shm-lane failures."""


class ShmLeaseError(ShmError):
    """Base class for the lease refusal matrix.  Class names ride the
    wire's ``("err", "ClassName: ...")`` discipline — clients classify
    on the name, exactly like ``LeaseError`` / ``Overloaded``."""


class StaleGeneration(ShmLeaseError):
    """A read or decref named a generation the segment no longer
    carries — the lease was reissued or the descriptor is stale."""


class ForeignSegment(ShmLeaseError):
    """A decref or read named a segment this arena never leased."""


class DoubleDecref(ShmLeaseError):
    """A decref for a lease that was already released."""


class LeaseExpired(ShmLeaseError):
    """The segment is gone: the lease expired (owner swept it) or the
    owner exited before the receiver mapped."""


# ---------------------------------------------------------------------------
# Owner side: Lease + Arena
# ---------------------------------------------------------------------------


class Lease:
    """One leased segment = one outgoing frame's out-of-band payload.
    Owned by the encoding thread until handed back to the arena; the
    arena only touches it under its own lock."""

    __slots__ = ("name", "generation", "size", "deadline", "used",
                 "_shm", "_cursor")

    def __init__(self, shm_obj, name: str, generation: int, size: int,
                 deadline: float):
        self._shm = shm_obj
        self.name = name
        self.generation = generation
        self.size = size
        self.deadline = deadline
        self._cursor = PAYLOAD_OFFSET
        self.used = 0

    def put(self, data) -> int | None:
        """Copy one leaf's bytes into the segment at the next aligned
        offset; returns the offset, or None when the segment is full
        (the caller falls back to an in-band buffer for that leaf)."""
        mv = data if isinstance(data, memoryview) else memoryview(data)
        n = mv.nbytes
        off = (self._cursor + _ALIGN - 1) // _ALIGN * _ALIGN
        if off + n > self.size:
            return None
        if n:
            self._shm.buf[off:off + n] = mv
        self._cursor = off + n
        self.used += 1
        return off

    def _dispose(self) -> None:
        try:
            self._shm.close()
        except (OSError, BufferError):
            pass
        try:
            self._shm.unlink()
        except (OSError, FileNotFoundError):
            pass


def _aligned(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


def _dispose_segment(seg) -> None:
    """Close + unlink one ``SharedMemory``, swallowing the races
    (already unlinked, exported buffers) that teardown paths hit."""
    try:
        seg.close()
    except (OSError, BufferError):
        pass
    try:
        seg.unlink()
    except (OSError, FileNotFoundError):
        pass


class Arena:
    """Process-wide segment allocator + lease table (module
    docstring).  One per process (:func:`arena`); every connection's
    :class:`ShmChannel` allocates from it."""

    #: freelist ceiling: the deepest in-repo pipeline (ingest at
    #: depth 4, double-buffered) parks ~6 segments per direction;
    #: past this, the oldest free segment is unlinked instead
    _FREE_SLOTS = 8

    def __init__(self):
        self._lock = make_lock("shm.Arena._lock")
        self._leased: dict[str, Lease] = {}  # guarded_by: self._lock
        #: acked segments parked for reuse: [(shm_obj, name, size)]
        self._free: list = []                # guarded_by: self._lock
        #: recently released names, kept so a second decref can be
        #: classified as DoubleDecref instead of ForeignSegment
        self._freed: dict[str, int] = {}     # guarded_by: self._lock
        self._gen = 0                        # guarded_by: self._lock
        self._n = 0                          # guarded_by: self._lock
        #: resident bytes = leased + parked-free segments
        self._bytes = 0                      # guarded_by: self._lock
        self._tag = secrets.token_hex(4)
        atexit.register(self.close)

    # -- alloc / decref -------------------------------------------------

    def alloc(self, payload_bytes: int) -> Lease | None:
        """Lease a segment for one frame's out-of-band leaves —
        recycling an acked free segment when one is big enough, else
        creating fresh.  Returns None — NEVER raises — on any failure
        (cap, ENOSPC, a broken /dev/shm): the frame silently ships
        in-band."""
        from multiprocessing import shared_memory

        self.sweep()
        size = PAYLOAD_OFFSET + _aligned(int(payload_bytes))
        overflow: list = []
        with self._lock:
            self._gen += 1
            gen = self._gen
            # smallest adequate parked segment wins: a frame's
            # payload size is near-constant per plane, so steady
            # state is an exact-size hit with warm pages
            best = None
            for i, (_, _, sz) in enumerate(self._free):
                if sz >= size and (best is None
                                   or sz < self._free[best][2]):
                    best = i
            if best is not None:
                seg, name, seg_size = self._free.pop(best)
            else:
                seg = None
                # creating fresh: evict parked segments before
                # refusing on the cap — free bytes are reclaimable
                while (self._bytes + size > max_bytes()
                       and self._free):
                    overflow.append(self._free.pop(0))
                    self._bytes -= overflow[-1][2]
                if self._bytes + size > max_bytes():
                    monitor.inc("shm/fallback_total", reason="cap")
                    hit_cap = True
                else:
                    hit_cap = False
                    self._n += 1
                    idx = self._n
        for o_seg, o_name, _ in overflow:
            _dispose_segment(o_seg)
        if seg is None and hit_cap:
            return None
        if seg is None:
            name = f"{SEG_PREFIX}_{os.getpid()}_{self._tag}_{idx}"
            try:
                seg = shared_memory.SharedMemory(create=True, name=name,
                                                 size=size)
            except Exception:
                monitor.inc("shm/fallback_total", reason="alloc")
                return None
            seg_size = size
            fresh = True
        else:
            fresh = False
        try:
            seg.buf[:_HEADER.size] = _HEADER.pack(HEADER_MAGIC, 0, gen)
        except (OSError, ValueError, TypeError):
            _dispose_segment(seg)
            monitor.inc("shm/fallback_total", reason="alloc")
            if not fresh:
                with self._lock:
                    self._bytes -= seg_size
            return None
        lease = Lease(seg, name, gen, seg_size,
                      time.monotonic() + lease_timeout_s())
        with self._lock:
            self._leased[name] = lease
            if fresh:
                self._bytes += seg_size
        return lease

    def decref(self, name: str, generation: int) -> None:
        """Release one lease (the receiver's piggybacked ack) back to
        the freelist — the ack proves every receiver view died, so the
        segment is safe to reissue.  The refusal matrix: unknown name
        -> :class:`ForeignSegment`, already-released ->
        :class:`DoubleDecref`, wrong generation ->
        :class:`StaleGeneration`."""
        overflow: list = []
        with self._lock:
            lease = self._leased.get(name)
            if lease is None:
                if name in self._freed:
                    raise DoubleDecref(
                        f"segment {name} was already released")
                raise ForeignSegment(
                    f"segment {name} was never leased by this arena")
            if int(generation) != lease.generation:
                raise StaleGeneration(
                    f"decref for {name} generation {generation}, lease "
                    f"holds generation {lease.generation}")
            self._drop_locked(lease, recycle=True)
            while len(self._free) > self._FREE_SLOTS:
                overflow.append(self._free.pop(0))
                self._bytes -= overflow[-1][2]
        for o_seg, o_name, _ in overflow:
            _dispose_segment(o_seg)

    def forget(self, name: str, generation: int) -> None:
        """Release one lease WITHOUT recycling (channel teardown: the
        peer may still hold live views, so the segment must never be
        reissued — unlink leaves those views valid).  Never refused."""
        with self._lock:
            lease = self._leased.get(name)
            if lease is None or int(generation) != lease.generation:
                return
            self._drop_locked(lease, recycle=False)
        lease._dispose()

    def cancel(self, lease: Lease) -> None:
        """Give back an allocated-but-unused lease (no leaf fit, or
        encoding failed after alloc) — no receiver ever saw it, so it
        recycles.  Not a decref, never refused."""
        overflow: list = []
        with self._lock:
            if self._leased.get(lease.name) is not lease:
                return
            self._drop_locked(lease, recycle=True)
            while len(self._free) > self._FREE_SLOTS:
                overflow.append(self._free.pop(0))
                self._bytes -= overflow[-1][2]
        for o_seg, o_name, _ in overflow:
            _dispose_segment(o_seg)

    def _drop_locked(self, lease, recycle):  # requires_lock: self._lock
        del self._leased[lease.name]
        if recycle:
            self._free.append((lease._shm, lease.name, lease.size))
        else:
            self._bytes -= lease.size
        self._freed[lease.name] = lease.generation
        while len(self._freed) > 1024:
            self._freed.pop(next(iter(self._freed)))

    # -- sweeps ---------------------------------------------------------

    def sweep(self) -> int:
        """Unlink every lease past its deadline (a peer that died — or
        stalled — mid-lease must not leak segments).  Returns the
        number swept."""
        now = time.monotonic()
        expired: list[Lease] = []
        with self._lock:
            for lease in list(self._leased.values()):
                if now >= lease.deadline:
                    # NOT recycled: the receiver never acked, so it
                    # may still hold live views — unlink keeps them
                    # valid, reuse would rewrite under them
                    self._drop_locked(lease, recycle=False)
                    expired.append(lease)
        for lease in expired:
            lease._dispose()
            monitor.inc("shm/lease_sweeps_total", kind="expired")
        return len(expired)

    def release_all(self) -> int:
        """Force-release every outstanding lease AND parked free
        segment (test teardown / process exit).  Receivers that
        already mapped keep valid views — the unlink only removes the
        name.  Returns the number of leases released (parked free
        segments are not leases)."""
        with self._lock:
            leases = list(self._leased.values())
            for lease in leases:
                self._drop_locked(lease, recycle=False)
            free, self._free = self._free, []
            for _, _, sz in free:
                self._bytes -= sz
        for lease in leases:
            lease._dispose()
            monitor.inc("shm/lease_sweeps_total", kind="close")
        for seg, _, _ in free:
            _dispose_segment(seg)
        return len(leases)

    def outstanding(self) -> int:
        with self._lock:
            return len(self._leased)

    def close(self) -> None:
        self.release_all()


_ARENA: Arena | None = None
_ARENA_LOCK = make_lock("shm._ARENA_LOCK")


def arena() -> Arena:
    """The process-global arena (created on first shm send; creation
    also sweeps orphans left by previously-killed owners)."""
    global _ARENA
    with _ARENA_LOCK:
        if _ARENA is None:
            _ARENA = Arena()
            try:
                sweep_orphans()
            except OSError:
                pass
    return _ARENA


def release_all() -> int:
    """Force-release this process's outstanding leases (the conftest
    segment fence calls this between tests)."""
    with _ARENA_LOCK:
        a = _ARENA
    return a.release_all() if a is not None else 0


def segment_names(prefix: str = SEG_PREFIX) -> list[str]:
    """Names of every live shm-lane segment on this host."""
    try:
        return sorted(n for n in os.listdir(_SHM_DIR)
                      if n.startswith(prefix + "_"))
    except OSError:
        return []


def sweep_orphans() -> int:
    """Unlink segments whose embedded creator pid is dead — the
    kill-a-peer leg's cleanup path.  Live owners' segments are left
    alone (their own sweeps/atexit handle them)."""
    swept = 0
    for name in segment_names():
        try:
            pid = int(name.split("_")[1])
        except (IndexError, ValueError):
            continue
        try:
            os.kill(pid, 0)
            continue  # owner alive — not an orphan
        except ProcessLookupError:
            pass
        except PermissionError:
            continue  # alive, other user
        try:
            os.unlink(os.path.join(_SHM_DIR, name))
            swept += 1
            monitor.inc("shm/lease_sweeps_total", kind="orphan")
        except OSError:
            pass
    return swept


# ---------------------------------------------------------------------------
# Receiver side: read-only mapping
# ---------------------------------------------------------------------------


def map_payload(name: str, generation: int) -> mmap.mmap:
    """Map one segment read-only and validate its header against the
    descriptor's generation.  Raw ``os.open`` + ``mmap`` — deliberately
    NOT ``SharedMemory`` attach, whose resource tracker would unlink
    the owner's segment when THIS process exits (3.10 has no
    ``track=False``)."""
    path = os.path.join(_SHM_DIR, name)
    if os.sep in name or not name.startswith(SEG_PREFIX + "_"):
        raise ForeignSegment(f"refusing to map non-lane segment {name!r}")
    try:
        fd = os.open(path, os.O_RDONLY)
    except FileNotFoundError:
        raise LeaseExpired(
            f"segment {name} is gone — the lease expired or its owner "
            "exited before this read") from None
    try:
        m = mmap.mmap(fd, 0, prot=mmap.PROT_READ)
    except (OSError, ValueError) as e:
        raise ShmError(f"cannot map segment {name}: {e}") from e
    finally:
        os.close(fd)
    if len(m) < PAYLOAD_OFFSET:
        m.close()
        raise ForeignSegment(f"segment {name} is too small to carry "
                             "a lane header")
    magic, _, gen = _HEADER.unpack_from(m, 0)
    if magic != HEADER_MAGIC:
        m.close()
        raise ForeignSegment(f"segment {name} carries no lane header")
    if gen != int(generation):
        m.close()
        raise StaleGeneration(
            f"segment {name} holds generation {gen}, descriptor says "
            f"{generation} — stale read refused")
    return m


# ---------------------------------------------------------------------------
# Per-connection lane state
# ---------------------------------------------------------------------------


class ShmChannel:
    """One connection's shm lane (both directions).  Hung off the
    negotiated ``wire.WireOptions``; shared by every stream of a mux
    connection, so all state is locked."""

    #: receiver-side map cache ceiling: one lease per frame means one
    #: live entry per concurrently-decoding stream — 8 is headroom
    _MAP_CACHE = 8

    def __init__(self, role: str):
        self.role = role
        self._lock = make_lock("shm.ShmChannel._lock")
        self._send_ok = True              # guarded_by: self._lock
        self._acks: list = []             # guarded_by: self._lock
        self._mine: set = set()           # guarded_by: self._lock
        self._maps: dict = {}             # guarded_by: self._lock
        self._closed = False              # guarded_by: self._lock
        #: per-decoding-thread stack of keys mapped by the frame in
        #: flight (mux streams decode concurrently; each thread's
        #: frames are its own)
        self._frames = threading.local()
        #: keys belonging to ANY thread's in-flight frame — the cache
        #: overflow evictor must never drop these (a re-map would
        #: register a second finalizer = a second ack)
        self._active: set = set()         # guarded_by: self._lock

    # -- sender side ----------------------------------------------------

    @property
    def send_ok(self) -> bool:
        with self._lock:
            return self._send_ok

    def alloc(self, payload_bytes: int) -> Lease | None:
        with self._lock:
            if not self._send_ok or self._closed:
                return None
        lease = arena().alloc(payload_bytes)
        if lease is not None:
            with self._lock:
                self._mine.add((lease.name, lease.generation))
        return lease

    def cancel(self, lease: Lease) -> None:
        with self._lock:
            self._mine.discard((lease.name, lease.generation))
        arena().cancel(lease)

    def disable_send(self, reason: str) -> None:
        """Silent per-connection degrade: every later frame ships
        in-band.  Counted once per flip."""
        with self._lock:
            if not self._send_ok:
                return
            self._send_ok = False
        monitor.inc("shm/fallback_total", reason=reason)

    # -- receiver side --------------------------------------------------

    def begin_frame(self) -> None:
        """Open a frame scope on this thread: keys mapped until the
        matching :meth:`end_frame` are released from the cache when
        the frame's decode completes (see :meth:`map_for_read`)."""
        stack = getattr(self._frames, "stack", None)
        if stack is None:
            stack = self._frames.stack = []
        stack.append([])

    def end_frame(self) -> None:
        """Close the thread's innermost frame scope and drop the cache
        entries it created.  A (name, generation) pair is referenced
        by exactly one frame, so no later decode can want them — from
        here the mapping lives exactly as long as the decoded views,
        and its death fires the decref ack."""
        stack = getattr(self._frames, "stack", None)
        if not stack:
            return
        keys = stack.pop()
        evicted: list = []
        with self._lock:
            for k in keys:
                self._active.discard(k)
                m = self._maps.pop(k, None)
                if m is not None:
                    evicted.append(m)
        # strong refs die OUTSIDE the lock: dropping a mapping can
        # fire its finalize -> _queue_ack -> this (non-reentrant) lock
        evicted.clear()

    def map_for_read(self, name: str, generation: int) -> mmap.mmap:
        """Map (or reuse this frame's mapping of) one segment.  The
        decref ack is queued by a ``weakref.finalize`` when the mmap
        DIES — i.e. once :meth:`end_frame` dropped it from the cache
        AND the last decoded view over it is garbage — which is
        exactly the proof the owner needs to recycle the segment.  A
        key must NEVER be mapped twice (two finalizers would ack
        twice, and the first ack would let the owner rewrite under the
        second mapping's views), which frame-scoping guarantees: each
        (name, generation) belongs to exactly one frame, and within a
        frame the cache dedupes."""
        key = (name, int(generation))
        evicted: list = []
        frame = getattr(self._frames, "stack", None)
        with self._lock:
            m = self._maps.get(key)
            if m is not None:
                return m
        fresh = map_payload(name, int(generation))
        try:
            with self._lock:
                m = self._maps.get(key)
                if m is not None:  # lost a benign race: keep the first
                    return m
                self._maps[key] = fresh
                if frame:
                    self._active.add(key)
                if len(self._maps) > self._MAP_CACHE:
                    for k in list(self._maps):
                        if len(self._maps) <= self._MAP_CACHE:
                            break
                        if k != key and k not in self._active:
                            evicted.append(self._maps.pop(k))
                weakref.finalize(fresh, self._queue_ack, name,
                                 int(generation))
                m = fresh
            if frame:
                frame[-1].append(key)
            return m
        finally:
            # strong refs die OUTSIDE the lock (finalize takes it too)
            del fresh
            evicted.clear()

    def _queue_ack(self, name: str, generation: int) -> None:
        """Finalizer target: the mapping (and so every view) of
        ``(name, generation)`` is dead — tell the owner."""
        with self._lock:
            if self._closed:
                return
            self._acks.append([name, int(generation)])

    def drain_acks(self) -> list:
        with self._lock:
            acks, self._acks = self._acks, []
        return acks

    def apply_acks(self, acks) -> None:
        """Owner side of the piggybacked decrefs.  Refusals raise the
        typed :class:`ShmLeaseError` subclasses — the wire layer turns
        them into a typed err reply; the connection survives."""
        if not isinstance(acks, list):
            raise ShmError(f"malformed shm ack list: {acks!r}")
        for item in acks:
            try:
                name, gen = item
                name, gen = str(name), int(gen)
            except (TypeError, ValueError) as e:
                raise ShmError(f"malformed shm ack {item!r}") from e
            arena().decref(name, gen)
            with self._lock:
                self._mine.discard((name, gen))

    # -- teardown -------------------------------------------------------

    def close(self) -> None:
        """Connection teardown: release every lease this channel still
        holds (acks that never came back must not wait out the
        timeout).  Released via :meth:`Arena.forget` — NOT recycled —
        because the peer may still hold live views; the unlink keeps
        those valid.  Receiver-side mappings are dropped outside the
        lock (their finalizers fire, but ``_closed`` suppresses the
        now-pointless acks)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._send_ok = False
            mine, self._mine = self._mine, set()
            maps, self._maps = self._maps, {}
            self._acks = []
            self._active = set()
        maps.clear()
        with _ARENA_LOCK:
            a = _ARENA
        if a is None:
            return
        for name, gen in mine:
            a.forget(name, gen)


# ---------------------------------------------------------------------------
# Negotiation (rides the wire-v2 hello, under the HMAC session)
# ---------------------------------------------------------------------------


def client_offer() -> dict | None:
    """The client's same-host proof for the hello: boot-id + uid + a
    fresh nonce the server must echo.  None (no offer) when the lane
    is disabled or the platform cannot carry it."""
    if not enabled() or not available():
        return None
    return {"boot_id": boot_id(), "uid": os.getuid(),
            "nonce": secrets.token_hex(8)}


def client_channel(offer: dict | None, reply: Any) -> ShmChannel | None:
    """Build the client-side channel from the server's hello reply —
    None (silent in-band) unless the grant is present AND echoes the
    offer's nonce."""
    if offer is None or not isinstance(reply, dict):
        return None
    grant = reply.get("shm")
    if not (isinstance(grant, dict) and grant.get("granted")):
        return None
    if grant.get("nonce") != offer.get("nonce"):
        monitor.inc("shm/fallback_total", reason="nonce")
        return None
    monitor.inc("shm/grants_total", role="client")
    return ShmChannel("client")


def server_grant(request: Any) -> tuple[ShmChannel | None, dict | None]:
    """Server side: grant only when the peer proves it shares this
    host (boot-id) and user (uid).  Returns (channel, reply-grant) or
    (None, None) — the reply simply omits ``shm`` on refusal, which an
    old client never looks for anyway."""
    if not enabled() or not available() or not isinstance(request, dict):
        return None, None
    if (request.get("boot_id") != boot_id()
            or request.get("uid") != os.getuid()):
        monitor.inc("shm/fallback_total", reason="remote")
        return None, None
    monitor.inc("shm/grants_total", role="server")
    return ShmChannel("server"), {"granted": True,
                                  "nonce": request.get("nonce")}
