"""Intra-host aggregation for the async rules — N local workers cost
ONE wire exchange per shard per period (ISSUE 14 tentpole;
docs/DESIGN.md "Hierarchical exchange").

The async center's wire cost used to scale with worker count: every
EASGD/ASGD worker shipped its full (or per-shard) tree to the service
each period, so an 8-worker host paid 8x the NIC bytes for math that
is a single sum.  The reference design (arXiv:1605.08325) concentrates
communication in one exchange process per host; the weight-update
sharding of arXiv:2004.13336 applies the same idea to a partitioned
center.  This module rebuilds both as an in-process aggregation plane
in front of the (possibly sharded) parameter service:

* :class:`LocalAggregator` — one per host.  Co-located workers submit
  their exchange payloads; when every registered worker's payload for
  the current period is in, the LAST arriver (on its own exchange
  thread under ``overlap=True``, so aggregation rides the existing
  comm/compute overlap) combines them and performs ONE wire exchange:

  - **ASGD** delta-sums exactly: the aggregate payload is the SUM of
    the workers' gradients, applied as one optimizer step
    (``push_pull_n``) — algebraically equal to n same-version pushes
    for any gradient-linear update; the fresh center fans back to all
    n workers over shared memory.
  - **EASGD** elastic displacements compose in closed form when
    applied against ONE center version: the aggregate payload is the
    MEAN of the workers' params and the center applies
    ``center += n*alpha*(mean - center)`` (``exchange_n``), returning
    the PRE-update center so each worker's own elastic pull
    ``w_i - alpha*(w_i - center)`` is computed host-side against that
    same version.  Exact in real arithmetic; f32 reordering bounds the
    deviation (docs/DESIGN.md documents the tolerance and the
    ``n*alpha <= 1`` stability note).

  The wire op carries the worker-count multiplier, so the center math
  and the shard plane's version-fence accounting stay identical to n
  independent exchanges at the same version — one tagged
  ``shard_exchange`` per shard per period.

* :class:`AggregatedExchange` — the per-worker port.  Duck-types the
  store clients (``exchange``/``push_pull``/``set_lr``/...), so the
  rules' worker loops and their ``_ExchangePipe`` overlap plane are
  unchanged.  Fallback matrix (never wedge): an aggregator that is
  down — killed, or its wire op failed — fails every waiter with the
  typed :class:`AggregatorDown`, and the port falls back to a DIRECT
  per-worker exchange for that period (lazily connecting its own
  client), rejoining the aggregator as soon as it is alive again.  A
  worker that leaves (finished, crashed, supervised restart) drops out
  of the period quorum via ``leave``, so the survivors' periods keep
  completing; a wedged period times out
  (``THEANOMPI_TPU_AGG_TIMEOUT_S``) into the same direct fallback.

Trust model: the aggregator runs in the training process and holds no
key material beyond what any worker already holds (the same
``THEANOMPI_TPU_SERVICE_KEY`` session) — it narrows the service's
attack surface if anything, since one authenticated connection per
host replaces N.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable

import jax
import numpy as np

from theanompi_tpu import monitor
from theanompi_tpu.analysis.lockgraph import make_condition, make_lock

PyTree = Any


def _agg_timeout_s() -> float:
    """How long a submitted worker waits for its period's quorum
    before withdrawing and falling back to a direct exchange — the
    backstop against a peer that died without leaving."""
    return float(os.environ.get("THEANOMPI_TPU_AGG_TIMEOUT_S", "120"))


class AggregatorDown(RuntimeError):
    """The aggregation plane cannot serve this period — killed, not
    yet restarted, or the period wedged past the timeout.  Typed so
    the port's fallback (and the fault-matrix tests) classify on the
    class, not prose."""


def _tree_sum(payloads: list) -> PyTree:
    out = payloads[0]
    for p in payloads[1:]:
        out = jax.tree.map(np.add, out, p)
    return out


class LocalAggregator:
    """One per host: combines the registered local workers' exchange
    payloads into ONE wire exchange per period (module docstring).

    ``client`` is the host's single service handle — an in-process
    store (``EASGDServer``/``ASGDServer``), a ``RemoteEASGD``/
    ``RemoteASGD``, or the sharded routers — anything exposing
    ``exchange_n`` (easgd) / ``push_pull_n`` (asgd).  The aggregator
    never owns the handle's lifecycle; the rule session does.

    Threading: workers call :meth:`exchange` concurrently.  The last
    arriver of a period becomes the FLYER — it performs the wire op
    outside the lock while the others wait on the condition — so no
    dedicated aggregator thread exists to supervise; "restart" is the
    :meth:`kill`/:meth:`restart` transition, with the ports' direct
    fallback covering the down window."""

    def __init__(self, kind: str, client, alpha: float | None = None,
                 wait_timeout_s: float | None = None):
        if kind not in ("easgd", "asgd"):
            raise ValueError(
                f"hierarchical aggregation applies to easgd/asgd only, "
                f"got {kind!r} — GOSGD pushes whole trees to random "
                "peers (nothing to sum) and BSP exchanges in-step")
        if kind == "easgd" and alpha is None:
            raise ValueError("easgd aggregation needs alpha (the "
                             "per-worker elastic pull is computed "
                             "host-side against the pre-update center)")
        self.kind = kind
        self._client = client
        self._alpha = None if alpha is None else float(alpha)
        self._timeout = (wait_timeout_s if wait_timeout_s is not None
                         else _agg_timeout_s())
        self._lock = make_lock("LocalAggregator._lock")
        self._cv = make_condition(self._lock, "LocalAggregator._cv")
        self._members: set[int] = set()     # guarded_by: self._lock
        self._pending: dict[int, PyTree] = {}  # guarded_by: self._lock
        self._gen = 0                       # guarded_by: self._lock
        self._flying = False                # guarded_by: self._lock
        #: gen -> {rank: (result, error)}   # guarded_by: self._lock
        self._results: dict[int, dict] = {}
        self._down: str | None = None       # guarded_by: self._lock
        #: flights below this gen were killed mid-air: their waiters
        #: already failed over, so they must never publish (a restart
        #: clearing _down would otherwise let a stale flight leak one
        #: full result tree per bailed waiter)  # guarded_by: self._lock
        self._kill_watermark = 0

    # -- membership ----------------------------------------------------

    def register(self, rank: int) -> None:
        """Add ``rank`` to the period quorum (idempotent).  The rule
        registers every local worker BEFORE the threads start, so the
        first period already aggregates at full fan-in."""
        with self._cv:
            self._members.add(int(rank))
            self._cv.notify_all()

    def leave(self, rank: int) -> None:
        """Drop ``rank`` from the quorum (finished / crashed /
        restarting worker) and wake waiters — the survivors' period
        may now be complete."""
        with self._cv:
            self._members.discard(int(rank))
            self._pending.pop(int(rank), None)
            self._cv.notify_all()

    def members(self) -> set[int]:
        with self._lock:
            return set(self._members)

    # -- liveness (the supervised-restart surface) ---------------------

    def alive(self) -> bool:
        with self._lock:
            return self._down is None

    def kill(self, reason: str = "aggregator killed") -> None:
        """Take the plane down: every waiter (and every later submit)
        gets a typed :class:`AggregatorDown`, which the ports turn
        into a direct exchange within the same period — the
        fault-matrix's no-idle-gap guarantee."""
        with self._cv:
            self._down = str(reason)
            self._pending.clear()
            self._results.clear()  # every waiter raises; don't leak
            self._kill_watermark = self._gen
            self._cv.notify_all()

    def restart(self) -> None:
        """Bring the plane back; ports rejoin on their next period
        (they probe :meth:`alive` before every submit)."""
        with self._cv:
            self._down = None
            self._cv.notify_all()

    # -- the period exchange -------------------------------------------

    def exchange(self, rank: int, payload: PyTree) -> PyTree:
        """Submit ``rank``'s host-side payload for the current period;
        blocks until the period's aggregate wire exchange completes
        and returns this worker's share (EASGD: its new params; ASGD:
        the fresh center).  Raises :class:`AggregatorDown` when the
        plane is down, the wire op failed, or the period wedged past
        the timeout — the caller falls back to a direct exchange."""
        rank = int(rank)
        deadline = time.monotonic() + self._timeout
        with self._cv:
            if self._down is not None:
                raise AggregatorDown(self._down)
            if rank not in self._members:
                raise AggregatorDown(
                    f"rank {rank} is not registered with the "
                    "aggregator")
            if rank in self._pending:
                raise RuntimeError(
                    f"rank {rank} already has a payload in the current "
                    "period — one exchange per worker per period")
            my_gen = self._gen
            self._pending[rank] = payload
            self._cv.notify_all()
            flyer = False
            while True:
                res = self._results.get(my_gen)
                if res is not None and rank in res:
                    out, err = res.pop(rank)
                    if not res:
                        self._results.pop(my_gen, None)
                    if err is not None:
                        raise err
                    break
                if self._down is not None:
                    self._pending.pop(rank, None)
                    raise AggregatorDown(self._down)
                # a kill that a fast restart() made invisible to this
                # waiter (it slept through the down window) must still
                # fail it over — otherwise it waits forever on a
                # result nobody will publish:
                if my_gen < self._kill_watermark:
                    # our generation's flight was in the air when the
                    # kill landed: the flyer discards its result (see
                    # the watermark note below).  At-least-once — the
                    # aggregate may still have applied, exactly a
                    # re-sent exchange after a lost reply
                    raise AggregatorDown(
                        "aggregation plane was killed while this "
                        "period's exchange was in flight")
                if self._gen == my_gen and rank not in self._pending:
                    # our payload was discarded by a kill before any
                    # flyer took it (a flyer bumps _gen atomically
                    # with taking the work): never applied, so the
                    # direct fallback cannot double-apply
                    raise AggregatorDown(
                        "payload discarded by an aggregation-plane "
                        "kill")
                if (self._gen == my_gen and not self._flying
                        and self._pending
                        and set(self._pending) >= self._members):
                    # last arriver: this thread flies the period
                    work = dict(self._pending)
                    self._pending.clear()
                    self._flying = True
                    self._gen += 1
                    flyer = True
                    break
                if not self._cv.wait(0.05) \
                        and time.monotonic() > deadline:
                    if rank in self._pending:
                        # a peer died without leaving: withdraw and
                        # fall back rather than wedge the worker —
                        # the payload was NOT applied, so the direct
                        # fallback cannot double-apply it
                        have = sorted(self._pending)  # incl. this rank
                        self._pending.pop(rank)
                        self._cv.notify_all()
                        raise AggregatorDown(
                            f"period quorum not met within "
                            f"{self._timeout:.0f}s (have {have}, "
                            f"need {sorted(self._members)})")
                    # the payload is already inside an in-flight wire
                    # op, whose own retry deadline bounds it: falling
                    # back now would apply this period twice — wait
                    # for the flight's result/error instead
                    deadline = time.monotonic() + self._timeout
        if flyer:
            # flyer path — wire op OUTSIDE the lock
            err = None
            center = None
            try:
                center = self._fly(work)
            except BaseException as e:
                err = e
            with self._cv:
                self._flying = False
                gen_res = {r: (center,
                               None if err is None else
                               AggregatorDown(f"aggregate wire "
                                              f"exchange failed: "
                                              f"{err}"))
                           for r in work}
                out, my_err = gen_res.pop(rank)
                if gen_res and self._down is None \
                        and my_gen >= self._kill_watermark:
                    # a kill mid-flight already failed this gen's
                    # waiters into their direct fallback
                    # (at-least-once, exactly like a re-sent exchange
                    # after a lost reply) — publishing would only leak
                    # entries nobody collects; the watermark covers a
                    # kill+restart both landing while this flight was
                    # in the air
                    self._results[my_gen] = gen_res
                self._cv.notify_all()
            if my_err is not None:
                raise my_err
        # every worker — flyer and waiters alike — computes its own
        # share OUTSIDE the lock on its own thread: for EASGD that is
        # ~n full-tree elementwise maps running in parallel (numpy
        # releases the GIL) instead of serialized on the flyer while
        # n-1 threads sit parked
        return self._share(payload, out)

    def _share(self, payload: PyTree, center: PyTree) -> PyTree:
        """One worker's period result from the wire reply: EASGD pulls
        its own params elastically against the PRE-update center;
        ASGD's reply is the fresh center, shared as-is."""
        if self.kind == "easgd":
            a = np.float32(self._alpha)
            return jax.tree.map(lambda w, c: w - a * (w - c),
                                payload, center)
        return center

    def _fly(self, work: dict[int, PyTree]) -> PyTree:
        """Combine one period's payloads and do the single wire
        exchange; returns the center reply every worker's
        :meth:`_share` is computed against."""
        n = len(work)
        payloads = [work[r] for r in sorted(work)]
        with monitor.span("local_aggregate", rule=self.kind):
            if self.kind == "easgd":
                total = _tree_sum(payloads)
                mean = (payloads[0] if n == 1 else
                        jax.tree.map(lambda s: s / np.float32(n), total))
                reply = self._client.exchange_n(mean, n)
            else:  # asgd
                gsum = payloads[0] if n == 1 else _tree_sum(payloads)
                reply = self._client.push_pull_n(gsum, n)
        if monitor.enabled():
            monitor.set_gauge("aggregate/fan_in", float(n),
                              rule=self.kind)
            monitor.inc("aggregate/exchanges_total", 1.0,
                        rule=self.kind)
            # bytes a direct fan-out would have put on the NIC and did
            # not: (n-1) extra requests + (n-1) extra replies
            saved = (n - 1) * (monitor.tree_bytes(payloads[0])
                               + monitor.tree_bytes(reply))
            if saved:
                monitor.inc("aggregate/bytes_saved_total",
                            float(saved), rule=self.kind)
        return reply


class AggregatedExchange:
    """Per-worker port onto the host's :class:`LocalAggregator` —
    duck-types the store clients the async rules already program
    against, with the direct-exchange fallback (module docstring).

    ``direct_connect`` is the rule's existing per-worker client
    factory; it is only invoked on the first fallback, so the happy
    path opens zero extra connections."""

    def __init__(self, agg: LocalAggregator, rank: int,
                 direct_connect: Callable[[], Any]):
        self._agg = agg
        self._rank = int(rank)
        self._connect = direct_connect
        self._direct = None
        agg.register(rank)

    # -- fallback plumbing --------------------------------------------

    def _direct_client(self):
        if self._direct is None:
            self._direct = self._connect()
        return self._direct

    def _via(self, agg_call, direct_call):
        if self._agg.alive():
            try:
                return agg_call()
            except AggregatorDown:
                pass
        # BOTH fallback routes count: a worker that raced the kill
        # inside exchange() AND one that found the plane already down
        # — the monitor must see every direct period of a down window
        monitor.inc("aggregate/fallbacks_total", rule=self._agg.kind)
        return direct_call()

    @staticmethod
    def _host(tree: PyTree) -> PyTree:
        return jax.tree.map(np.asarray, jax.device_get(tree))

    # -- store-client surface -----------------------------------------

    def exchange(self, worker_params: PyTree) -> PyTree:
        host = self._host(worker_params)
        return self._via(
            lambda: self._agg.exchange(self._rank, host),
            lambda: self._direct_client().exchange(host))

    def push_pull(self, grads: PyTree) -> PyTree:
        host = self._host(grads)
        return self._via(
            lambda: self._agg.exchange(self._rank, host),
            lambda: self._direct_client().push_pull(host))

    # control ops ride the aggregator's (thread-safe) service handle —
    # they are rare and tiny, so aggregating them would buy nothing
    def set_lr(self, lr: float) -> None:
        self._agg._client.set_lr(lr)

    def get_center(self) -> PyTree:
        return self._agg._client.get_center()

    def get_opt_state(self) -> PyTree:
        return self._agg._client.get_opt_state()

    @property
    def supports_opt_state(self) -> bool:
        return getattr(self._agg._client, "supports_opt_state", True)

    def close(self) -> None:
        """Leave the period quorum and drop the fallback client (if
        one was ever opened).  Never touches the aggregator's shared
        service handle — the rule session owns that."""
        self._agg.leave(self._rank)
        direct, self._direct = self._direct, None
        if direct is not None and hasattr(direct, "close"):
            direct.close()
