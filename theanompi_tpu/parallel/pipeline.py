"""Pipeline parallelism over the mesh's ``pipe`` axis (GPipe-style).

Beyond reference parity (the reference is data-parallel only,
SURVEY.md §2.11).  The TPU-native shape of pipelining is NOT the
reference's process-topology kind: all stages run ONE SPMD program;
each ``pipe`` shard holds one stage's layer stack (layers arrive
stacked on a leading axis, sharded ``P('pipe')``), microbatches flow
stage-to-stage via ``lax.ppermute`` inside a ``lax.scan`` over
schedule ticks, and the BACKWARD schedule is not hand-written at all —
jax differentiates through the scan+ppermute, transposing the
permutation automatically.

Schedule: classic GPipe fill-drain.  With S stages and M microbatches
the scan runs S-1+M ticks; stage 0 injects microbatch t at tick t,
stage s computes microbatch t-s at tick t, the last stage emits
microbatch t-(S-1) at tick t.  Bubble fraction (S-1)/(S-1+M) — choose
M >= 4*S in real runs.  Activation memory is bounded with
``jax.checkpoint`` around the per-tick stage body.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

from theanompi_tpu.parallel.mesh import AXIS_PIPE

PyTree = Any


def pipeline_apply(
    stage_fn: Callable[[PyTree, jax.Array], jax.Array],
    stage_params: PyTree,
    microbatches: jax.Array,
    axis_name: str = AXIS_PIPE,
    remat: bool = True,
):
    """Run ``microbatches`` (M, mb, ...) through the S-stage pipeline.

    Called INSIDE shard_map; ``stage_params`` is this shard's stage
    (the caller shards the stacked layer axis over ``axis_name``).
    ``stage_fn(stage_params, x) -> y`` applies one stage to one
    microbatch; activations must keep one shape across stages.

    Returns (M, mb, ...) outputs that are REAL on the last stage and
    ZERO elsewhere.  The loss must be masked to the last stage too
    (``last_stage_mask``) — do NOT broadcast the outputs across
    ``pipe`` before the loss: a replicated loss seeds the backward on
    every shard and collective transposes then scale all gradients by
    S.  With the masked convention each stage's block gradients come
    out exactly 1x (the cotangent travels the reversed ppermute
    chain), while gradients of replicated params touched on only one
    stage (embeddings on stage 0, the head on the last) are zero
    elsewhere — the training step psums those over ``pipe``
    (``make_pp_train_step``'s ``pipe_psum_mask``), as it does the
    masked metrics.
    """
    s = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    m = microbatches.shape[0]
    n_ticks = s - 1 + m
    body = jax.checkpoint(stage_fn) if remat else stage_fn

    perm = [(i, (i + 1) % s) for i in range(s)]

    def tick(carry, t):
        state = carry
        # stage 0 injects microbatch t (clamped; ticks >= M feed a
        # dummy that never reaches the collected outputs)
        inject = lax.dynamic_index_in_dim(
            microbatches, jnp.clip(t, 0, m - 1), keepdims=False)
        x = jnp.where(idx == 0, inject, state)
        y = body(stage_params, x)
        # last stage's result this tick is microbatch t-(S-1); keep it.
        # ppermute forwards every stage's output to the next stage
        # (the wrap-around last->0 edge carries values stage 0 ignores)
        nxt = lax.ppermute(y, axis_name, perm)
        return nxt, y

    _, ys = lax.scan(tick, jnp.zeros_like(microbatches[0]), jnp.arange(n_ticks))

    # ys on the LAST stage holds the pipeline outputs at ticks
    # [S-1, S-1+M); every other stage holds intermediates — masked to
    # zero so downstream per-stage compute stays finite and the
    # backward seeds only on the last stage.
    outs = lax.dynamic_slice_in_dim(ys, s - 1, m, axis=0)
    is_last = (idx == s - 1).astype(outs.dtype)
    return outs * is_last


def last_stage_mask(axis_name: str = AXIS_PIPE, dtype=jnp.float32):
    """1.0 on the pipeline's last stage, 0.0 elsewhere — multiply the
    loss (and metrics) by this so the backward seeds exactly once."""
    s = lax.axis_size(axis_name)
    return (lax.axis_index(axis_name) == s - 1).astype(dtype)


def stack_stages(layer_params: list[PyTree]) -> PyTree:
    """Stack per-layer param trees onto a leading axis the caller
    shards over ``pipe`` (layers must share a structure/shape)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *layer_params)


def make_pp_train_step(
    loss_fn: Callable,
    tx,
    mesh,
    state_specs: PyTree,
    pipe_psum_mask: PyTree,
    batch_partition=None,
    data_axis: str = "data",
    pipe_axis: str = AXIS_PIPE,
    donate: bool = True,
    grad_scale: float = 1.0,
):
    """shard_map training step for a pipeline-parallel model.

    Unlike the replicated-state BSP step, ``state_specs`` is a
    per-leaf spec tree: stage (block) params arrive sharded
    ``P('pipe')`` on their stacked layer axis and their grads stay
    LOCAL over ``pipe`` (each stage owns its layers); leaves where
    ``pipe_psum_mask`` is True (every replicated param — embeddings
    touched only by stage 0's compute path, head/final-norm only by
    the last stage's masked loss) are psum-ed over ``pipe`` to keep
    their replicas in sync.  The loss_fn must follow the masked-loss
    convention (``pipeline_apply`` docstring): loss and metrics are
    zero off the last stage, so metrics are psum-ed over ``pipe`` here
    and averaged over ``data`` as usual.
    """
    from jax.sharding import PartitionSpec as P

    if batch_partition is None:
        batch_partition = P(data_axis)

    from theanompi_tpu.parallel.bsp import apply_update, grad_and_metrics

    def shard_step(state, batch, rng):
        rng = jax.random.fold_in(rng, lax.axis_index(data_axis))
        grads, new_ms, metrics = grad_and_metrics(
            loss_fn, state.params, state.model_state, batch, rng)
        grads = jax.tree.map(
            lambda g, do_psum: lax.psum(g, pipe_axis) if do_psum else g,
            grads, pipe_psum_mask)
        grads = jax.tree.map(lambda g: lax.pmean(g, data_axis), grads)
        if grad_scale != 1.0:  # reference 'cdd' sum-mode exchange
            grads = jax.tree.map(lambda g: g * grad_scale, grads)
        # masked-loss convention: real metrics live on the last stage
        # only; psum replicates them across 'pipe', then average 'data'
        metrics = jax.tree.map(lambda x: lax.psum(x, pipe_axis), metrics)
        metrics = jax.tree.map(lambda x: lax.pmean(x, data_axis), metrics)
        return apply_update(tx, state, grads, new_ms), metrics

    sharded = jax.shard_map(
        shard_step,
        mesh=mesh,
        in_specs=(state_specs, batch_partition, P()),
        out_specs=(state_specs, P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def make_pp_eval_step(
    eval_fn: Callable,
    mesh,
    state_specs: PyTree,
    batch_partition=None,
    data_axis: str = "data",
):
    """shard_map eval step with the pipeline's per-leaf state specs;
    masked metrics (real on the last stage only) are psum-ed over
    ``pipe`` and pmean-ed over ``data``."""
    from jax.sharding import PartitionSpec as P

    if batch_partition is None:
        batch_partition = P(data_axis)

    def shard_step(state, batch):
        metrics = eval_fn(state.params, state.model_state, batch)
        metrics = jax.tree.map(lambda x: lax.psum(x, AXIS_PIPE), metrics)
        return jax.tree.map(lambda x: lax.pmean(x, data_axis), metrics)

    sharded = jax.shard_map(
        shard_step,
        mesh=mesh,
        in_specs=(state_specs, batch_partition),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(sharded)
