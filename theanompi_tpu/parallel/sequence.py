"""Sequence/context parallelism — ring attention + all-to-all variants.

The reference predates attention entirely (SURVEY.md §2.11/§5.7: a
2016 CNN framework; its only "sequence length" story is image
resolution).  The TPU rebuild makes long-context a first-class axis
anyway: the mesh reserves ``seq`` (parallel/mesh.py), and this module
supplies the attention primitives that shard the TIME dimension across
devices, so context length scales with chips instead of HBM.

Three strategies, all pure SPMD collectives over ICI (used inside a
``shard_map`` whose inputs are time-sharded ``P(..., 'seq', ...)``):

* ``ring_attention`` — blockwise attention with the online-softmax
  (flash) accumulation; K/V blocks rotate around the ring via
  ``lax.ppermute`` while each device keeps only its Q shard resident.
  Memory per device is O(T/n); the n ppermute hops ride ICI and XLA
  overlaps them with the per-block einsums.  Causal masking uses
  global positions, so rotation order never changes semantics.
* ``allgather_attention`` — K/V ``all_gather`` over the seq axis, then
  ordinary attention against the local Q shard.  Simplest; memory
  O(T) for K/V but still O(T/n) for scores if T_local is small.
* ``ulysses_attention`` — the all-to-all layout swap: resharding
  (time-sharded, all heads) → (all time, head-sharded) around a plain
  local attention, then back.  Needs n_heads % n_seq == 0.

All take/return (B, T_local, H, D) and are differentiable (the ring
loop is a ``lax.scan``), so they drop into a jitted training step.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from theanompi_tpu.ops.attention import (
    _MASK_NEG,
    block_scores as _block_scores,
    causal_mask as _causal_mask,
    fused_attention,
)
from theanompi_tpu.parallel.mesh import AXIS_SEQ


def attention_reference(q, k, v, causal: bool = False,
                        scale: Optional[float] = None):
    """Plain single-device attention (the correctness oracle and the
    inner kernel of the non-ring strategies)."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = _block_scores(q, k, scale)
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = _causal_mask(jnp.arange(tq), jnp.arange(tk))
        s = jnp.where(mask[None, None], s, _MASK_NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


def _attention_positions(q, k, v, q_pos, k_pos, scale):
    """Masked attention with explicit global positions (causal) — the
    tests' position-mask oracle; delegates to the one composed-XLA
    implementation (ops/attention.py)."""
    from theanompi_tpu.ops.attention import _xla_attention

    return _xla_attention(q, k, v, q_pos, k_pos, scale, causal=True)


def ring_attention(q, k, v, axis_name: str = AXIS_SEQ,
                   causal: bool = False, scale: Optional[float] = None):
    """Blockwise ring attention over the ``axis_name`` mesh axis.

    Inputs are the local time shard (B, T_local, H, D), laid out so
    shard i holds global positions [i*T_local, (i+1)*T_local).
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, t_local, h, d = q.shape
    q_pos = idx * t_local + jnp.arange(t_local)

    m0 = jnp.full((b, h, t_local), _MASK_NEG, jnp.float32)
    l0 = jnp.zeros((b, h, t_local), jnp.float32)
    acc0 = jnp.zeros((b, h, t_local, d), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(carry, step):
        k_blk, v_blk, m, l, acc = carry
        # after `step` rotations this device holds the block that
        # originated on ring neighbour (idx - step)
        src = (idx - step) % n
        k_pos = src * t_local + jnp.arange(t_local)
        s = _block_scores(q, k_blk, scale)            # (B,H,Tq,Tk)
        if causal:
            s = jnp.where(_causal_mask(q_pos, k_pos)[None, None],
                          s, _MASK_NEG)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(axis=-1)
        acc = acc * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32))
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, m_new, l, acc), None

    (k_f, v_f, m, l, acc), _ = lax.scan(
        body, (k, v, m0, l0, acc0), jnp.arange(n))
    del k_f, v_f
    out = acc / l[..., None]                          # (B,H,Tq,D)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # (B,Tq,H,D)


def allgather_attention(q, k, v, axis_name: str = AXIS_SEQ,
                        causal: bool = False,
                        scale: Optional[float] = None):
    """K/V all-gathered over the seq axis, local Q shard attends."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    t_local = q.shape[1]
    k_full = lax.all_gather(k, axis_name, axis=1, tiled=True)
    v_full = lax.all_gather(v, axis_name, axis=1, tiled=True)
    if not causal:
        return fused_attention(q, k_full, v_full, causal=False,
                               scale=scale)
    q_pos = idx * t_local + jnp.arange(t_local)
    k_pos = jnp.arange(n * t_local)
    return fused_attention(q, k_full, v_full, q_pos=q_pos, k_pos=k_pos,
                           causal=True, scale=scale)


def ulysses_attention(q, k, v, axis_name: str = AXIS_SEQ,
                      causal: bool = False,
                      scale: Optional[float] = None):
    """All-to-all head/time reshard around a plain local attention
    (the DeepSpeed-Ulysses layout): (B, T/n, H, D) -> (B, T, H/n, D)
    -> attend -> back.  Requires H % n == 0."""
    n = lax.axis_size(axis_name)
    h = q.shape[2]
    if h % n != 0:
        raise ValueError(f"ulysses needs heads ({h}) divisible by seq axis ({n})")

    def to_headshard(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def to_timeshard(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = to_headshard(q), to_headshard(k), to_headshard(v)
    out = fused_attention(qh, kh, vh, causal=causal, scale=scale)
    return to_timeshard(out)


STRATEGIES = {
    "ring": ring_attention,
    "allgather": allgather_attention,
    "ulysses": ulysses_attention,
}


def sequence_attention(q, k, v, axis_name: str = AXIS_SEQ,
                       causal: bool = False,
                       scale: Optional[float] = None,
                       strategy: str = "ring"):
    """Dispatch on the SP strategy name (the async-exchanger-style
    strategy seam, kept string-keyed like the reference's exchanger
    strategies — SURVEY.md §2.4)."""
    try:
        fn = STRATEGIES[strategy]
    except KeyError:
        raise ValueError(
            f"unknown sequence-parallel strategy {strategy!r}; "
            f"available: {sorted(STRATEGIES)}") from None
    return fn(q, k, v, axis_name=axis_name, causal=causal, scale=scale)
