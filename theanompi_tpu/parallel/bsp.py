"""BSP training as a single SPMD program.

In the reference, BSP was a subsystem: each rank ran ``train_iter()``
then called ``BSP_Exchanger.exchange()`` to allreduce gradients over
MPI/NCCL (reference layout ``theanompi/lib/exchanger.py`` + the BSP
worker module; SURVEY.md §2.3–§2.4, §3.2 — mount empty, no file:line).

On TPU, BSP is a compiler annotation: one jitted step, ``shard_map``-ped
over the ``data`` axis of a mesh, with the exchange traced inside it as
``psum``.  XLA schedules the ICI collectives and overlaps them with the
backward pass — the calc/comm overlap the reference could only
approximate with multi-stream tricks falls out of the compiler.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import PartitionSpec as P

from theanompi_tpu.parallel.exchanger import BSP_Exchanger
from theanompi_tpu.parallel.mesh import AXIS_DATA

PyTree = Any

# loss_fn(params, model_state, batch, rng) -> (loss, (new_model_state, metrics))
LossFn = Callable[[PyTree, PyTree, PyTree, jax.Array], tuple[jax.Array, tuple]]


@struct.dataclass
class TrainState:
    """Replicated training state (params + optimizer + mutable model
    collections such as BN batch_stats).

    ``exchange_residual`` is the bf16-exchange error-feedback buffer
    (``BSP_Exchanger.exchange_with_residual``): a per-shard f32 tree
    carried with a LEADING data-shard axis — leaf shape
    ``(n_data, *param_shape)`` globally, sharded ``P('data')``, seen
    as ``(1, *param_shape)`` inside the shard body.  It is per-shard
    state (each shard's quantization error differs), which is why it
    cannot ride the replicated part of the tree; ``None`` (the
    default, an empty subtree) keeps the pytree leaf set — and
    therefore every existing checkpoint — unchanged when the feature
    is off."""

    step: jax.Array
    params: PyTree
    opt_state: PyTree
    model_state: PyTree
    exchange_residual: PyTree = None

    @classmethod
    def create(cls, params, tx: optax.GradientTransformation, model_state=None):
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
            model_state={} if model_state is None else model_state,
        )


def _pmean(tree: PyTree, axes=(AXIS_DATA,)) -> PyTree:
    return jax.tree.map(lambda x: jax.lax.pmean(x, axes), tree)


def grad_and_metrics(loss_fn: LossFn, params, model_state, batch, rng):
    """Shared step-front: value_and_grad + metrics normalization.
    Used by every step builder (bsp/tensor/pipeline) so the core stays
    in one place; the builders differ only in which collectives wrap
    the results."""
    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
    (loss, (new_ms, metrics)), grads = grad_fn(params, model_state, batch,
                                               rng)
    metrics = dict(metrics)
    metrics.setdefault("loss", loss)
    return grads, new_ms, metrics


def apply_update(tx: optax.GradientTransformation, state: "TrainState",
                 grads, new_ms) -> "TrainState":
    """Shared step-tail: optimizer update + TrainState rebuild."""
    updates, new_opt = tx.update(grads, state.opt_state, state.params)
    new_params = optax.apply_updates(state.params, updates)
    return TrainState(step=state.step + 1, params=new_params,
                      opt_state=new_opt, model_state=new_ms,
                      exchange_residual=state.exchange_residual)


def _default_exchanger(exchanger: BSP_Exchanger | None,
                       reduce_axes: tuple[str, ...]) -> BSP_Exchanger:
    return exchanger or BSP_Exchanger(
        axis=reduce_axes if len(reduce_axes) > 1 else reduce_axes[0])


def _fold_axis_rng(rng, reduce_axes: tuple[str, ...]):
    """Decorrelate per-shard randomness (dropout, augment draws)."""
    for ax in reduce_axes:
        rng = jax.random.fold_in(rng, jax.lax.axis_index(ax))
    return rng


def _donate_argnums(donate: bool, donate_batch: bool) -> tuple[int, ...]:
    """argnums for the stacked-cadence steps: state (0) and optionally
    the staged batch (1).  The r3/r4 copy account charges 2.37 ms/step
    to 1 334 copy-done events; keeping a multi-megabyte staged batch
    alive across the whole scanned program forces XLA to copy around
    it, so the cadences donate it by default — the prefetcher stages a
    fresh batch per dispatch and never touches one after yielding it.
    ``donate_batch`` exists for callers that deliberately replay one
    staged batch (bench.py's device-step leg; equivalence tests that
    re-feed a stacked batch to a second step builder)."""
    if not donate:
        return ()
    return (0, 1) if donate_batch else (0,)


def state_partition_spec(residual_axis: str = AXIS_DATA) -> "TrainState":
    """TrainState-shaped PartitionSpec tree for the shard_map step
    builders: everything replicated EXCEPT the error-feedback residual,
    whose leading axis is sharded over ``residual_axis``.  Each field's
    spec is a pytree PREFIX, so this one tree covers both the
    residual-off case (``None`` — empty subtree under the prefix) and
    the residual-on case (every leaf split on its shard axis)."""
    return TrainState(step=P(), params=P(), opt_state=P(),
                      model_state=P(),
                      exchange_residual=P(residual_axis))


def init_exchange_residual(params: PyTree, n_shards: int) -> PyTree:
    """Zero residual with the leading shard axis, host-side; the caller
    places it (``P('data')`` on the leading axis)."""
    import numpy as np

    return jax.tree.map(
        lambda p: np.zeros((n_shards,) + tuple(p.shape), np.float32),
        params)


def _exchange_grads_and_update(exchanger: BSP_Exchanger,
                               tx: optax.GradientTransformation,
                               state: "TrainState", grads, new_ms,
                               reduce_axes) -> "TrainState":
    """Shared grads-mode tail: BN-stat pmean + exchange + update.
    Used by the single/multi-step grads branch AND the accum step so
    exchange semantics live in one place."""
    new_ms = _pmean(new_ms, reduce_axes)
    if exchanger.error_feedback:
        if state.exchange_residual is None:
            raise ValueError(
                "error_feedback needs state.exchange_residual "
                "(init_exchange_residual; models/base.py builds it from "
                "ModelConfig.exchange_error_feedback)")
        # residual leaves arrive per-shard as (1, *shape) — the leading
        # axis is the data-shard axis the spec splits
        res = jax.tree.map(lambda r: r[0], state.exchange_residual)
        grads, new_res = exchanger.exchange_with_residual(grads, res)
        new_state = apply_update(tx, state, grads, new_ms)
        return new_state.replace(
            exchange_residual=jax.tree.map(lambda r: r[None], new_res))
    grads = exchanger.exchange(grads)
    return apply_update(tx, state, grads, new_ms)


def _make_shard_step(
    loss_fn: LossFn,
    tx: optax.GradientTransformation,
    exchanger: BSP_Exchanger | None,
    reduce_axes: tuple[str, ...],
):
    """The per-shard training step body (one iteration): fwd + bwd +
    exchange + update + cross-replica syncs.  Shared by the single-step
    and the scanned multi-step builders."""
    exchanger = _default_exchanger(exchanger, reduce_axes)

    def bucketed_step(state: TrainState, batch, rng):
        # exchange_buckets > 1 grads path: the per-bucket collectives
        # are embedded in the backward DAG (exchanger.backward_exchange
        # boundary tags), so grads come back ALREADY exchanged — the
        # step tail is just BN-stat pmean + optimizer update
        res = None
        if exchanger.error_feedback:
            if state.exchange_residual is None:
                raise ValueError(
                    "error_feedback needs state.exchange_residual "
                    "(init_exchange_residual; models/base.py builds it "
                    "from ModelConfig.exchange_error_feedback)")
            res = jax.tree.map(lambda r: r[0], state.exchange_residual)
        loss, (new_ms, metrics), grads, new_res = (
            exchanger.backward_exchange(loss_fn, state.params,
                                        state.model_state, batch, rng,
                                        residual=res))
        metrics = dict(metrics)
        metrics.setdefault("loss", loss)
        new_ms = _pmean(new_ms, reduce_axes)
        new_state = apply_update(tx, state, grads, new_ms)
        if new_res is not None:
            new_state = new_state.replace(
                exchange_residual=jax.tree.map(lambda r: r[None],
                                               new_res))
        return new_state, _pmean(metrics, reduce_axes)

    def shard_step(state: TrainState, batch, rng):
        rng = _fold_axis_rng(rng, reduce_axes)
        if (exchanger.exchange_what == "grads"
                and exchanger.exchange_buckets > 1):
            return bucketed_step(state, batch, rng)
        grads, new_ms, metrics = grad_and_metrics(
            loss_fn, state.params, state.model_state, batch, rng)

        if exchanger.exchange_what == "grads":
            new_state = _exchange_grads_and_update(
                exchanger, tx, state, grads, new_ms, reduce_axes)
        else:  # 'params': local update, then allreduce parameters
            # Cross-replica sync of mutable collections (BN stats):
            # each shard saw a different micro-batch; average them.
            new_ms = _pmean(new_ms, reduce_axes)
            new_state = apply_update(tx, state, grads, new_ms)
            avg_exch = (
                exchanger if exchanger.avg
                else dataclasses.replace(exchanger, avg=True)
            )
            new_state = new_state.replace(
                params=avg_exch.exchange(new_state.params),
                # Momentum buffers live per-shard in 'params' mode;
                # average them too so state stays replicated (matches
                # the reference's param-averaging BSP semantics closely
                # enough, and keeps the SPMD invariant that state is
                # identical on every shard).
                opt_state=_pmean(new_state.opt_state, reduce_axes),
            )

        return new_state, _pmean(metrics, reduce_axes)

    return shard_step


def make_bsp_train_step(
    loss_fn: LossFn,
    tx: optax.GradientTransformation,
    mesh: jax.sharding.Mesh,
    exchanger: BSP_Exchanger | None = None,
    donate: bool = True,
    batch_partition: P = P(AXIS_DATA),
    reduce_axes: tuple[str, ...] = (AXIS_DATA,),
):
    """Build the jitted SPMD training step.

    Returns ``step(state, batch, rng) -> (state, metrics)`` where
    ``state`` is replicated over the mesh, ``batch`` is a pytree whose
    arrays are sharded by ``batch_partition`` (default: leading dim
    over the ``data`` axis; a sequence-parallel step passes
    ``P('data', 'seq')`` with ``reduce_axes=('data', 'seq')``), and
    ``rng`` is a replicated key (folded per-shard inside for dropout
    decorrelation).
    """
    shard_step = _make_shard_step(loss_fn, tx, exchanger, reduce_axes)
    st = state_partition_spec()
    sharded = jax.shard_map(
        shard_step,
        mesh=mesh,
        in_specs=(st, batch_partition, P()),
        out_specs=(st, P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def make_bsp_multi_step(
    loss_fn: LossFn,
    tx: optax.GradientTransformation,
    mesh: jax.sharding.Mesh,
    exchanger: BSP_Exchanger | None = None,
    donate: bool = True,
    donate_batch: bool = True,
    batch_partition: P = P(AXIS_DATA),
    reduce_axes: tuple[str, ...] = (AXIS_DATA,),
):
    """``lax.scan`` several training iterations into ONE device program.

    Returns ``multi_step(state, stacked_batch, rng) -> (state, metrics)``
    where ``stacked_batch`` arrays carry a leading steps axis ``k`` (the
    per-step batch axis behind it, sharded by ``batch_partition``) and
    ``metrics`` leaves come back stacked ``(k,)``.

    Why: each jitted execution through the axon tunnel pays a dispatch
    round-trip; at ~50 ms steps that overhead is material, and one
    program per k batches amortizes it k-fold.  Inside the scan each
    sub-step is the SAME program as ``make_bsp_train_step`` builds —
    grads psum-ed per sub-step, optimizer applied per sub-step — so the
    training trajectory is identical to k separate calls with rngs
    ``fold_in(rng, i)``.
    """
    single = _make_shard_step(loss_fn, tx, exchanger, reduce_axes)

    def shard_multi(state: TrainState, stacked, rng):
        def body(carry, xs):
            i, batch = xs
            new_state, metrics = single(carry, batch,
                                        jax.random.fold_in(rng, i))
            return new_state, metrics

        k = jax.tree.leaves(stacked)[0].shape[0]
        state, metrics = jax.lax.scan(
            body, state, (jnp.arange(k), stacked))
        return state, metrics

    stacked_partition = P(None, *batch_partition)
    st = state_partition_spec()
    sharded = jax.shard_map(
        shard_multi,
        mesh=mesh,
        in_specs=(st, stacked_partition, P()),
        out_specs=(st, P()),
        check_vma=False,
    )
    return jax.jit(sharded,
                   donate_argnums=_donate_argnums(donate, donate_batch))


def accumulate_microbatch_grads(loss_fn: LossFn, params, model_state,
                                stacked, rng, init_gsum, add_grads):
    """Shared accumulation scan for the grad-accum cadences (plain
    and ZeRO): threads model_state through ``a`` microbatches with
    per-microbatch rng folds, combining grads via ``add_grads(gsum,
    grads_tree)``.  Returns (new_model_state, gsum, metrics_mean, a) —
    the cadence semantics live HERE so the two step builders cannot
    diverge."""
    a = jax.tree.leaves(stacked)[0].shape[0]

    def body(carry, xs):
        ms, gsum = carry
        i, mb = xs
        grads, ms, metrics = grad_and_metrics(
            loss_fn, params, ms, mb, jax.random.fold_in(rng, i))
        return (ms, add_grads(gsum, grads)), metrics

    (ms, gsum), metrics = jax.lax.scan(
        body, (model_state, init_gsum), (jnp.arange(a), stacked))
    metrics = jax.tree.map(lambda m: m.mean(axis=0), metrics)
    return ms, gsum, metrics, a


def make_bsp_accum_step(
    loss_fn: LossFn,
    tx: optax.GradientTransformation,
    mesh: jax.sharding.Mesh,
    exchanger: BSP_Exchanger | None = None,
    donate: bool = True,
    donate_batch: bool = True,
    batch_partition: P = P(AXIS_DATA),
    reduce_axes: tuple[str, ...] = (AXIS_DATA,),
):
    """Gradient accumulation: ``a`` microbatches → ONE optimizer update.

    Returns ``accum_step(state, stacked_batch, rng) -> (state, metrics)``
    where ``stacked_batch`` arrays carry a leading microbatch axis ``a``
    (per-microbatch batch axis behind it, sharded by
    ``batch_partition``) and metrics come back averaged over the ``a``
    microbatches.  Grads are averaged across microbatches locally, then
    exchanged ONCE — so the effective global batch is
    ``a * global_batch`` at the HBM footprint of one microbatch, and
    the per-update ICI traffic of plain BSP.  Mean-of-means equals the
    big-batch gradient exactly for equal microbatch sizes (tested).

    Mutable model collections (BN batch_stats) thread through the scan
    per-microbatch, matching what a sequential big-batch pass would do
    step-wise.  ``exchange_what='params'`` has no well-defined
    accumulation semantics and is rejected.
    """
    exchanger = _default_exchanger(exchanger, reduce_axes)
    if exchanger.exchange_what != "grads":
        raise ValueError("gradient accumulation requires "
                         "exchange_what='grads' (param-averaging per "
                         "microbatch has no accumulation semantics)")

    def shard_accum(state: TrainState, stacked, rng):
        rng = _fold_axis_rng(rng, reduce_axes)
        gz = jax.tree.map(jnp.zeros_like, state.params)
        new_ms, gsum, metrics, a = accumulate_microbatch_grads(
            loss_fn, state.params, state.model_state, stacked, rng,
            gz, lambda gsum, g: jax.tree.map(jnp.add, gsum, g))
        grads = jax.tree.map(lambda g: g / a, gsum)

        new_state = _exchange_grads_and_update(
            exchanger, tx, state, grads, new_ms, reduce_axes)
        return new_state, _pmean(metrics, reduce_axes)

    stacked_partition = P(None, *batch_partition)
    st = state_partition_spec()
    sharded = jax.shard_map(
        shard_accum,
        mesh=mesh,
        in_specs=(st, stacked_partition, P()),
        out_specs=(st, P()),
        check_vma=False,
    )
    return jax.jit(sharded,
                   donate_argnums=_donate_argnums(donate, donate_batch))


def make_bsp_eval_step(
    eval_fn: Callable[[PyTree, PyTree, PyTree], dict],
    mesh: jax.sharding.Mesh,
    batch_partition: P = P(AXIS_DATA),
    reduce_axes: tuple[str, ...] = (AXIS_DATA,),
):
    """Build the jitted SPMD eval step.

    ``eval_fn(params, model_state, batch) -> metrics`` runs per shard;
    metrics are pmean-ed over the reduce axes (the reference allreduced
    val metrics the same way, SURVEY.md §3.5).
    """

    def shard_step(state: TrainState, batch):
        metrics = eval_fn(state.params, state.model_state, batch)
        return _pmean(metrics, reduce_axes)

    sharded = jax.shard_map(
        shard_step,
        mesh=mesh,
        in_specs=(P(), batch_partition),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(sharded)
