"""Byte-balanced contiguous range partitioning — the ONE greedy walk
behind every plan that must be derived identically on every rank.

Two consumers, one algorithm:

* ``parallel/shards.py partition_ranges`` — the sharded parameter
  service cuts the center tree's leaves into K shard ranges (ISSUE 8);
  validates ``k <= n`` because a shard with no leaves has nothing to
  serve.
* ``parallel/exchanger.py`` — the bucketed gradient exchange cuts the
  flatten-order gradient leaves into layer-ordered exchange buckets
  (ISSUE 13); clamps ``k`` to ``n`` because a bucket plan over fewer
  leaves than buckets should just degrade to per-leaf buckets.

The plan is a pure function of (sizes, k): deterministic, no RNG, no
host state — every client/rank recomputes it from its own copy of the
model tree and lands on the identical cut, so no plan ever travels
over a wire.  Keeping the walk here (instead of two copies) is what
makes that guarantee auditable.
"""

from __future__ import annotations

from typing import Sequence


def balanced_ranges(sizes: Sequence[int], k: int) -> list[tuple[int, int]]:
    """Cut ``len(sizes)`` items into ``k`` contiguous ``(lo, hi)``
    ranges balanced by total size.

    Greedy walk: each range takes items while that brings its
    cumulative total closer to the i-th size quantile, always taking
    at least one item and leaving at least one for every range after
    it.  Requires ``1 <= k <= len(sizes)``; callers that want
    clamping (bucket plans) clamp before calling.
    """
    sizes = [int(s) for s in sizes]
    n, k = len(sizes), int(k)
    if k < 1:
        raise ValueError(f"need k >= 1 ranges, got {k}")
    if n == 0:
        raise ValueError("cannot partition an empty sequence")
    if k > n:
        raise ValueError(
            f"{k} ranges over {n} items — items are never split, so "
            "at most one range per item")
    total = sum(sizes)
    ranges: list[tuple[int, int]] = []
    lo, acc = 0, 0
    for i in range(k):
        hi = lo + 1
        acc += sizes[lo]
        cap = n - (k - i - 1)  # leave >= 1 item per remaining range
        target = total * (i + 1) / k
        while hi < cap:
            nxt = acc + sizes[hi]
            if abs(nxt - target) <= abs(acc - target):
                acc = nxt
                hi += 1
            else:
                break
        ranges.append((lo, hi))
        lo = hi
    assert lo == n, (ranges, n)
    return ranges
