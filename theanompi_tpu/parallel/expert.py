"""Expert parallelism over the mesh's ``expert`` axis (switch-style MoE).

Beyond reference parity (the reference is data-parallel only,
SURVEY.md §2.11) — the fifth and last reserved mesh axis becomes real.
The canonical TPU pattern: experts are sharded over ``expert`` (each
shard owns ``E / ep`` expert FFNs, params stacked on a leading expert
axis ``P('expert')``), tokens are batch-sharded over data axes, and a
pair of ``lax.all_to_all`` collectives regroups tokens by expert and
back inside the jitted step.

Routing is top-1 (switch) with a fixed capacity per expert — static
shapes, as XLA requires: each token picks its argmax expert, tokens
beyond an expert's capacity are dropped (their combine weight is
zero), and the router is trained with the standard load-balancing
auxiliary loss (mean fraction routed x mean router probability, scaled
by E).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from theanompi_tpu.parallel.mesh import AXIS_EXPERT

PyTree = Any


def top1_dispatch(router_logits: jax.Array, capacity: int):
    """Build switch-routing dispatch/combine tensors for one shard.

    ``router_logits``: (n_tokens, E).  Returns
    ``dispatch`` (E, capacity, n_tokens) one-hot — token t is slot s of
    expert e; ``combine`` (n_tokens, E, capacity) — router-prob weights
    (zero for dropped tokens); and the load-balancing aux loss.
    """
    n, e = router_logits.shape
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)            # (n,)
    expert_prob = jnp.max(probs, axis=-1)              # (n,)

    # position of each token within its expert's queue
    onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.int32)   # (n, E)
    position = jnp.cumsum(onehot, axis=0) * onehot - 1        # (n, E)
    pos_in_expert = position.max(axis=-1)                     # (n,)
    keep = pos_in_expert < capacity

    # aux loss (Switch Transformer eq. 4): E * mean(frac_tokens) . mean(prob)
    frac_tokens = onehot.astype(jnp.float32).mean(axis=0)
    frac_probs = probs.mean(axis=0)
    aux = e * jnp.sum(frac_tokens * frac_probs)

    slot = jnp.where(keep, pos_in_expert, 0)
    dispatch = (
        jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)[:, :, None]
        * jax.nn.one_hot(slot, capacity, dtype=jnp.float32)[:, None, :]
        * keep[:, None, None]
    )                                                   # (n, E, capacity)
    combine = dispatch * expert_prob[:, None, None]
    return jnp.moveaxis(dispatch, 0, -1), combine, aux  # (E, cap, n), ...


def moe_ffn(x: jax.Array, router_kernel: jax.Array, expert_params: PyTree,
            apply_expert, capacity_factor: float = 1.25,
            axis_name: str | None = AXIS_EXPERT):
    """Switch-MoE FFN over tokens ``x`` (n_tokens, d).

    ``expert_params`` leaves carry a leading LOCAL-expert axis (E/ep
    per shard when ``axis_name`` is a real mesh axis; E when None or
    inside a size-1 axis).  ``apply_expert(params_e, tokens) -> out``
    applies one expert FFN; it is vmapped over local experts.

    With expert parallelism the dispatched tokens cross shards via
    ``all_to_all`` (tokens -> owning expert's shard) and return the
    same way; XLA schedules both on ICI.  Returns (out, aux_loss).
    """
    n, d = x.shape
    ep = lax.axis_size(axis_name) if axis_name is not None else 1
    e_local = jax.tree.leaves(expert_params)[0].shape[0]
    e = e_local * ep
    capacity = max(1, int(capacity_factor * n / e))

    router_logits = x.astype(jnp.float32) @ router_kernel  # (n, E)
    dispatch, combine, aux = top1_dispatch(router_logits, capacity)

    # tokens for every expert, gathered from this shard: (E, cap, d)
    expert_in = jnp.einsum("ecn,nd->ecd", dispatch, x.astype(jnp.float32))

    if ep > 1:
        # outbound: shard j receives, from every source shard s, the
        # (e_local, cap, d) block of tokens routed to ITS experts —
        # result (ep[source], e_local, cap, d) -> (e_local, ep*cap, d)
        expert_in = expert_in.reshape(ep, e_local, capacity, d)
        expert_in = lax.all_to_all(expert_in, axis_name, split_axis=0,
                                   concat_axis=0, tiled=False)
        expert_in = jnp.moveaxis(expert_in, 0, 1)  # (E_local, ep, cap, d)
        expert_in = expert_in.reshape(e_local, ep * capacity, d)
    # apply this shard's experts
    expert_out = jax.vmap(apply_expert)(expert_params, expert_in)
    if ep > 1:
        # return trip (exact mirror): send each source shard its token
        # slots back; dim0 of the result indexes the expert-owner
        # shard, so reshaping restores the global (E, cap, d) layout
        expert_out = expert_out.reshape(e_local, ep, capacity, d)
        expert_out = jnp.moveaxis(expert_out, 1, 0)  # (ep, E_local, cap, d)
        expert_out = lax.all_to_all(expert_out, axis_name, split_axis=0,
                                    concat_axis=0, tiled=False)
        expert_out = expert_out.reshape(e, capacity, d)
    out = jnp.einsum("nec,ecd->nd", combine, expert_out)
    return out.astype(x.dtype), aux


def make_moe_train_step(
    loss_fn,
    tx,
    mesh,
    state_specs: PyTree,
    expert_mask: PyTree,
    batch_partition=None,
    data_axis: str = "data",
    expert_axis: str = AXIS_EXPERT,
    donate: bool = True,
    grad_scale: float = 1.0,
):
    """shard_map training step for an expert-parallel model.

    The batch is sharded over BOTH ``(data, expert)`` — for non-MoE
    layers the expert axis is just more data parallelism — so grads of
    replicated params are pmean-ed over both axes, while leaves where
    ``expert_mask`` is True (the expert FFN stacks, sharded
    ``P('expert')``) already saw every token routed to them via the
    all_to_all and are pmean-ed over ``data`` only.
    """
    from jax.sharding import PartitionSpec as P

    from theanompi_tpu.parallel.bsp import apply_update, grad_and_metrics

    if batch_partition is None:
        batch_partition = P((data_axis, expert_axis))

    def shard_step(state, batch, rng):
        for ax in (data_axis, expert_axis):
            rng = jax.random.fold_in(rng, lax.axis_index(ax))
        grads, new_ms, metrics = grad_and_metrics(
            loss_fn, state.params, state.model_state, batch, rng)
        # expert leaves: the all_to_all TRANSPOSE already accumulated
        # every expert-axis shard's cotangent onto the owning shard (a
        # SUM over the axis, where replicated params get a per-shard
        # local grad) — divide by ep so expert grads live on the same
        # global-mean-loss scale as everything else, then average the
        # data replicas.  Non-expert leaves: plain mean over both axes.
        ep = lax.axis_size(expert_axis)
        grads = jax.tree.map(
            lambda g, is_exp: (
                lax.pmean(g, data_axis) / ep if is_exp
                else lax.pmean(g, (data_axis, expert_axis))),
            grads, expert_mask)
        if grad_scale != 1.0:  # reference 'cdd' sum-mode exchange
            grads = jax.tree.map(lambda g: g * grad_scale, grads)
        metrics = jax.tree.map(
            lambda x: lax.pmean(x, (data_axis, expert_axis)), metrics)
        return apply_update(tx, state, grads, new_ms), metrics

    sharded = jax.shard_map(
        shard_step,
        mesh=mesh,
        in_specs=(state_specs, batch_partition, P()),
        out_specs=(state_specs, P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def make_moe_eval_step(
    eval_fn,
    mesh,
    state_specs: PyTree,
    batch_partition=None,
    data_axis: str = "data",
    expert_axis: str = AXIS_EXPERT,
):
    from jax.sharding import PartitionSpec as P

    if batch_partition is None:
        batch_partition = P((data_axis, expert_axis))

    def shard_step(state, batch):
        metrics = eval_fn(state.params, state.model_state, batch)
        return jax.tree.map(
            lambda x: lax.pmean(x, (data_axis, expert_axis)), metrics)

    sharded = jax.shard_map(
        shard_step,
        mesh=mesh,
        in_specs=(state_specs, batch_partition),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(sharded)
