"""One-in-flight exchange thread — the shared comm/compute overlap
primitive.

Extracted from ``rules/async_rules.py`` (ISSUE 8) so the sharded
parameter-service router (``parallel/shards.py``) can reuse the same
thread discipline for its per-shard sub-exchanges without importing
the rules layer: the async rules overlap ONE exchange behind compute,
the shard router runs K per-shard sub-calls concurrently — both are
"hand a payload to a dedicated thread, collect exactly once, errors
re-raise at the collect site".
"""

from __future__ import annotations

import queue
import threading

from theanompi_tpu import monitor
from theanompi_tpu.analysis.lockgraph import make_lock
from theanompi_tpu.monitor import trace

#: _ExchangePipe shutdown sentinel
_STOP = object()


class _ExchangePipe:
    """One in-flight parameter exchange per worker — the comm/compute
    overlap plane (ISSUE 5 tentpole; the reference hid its MPI
    exchanges behind compute the same way, with a dedicated exchanger
    stream per worker).

    ``submit(payload)`` hands a HOST-side payload to this worker's
    exchange thread and returns immediately; the worker keeps
    computing while the RPC (serialize + wire + server merge) runs.
    ``collect()`` blocks until the in-flight exchange finishes and
    returns ``(payload, result)``.  The barrier is bounded-staleness:
    at most ONE exchange outstanding (``submit`` while outstanding
    raises), so a worker can never run ahead of the center by more
    than one exchange period.

    Fault-site-aware: the exchange function runs the SAME client call
    path as the synchronous mode, so an injected ``service_call``
    fault (resilience.faults) still lands — its exception is carried
    to the worker and re-raised at ``collect()``/``submit()``, where
    the supervisor's restart semantics see it exactly like a
    synchronous failure.

    Telemetry: each RPC runs under a top-level span in the exchange
    thread (``<name>_rpc`` by default; the shard router passes
    ``span='shard_exchange'``); the worker's wait inside ``collect``
    is its own ``<name>_collect`` span — the monitor can therefore
    PROVE overlap (compute spans no longer enclose the RPC span;
    collect time << rpc time), asserted by
    tests/test_async_overlap.py."""

    def __init__(self, fn, name: str, worker: int, span: str | None = None):
        self._fn = fn
        self._name = name
        self._span = span if span is not None else f"{name}_rpc"
        self._worker = str(worker)
        self._req: queue.Queue = queue.Queue(maxsize=1)
        self._res: queue.Queue = queue.Queue(maxsize=1)
        self._lock = make_lock("_ExchangePipe._lock")
        self._err: BaseException | None = None  # guarded_by: self._lock
        self.outstanding = False                # guarded_by: self._lock
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"{name}-exchange-w{worker}")
        self._thread.start()

    def _run(self):
        while True:
            item = self._req.get()
            if item is _STOP:
                return
            payload, ctx = item
            try:
                # the submitter's captured trace context re-attaches
                # here, so the exchange span (and the RPC it wraps)
                # stays a child of the submitting worker's span even
                # though it runs on this thread — without the handoff,
                # every overlapped exchange would root its own trace
                with trace.attach_wire(ctx), \
                        monitor.span(self._span, worker=self._worker):
                    out = (self._fn(payload), None)
            except BaseException as e:  # surfaced at collect()
                out = (None, e)
            self._res.put((payload, out))

    def busy(self) -> bool:
        """Locked read of the barrier flag — the worker loop's drain
        checks go through here so every access of the guarded state
        honors the declared discipline."""
        with self._lock:
            return self.outstanding

    def submit(self, payload) -> None:
        """Hand one host payload to the exchange thread (returns
        immediately).  A prior failure or an already-outstanding
        exchange raises here."""
        # the barrier flag and the sticky error are declared
        # guarded_by this lock: today a pipe is owned by exactly one
        # worker thread, so the lock buys visibility/discipline rather
        # than fixing a live race — but it keeps check-then-set atomic
        # if the ownership story ever changes, at nanoseconds of cost
        with self._lock:
            if self._err is not None:
                raise self._err
            if self.outstanding:
                raise RuntimeError(
                    f"{self._name}: bounded-staleness barrier — at most "
                    "one exchange may be outstanding; collect() first")
            self.outstanding = True
        try:
            # queue put outside the lock: it can block when the
            # exchange thread still holds the previous item; the trace
            # context is captured HERE, on the submitting thread, where
            # the caller's span is still open
            self._req.put((payload, trace.capture()))
        except BaseException:
            with self._lock:
                self.outstanding = False
            raise

    def collect(self):
        """Block for the in-flight exchange; returns (payload, result).
        Re-raises the exchange thread's exception (incl. injected
        faults) in the worker thread."""
        payload, (result, err) = self._res.get()
        with self._lock:
            self.outstanding = False
            if err is not None:
                self._err = err
        if err is not None:
            raise err
        return payload, result

    def close(self) -> None:
        """Stop the exchange thread (idempotent; never blocks on an
        uncollected result — the queues hold at most one item each)."""
        try:
            self._req.put_nowait(_STOP)
        except queue.Full:
            # a request is still queued: a dropped sentinel would leave
            # the exchange thread parked on _req.get() forever (pinning
            # the client + model closures across supervisor restarts) —
            # a reaper delivers STOP once the thread dequeues the
            # request, without blocking the worker here
            threading.Thread(target=self._req.put, args=(_STOP,),
                             daemon=True,
                             name=f"{self._name}-exchange-reaper").start()
