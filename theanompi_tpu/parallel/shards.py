"""Sharded parameter service — the center pytree split across K
independent shard processes (ISSUE 8 tentpole; docs/DESIGN.md
"Sharded parameter service").

EASGD/ASGD previously converged on ONE center process
(``parallel/service.py``): wire v2 made each round trip cheap, but
every worker still talked to the same socket, so the async host plane
topped out at one host's NIC and one Python GIL.  This module is the
sharded parameter server of the TensorFlow paper (arXiv:1605.08695)
rebuilt on our framed transport, applied to the elastic-averaging
rules of Theano-MPI (arXiv:1605.08325):

* **Leaf-range partitioning** (:func:`partition_ranges`): the center
  tree's leaves, in canonical ``jax.tree.flatten`` order, are cut into
  K contiguous ranges balanced by bytes.  The partition is a pure
  function of (leaf byte sizes, K), so every client computes the same
  plan from its own model state — no plan distribution step.  Leaves
  are never split, so any per-leaf optimizer (the whole
  ``build_optimizer`` zoo — SGD/momentum, Adam(W), RMSProp, LARS) and
  the elastic-averaging update produce **byte-identical** math under
  any K (pinned by tests/test_shards.py).
* **Shard = one param service process** (:class:`ShardParamService`
  behind the same ``serve`` loop): each shard owns its leaf range as
  an ordinary EASGD/ASGD store, speaks wire v2 with its own HMAC
  session, and restarts like the tested single-server matrix — the
  per-shard client's session rejoin re-seeds ONLY that shard's leaf
  range from its last good sub-result.
* **Shard router** (:class:`ShardedEASGD` / :class:`ShardedASGD`, on
  ``service.ShardedServiceClient``): duck-types the single-center
  stores, scattering each full-tree op into K tagged sub-ops issued
  concurrently on per-shard exchange threads and reassembling the
  tree.
* **Cross-shard version fence**: every mutating sub-op carries a
  ``(client_id, seq)`` tag (one seq per full-tree op), each shard
  keeps a per-client vector clock, and a consistent read is two-phase
  — freeze all shards (blocking new exchanges, draining in-flight
  ones), read only if all vector clocks agree, release.  Checkpoints
  and exports therefore always restore a tree equal to some single
  global version, never a mix of exchange E's shard A with
  pre-E's shard B.

Trust model: each shard connection authenticates with the SAME
``THEANOMPI_TPU_SERVICE_KEY`` HMAC handshake but holds its own
session; compromising one shard port exposes only that shard's leaf
range (see docs/DESIGN.md for the full note).

GOSGD is deliberately NOT sharded: its hub is a rendezvous of whole
param trees, not an accumulating center — shard it and a gossip push
would straddle processes with nothing to reassemble.  The launcher and
the rule both refuse.

Launch one shard:  ``python -m theanompi_tpu.parallel.shards --port
45810 --shard-index 0`` — or let ``tmlocal <rule> --shards K`` spawn
and supervise the whole fleet (:class:`ShardProcessGroup`).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import threading
import time
from typing import Any, Sequence

import jax
import numpy as np

from theanompi_tpu import monitor
from theanompi_tpu.analysis.lockgraph import make_condition, make_lock
from theanompi_tpu.parallel import rpc
from theanompi_tpu.parallel.partition import balanced_ranges
from theanompi_tpu.parallel.service import (
    FenceBusy,
    ParamService,
    RemoteASGD,
    RemoteEASGD,
    ServiceClient,
    ShardNotReady,
    ShardedServiceClient,
    _authkey,
    _np,
)

PyTree = Any

#: first port ``tmlocal --shards`` probes from (shard i binds a free
#: port, so this is cosmetic — the clients get explicit addresses)
DEFAULT_BASE_PORT = 45810


def _fence_timeout_s() -> float:
    """How long a shard honors a freeze with no release before
    auto-expiring it — a reader that died between freeze and release
    must not wedge training forever."""
    return float(os.environ.get(
        "THEANOMPI_TPU_SHARD_FENCE_TIMEOUT_S", "30"))


# ---------------------------------------------------------------------------
# Leaf-range partitioning
# ---------------------------------------------------------------------------


def partition_ranges(sizes: Sequence[int], n_shards: int
                     ) -> list[tuple[int, int]]:
    """Cut ``len(sizes)`` leaves into ``n_shards`` contiguous
    ``(lo, hi)`` ranges balanced by total bytes.

    Deterministic in (sizes, n_shards) — every client derives the same
    plan from its own copy of the model tree.  The greedy quantile
    walk lives in ``parallel/partition.py`` (shared with the bucketed
    gradient exchange, which derives its layer-ordered bucket plan
    from the same function — one algorithm, one audit surface); this
    wrapper keeps the shard-fleet error messages."""
    k, n = int(n_shards), len(sizes)
    if k < 1:
        raise ValueError(f"n_shards must be >= 1, got {k}")
    if n == 0:
        raise ValueError("cannot shard an empty tree")
    if k > n:
        raise ValueError(
            f"{k} shards over {n} leaves — a leaf is never split, so "
            "at most one shard per leaf (lower --shards)")
    return balanced_ranges(sizes, k)


def shard_addresses(server_addr: str | None) -> list[str] | None:
    """Parse the launcher/rules ``server_addr`` — a single ``host:port``
    or a comma-separated shard fleet — into a list (None when unset)."""
    if not server_addr:
        return None
    addrs = [a.strip() for a in server_addr.split(",") if a.strip()]
    if not addrs:
        raise ValueError(f"no addresses in server_addr {server_addr!r}")
    return addrs


# ---------------------------------------------------------------------------
# Server side: one shard of the center
# ---------------------------------------------------------------------------


class ShardParamService(ParamService):
    """A :class:`ParamService` that owns ONE leaf range of the center
    and adds the version-fence plane (module docstring):

    * ``shard_exchange`` / ``shard_push_pull`` — the tagged forms of
      ``easgd_exchange`` / ``asgd_push_pull``: same store arithmetic,
      plus fence admission (a frozen shard blocks new mutations) and
      vector-clock accounting ``{client_id: max seq}``;
    * ``shard_freeze (kind, session_id, token)`` — block new mutations,
      drain the in-flight one, return this shard's vector clock.  A
      fence held by ANOTHER token raises :class:`FenceBusy`
      (retryable client-side); a fence whose reader never released
      auto-expires after ``THEANOMPI_TPU_SHARD_FENCE_TIMEOUT_S``;
    * ``shard_release (kind, session_id, token)`` — lift the freeze
      (idempotent; a stranger's token is a no-op).

    Reads (``*_get_center`` …) are never blocked: the freeze exists
    exactly so the fence holder can read.  Everything else —
    init/join/rejoin session fencing, displacement fail-fast, the wire
    loop — is inherited unchanged, which is what makes a shard restart
    look like the already-tested server-restart matrix."""

    #: tagged mutating op -> the base-store op it wraps
    MUT_OPS = {"shard_exchange": "easgd_exchange",
               "shard_push_pull": "asgd_push_pull"}

    #: RPC-substrate control-pool routing (parallel/rpc.py): during a
    #: fence, frozen mutations legitimately PARK their executor
    #: workers in _admit — freeze/release and the fenced read/write
    #: ops must run on the control pool or the fence would starve
    #: behind the very mutations it holds back (the pool-level form of
    #: the dedicated-fence-connection rationale in docs/DESIGN.md)
    RPC_CONTROL_OPS = ParamService.RPC_CONTROL_OPS | frozenset({
        "shard_freeze", "shard_release", "shard_info",
        "easgd_get_center", "asgd_get_center", "asgd_get_opt_state",
        "asgd_set_lr",
    })

    def __init__(self, shard_index: int = 0):
        super().__init__()
        self.shard_index = int(shard_index)
        self._gate = make_lock("ShardParamService._gate")
        self._gate_cv = make_condition(self._gate,
                                       "ShardParamService._gate_cv")
        self._frozen: dict[str, str | None] = {}   # guarded_by: self._gate
        self._frozen_at: dict[str, float] = {}     # guarded_by: self._gate
        self._inflight: dict[str, int] = {}        # guarded_by: self._gate
        self._vclock: dict[str, dict[str, int]] = {}  # guarded_by: self._gate
        # monotone count of APPLIED mutations — unlike the vclock's
        # per-client max-seq, an at-least-once duplicate re-apply bumps
        # it, so the fence's post-read validation catches a duplicate
        # that slipped through an expired fence mid-read (the vclock
        # alone is blind to that torn cut)
        self._applied: dict[str, int] = {}         # guarded_by: self._gate

    # -- fence admission ----------------------------------------------

    def _admit(self, kind: str) -> None:
        """Block while ``kind`` is frozen (auto-expiring a stale
        fence), then count this mutation in-flight."""
        deadline = time.monotonic() + 2 * _fence_timeout_s()
        with self._gate_cv:
            while self._frozen.get(kind) is not None:
                if (time.monotonic() - self._frozen_at.get(kind, 0.0)
                        > _fence_timeout_s()):
                    # the reader died between freeze and release:
                    # training must not stay wedged on its corpse
                    self._frozen[kind] = None
                    self._gate_cv.notify_all()
                    monitor.inc("service/shard_fence_expired_total")
                    break
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"shard {self.shard_index}: {kind} mutation "
                        "blocked past twice the fence timeout")
                self._gate_cv.wait(0.05)
            self._inflight[kind] = self._inflight.get(kind, 0) + 1

    def _settle(self, kind: str, client_id: str | None = None,
                seq: int | None = None, count: int = 1) -> None:
        """Retire an in-flight mutation; on success record it in the
        vector clock (per-client max — an at-least-once duplicate of a
        lost-reply re-send must not read as a NEW exchange).  ``count``
        is the aggregate op's worker-count multiplier: one hierarchical
        exchange stands for ``count`` same-version worker exchanges,
        and the applied counter must say so — the fence's accounting
        stays identical to ``count`` independent exchanges."""
        with self._gate_cv:
            self._inflight[kind] = self._inflight.get(kind, 1) - 1
            if client_id is not None:
                vc = self._vclock.setdefault(kind, {})
                vc[client_id] = max(int(seq), vc.get(client_id, 0))
                self._applied[kind] = self._applied.get(kind, 0) \
                    + int(count)
            self._gate_cv.notify_all()

    def _freeze(self, kind: str, session_id: str, token: str) -> dict:
        # session fencing: a DISPLACED session fails fast (the reader's
        # whole training session is stale), but a missing store raises
        # the retryable ShardNotReady — the freeze raced this shard's
        # restart, and a worker's rejoin rebuilds the range shortly
        cur = self._sessions.get(kind)
        if cur is not None and cur != session_id:
            self._store(kind, session_id)  # raises the displaced error
        if self._stores.get(kind) is None or cur != session_id:
            raise ShardNotReady(
                f"{kind} session {session_id!r} is not live on shard "
                f"{self.shard_index} (restart in progress?)")
        t0 = time.monotonic()
        with self._gate_cv:
            cur = self._frozen.get(kind)
            if cur is not None and cur != token:
                if (time.monotonic() - self._frozen_at.get(kind, 0.0)
                        <= _fence_timeout_s()):
                    raise FenceBusy(
                        f"{kind} fence on shard {self.shard_index} is "
                        "held by another reader")
                monitor.inc("service/shard_fence_expired_total")
            self._frozen[kind] = token
            self._frozen_at[kind] = time.monotonic()
            while self._inflight.get(kind, 0) > 0:
                if time.monotonic() - t0 > _fence_timeout_s():
                    self._frozen[kind] = None
                    self._gate_cv.notify_all()
                    raise RuntimeError(
                        f"shard {self.shard_index}: freeze timed out "
                        f"waiting for an in-flight {kind} mutation")
                self._gate_cv.wait(0.05)
            return {"shard": self.shard_index,
                    "vclock": dict(self._vclock.get(kind, {})),
                    "applied": self._applied.get(kind, 0)}

    def _release(self, kind: str, session_id: str, token: str) -> str:
        with self._gate_cv:
            if self._frozen.get(kind) == token:
                self._frozen[kind] = None
                self._frozen_at.pop(kind, None)
                self._gate_cv.notify_all()
        return "released"

    # -- dispatch ------------------------------------------------------

    def handle(self, op: str, *args):
        base = self.MUT_OPS.get(op)
        if base is not None:
            if len(args) not in (4, 5) or not isinstance(args[0], str):
                raise ValueError(
                    f"{op} requires (session_id, payload, client_id, "
                    f"seq[, n_workers]) — got {len(args)} args")
            sid, payload, client_id, seq = args[:4]
            try:
                # validate BEFORE the store op: a mutation that applied
                # but could not be versioned would be invisible to the
                # fence's clock comparison — a silent torn-cut hole
                seq = int(seq)
            except (TypeError, ValueError):
                raise ValueError(
                    f"{op} seq must be an int, got {seq!r}") from None
            # optional 5th arg: the hierarchical plane's worker-count
            # multiplier (parallel/aggregate.py) — the SAME tagged op,
            # dispatched to the aggregate store math, counted in the
            # fence accounting as n_workers same-version exchanges
            n_workers = None
            if len(args) == 5:
                try:
                    n_workers = int(args[4])
                except (TypeError, ValueError):
                    raise ValueError(
                        f"{op} n_workers must be an int, "
                        f"got {args[4]!r}") from None
                if n_workers < 1:
                    raise ValueError(
                        f"{op} n_workers must be >= 1, got {n_workers}")
            kind = base.split("_", 1)[0]
            self._admit(kind)
            try:
                if n_workers is None:
                    out = super().handle(base, sid, payload)
                else:
                    out = super().handle(base + "_n", sid, payload,
                                         n_workers)
            except BaseException:
                self._settle(kind)  # failed mutations don't version
                raise
            self._settle(kind, str(client_id), seq,
                         count=1 if n_workers is None else n_workers)
            return out
        if op == "shard_freeze":
            return self._freeze(*args)
        if op == "shard_release":
            return self._release(*args)
        if op == "shard_info":
            return {"shard": self.shard_index}
        return super().handle(op, *args)


def serve_shard(host: str = "0.0.0.0", port: int = 0,
                shard_index: int = 0,
                ready_event: threading.Event | None = None,
                stop_event: threading.Event | None = None,
                authkey: bytes | None = None) -> None:
    """The param-service wire loop over a :class:`ShardParamService`."""
    from theanompi_tpu.parallel.service import serve

    serve(host, port, ready_event=ready_event, stop_event=stop_event,
          authkey=authkey, service=ShardParamService(shard_index))


# ---------------------------------------------------------------------------
# Client side: per-shard session clients + routers
# ---------------------------------------------------------------------------


def _shard_transports(addresses: Sequence[str]) -> list | None:
    """One multiplexed transport per shard peer: the shard's session
    client and its fence control client become two streams on ONE
    socket — halving the router's fd count — which the selector loop's
    control-pool routing of ``shard_freeze``/``shard_release`` makes
    deadlock-free (see ``ShardedServiceClient``).  ON by default
    (``THEANOMPI_TPU_SHARD_MUX=0`` opts out) since the ``bench_rpc
    --soak`` byte-identity pins hold under sustained load; against a
    non-mux server the transports silently degrade to dedicated
    sockets, so the default is safe either way."""
    if os.environ.get("THEANOMPI_TPU_SHARD_MUX", "1") != "1":
        return None
    if os.environ.get("THEANOMPI_TPU_WIRE_PROTOCOL", "v2") == "v1":
        # mux streams are wire-v2 framed by construction; a client
        # pinned to v1 pickle keeps its dedicated sockets — the same
        # silent degradation as a non-mux server
        return None
    from theanompi_tpu.parallel.rpc import MuxConnection

    return [MuxConnection(addr) for addr in addresses]


class _ShardEASGD(RemoteEASGD):
    """One shard's session client: a :class:`RemoteEASGD` whose tree is
    this shard's sub-list of leaves.  Inherits the whole
    reconnect/rejoin matrix — after a shard restart, ``_rejoin``
    re-seeds ONLY this shard's leaf range from its last good
    sub-result."""

    def exchange_tagged(self, sub_leaves: list, client_id: str,
                        seq: int, n_workers: int | None = None) -> list:
        """``n_workers`` marks an AGGREGATE sub-exchange (the
        hierarchical plane): same tagged op, a 5th multiplier arg, and
        the reply is this shard's PRE-update center range instead of
        the new worker range."""
        if n_workers is None:
            out = self.call("shard_exchange", self._sid, sub_leaves,
                            client_id, int(seq))
        else:
            out = self.call("shard_exchange", self._sid, sub_leaves,
                            client_id, int(seq), int(n_workers))
        self._rebuild = out
        return out

    def exchange(self, worker_params):  # pragma: no cover - guard
        raise RuntimeError("sharded exchanges must carry a version tag "
                           "— use exchange_tagged (via ShardedEASGD)")


class _ShardASGD(RemoteASGD):
    """One shard's ASGD session client (see :class:`_ShardEASGD`)."""

    def push_pull_tagged(self, sub_grads: list, client_id: str,
                         seq: int, n_workers: int | None = None) -> list:
        """``n_workers`` marks an AGGREGATE sub-push (see
        ``_ShardEASGD.exchange_tagged``); the reply stays the fresh
        center range either way."""
        if n_workers is None:
            out = self.call("shard_push_pull", self._sid, sub_grads,
                            client_id, int(seq))
        else:
            out = self.call("shard_push_pull", self._sid, sub_grads,
                            client_id, int(seq), int(n_workers))
        self._rebuild = out
        return out

    def push_pull(self, grads):  # pragma: no cover - guard
        raise RuntimeError("sharded pushes must carry a version tag — "
                           "use push_pull_tagged (via ShardedASGD)")


class _TreePlan:
    """Flatten-order plan shared by the routers: treedef + contiguous
    leaf ranges.  The session CREATOR derives it from the init params;
    a JOINER (params=None) derives it lazily from its first exchanged
    tree — identical by construction, since the partition is a pure
    function of (leaf sizes, K) and all workers share one model."""

    def __init__(self, n_shards: int):
        self.n_shards = n_shards
        self.treedef = None
        self.ranges: list[tuple[int, int]] | None = None

    def split(self, tree: PyTree) -> list[list[np.ndarray]]:
        flat, treedef = jax.tree.flatten(tree)
        flat = [np.asarray(a) for a in jax.device_get(flat)]
        if self.treedef is None:
            self.treedef = treedef
            self.ranges = partition_ranges([a.nbytes for a in flat],
                                           self.n_shards)
        return [flat[lo:hi] for lo, hi in self.ranges]

    def join(self, subs: list[list]) -> PyTree:
        if self.treedef is None:
            raise RuntimeError(
                "this sharded client has not seen the tree structure "
                "yet — init with params, or exchange once, before "
                "reading the center")
        leaves = [np.asarray(x) for sub in subs for x in sub]
        return jax.tree.unflatten(self.treedef, leaves)


class ShardedEASGD(ShardedServiceClient):
    """``EASGDServer`` API over K shards (drop-in for
    :class:`RemoteEASGD` in the EASGD rule).  The elastic exchange is
    element-wise, so K independent per-range exchanges reassemble to
    the exact single-center result — pinned byte-identical by
    tests/test_shards.py."""

    def __init__(self, addresses: Sequence[str], params: PyTree | None,
                 alpha: float, session_id: str = "default"):
        addresses = list(addresses)
        self._alpha = float(alpha)
        self._plan = _TreePlan(len(addresses))
        subs = (self._plan.split(_np(jax.device_get(params)))
                if params is not None else [None] * len(addresses))
        transports = _shard_transports(addresses)
        clients = [_ShardEASGD(addr, sub, alpha=alpha,
                               session_id=session_id, transport=tr)
                   for addr, sub, tr in zip(addresses, subs,
                                            transports or
                                            [None] * len(addresses))]
        super().__init__(clients, "easgd", session_id,
                         transports=transports)

    def exchange(self, worker_params: PyTree) -> PyTree:
        subs = self._plan.split(worker_params)
        seq = self._next_seq()
        cid = self._client_id
        thunks = [
            (lambda c=c, sub=sub: c.exchange_tagged(sub, cid, seq))
            for c, sub in zip(self._shard_clients, subs)]
        return self._plan.join(self._scatter(thunks))

    def exchange_n(self, worker_mean: PyTree, n: int) -> PyTree:
        """Aggregated exchange over the fleet: ONE tagged sub-exchange
        per shard carries the n-worker mean + multiplier; the
        reassembled reply is the PRE-update center (see
        ``EASGDServer.exchange_n``) the aggregator fans back out."""
        subs = self._plan.split(worker_mean)
        seq = self._next_seq()
        cid = self._client_id
        n = int(n)
        thunks = [
            (lambda c=c, sub=sub: c.exchange_tagged(sub, cid, seq, n))
            for c, sub in zip(self._shard_clients, subs)]
        return self._plan.join(self._scatter(thunks))

    def fenced_center(self) -> tuple[PyTree, dict]:
        """The consistent cut + the vector clock it froze at (the
        'single global version' the checkpoint corresponds to)."""
        outs, vclock = self.fenced_read("easgd_get_center")
        return self._plan.join(outs), vclock

    def get_center(self) -> PyTree:
        return self.fenced_center()[0]

    @property
    def n_exchanges(self) -> int:
        # every full exchange lands once on every shard, so shard 0
        # speaks for the fleet
        return int(self._shard_clients[0].call("stats")
                   .get("n_exchanges", 0))


class ShardedASGD(ShardedServiceClient):
    """``ASGDServer`` API over K shards (drop-in for
    :class:`RemoteASGD` in the ASGD rule).  Each shard runs its own
    optimizer over its leaf range; the ``build_optimizer`` zoo is
    per-leaf, so the reassembled center is byte-identical to the
    single-center run.

    Optimizer-state caveat (documented in docs/RESILIENCE.md): the
    per-shard optimizer states do not reassemble into the single-tree
    optax structure (each shard holds its own hyperparam/count
    leaves), so sharded ASGD neither ships a restored ``opt_state`` at
    init nor serves ``get_opt_state`` — a sharded resume re-seeds the
    center exactly and restarts server momentum fresh, the same trade
    the service-restart rejoin already makes."""

    #: the ASGD rule checks this before trying to checkpoint/restore
    #: the server optimizer state through a sharded client
    supports_opt_state = False

    def __init__(self, addresses: Sequence[str], params: PyTree | None,
                 opt_cfg: dict, opt_state: PyTree | None = None,
                 session_id: str = "default"):
        if opt_state is not None:
            raise ValueError(
                "sharded ASGD cannot scatter a restored opt_state "
                "(per-shard optax states each hold their own "
                "hyperparam/count leaves); resume re-seeds the center "
                "and starts server momentum fresh — docs/RESILIENCE.md")
        addresses = list(addresses)
        self._plan = _TreePlan(len(addresses))
        subs = (self._plan.split(_np(jax.device_get(params)))
                if params is not None else [None] * len(addresses))
        transports = _shard_transports(addresses)
        clients = [_ShardASGD(addr, sub, dict(opt_cfg),
                              session_id=session_id, transport=tr)
                   for addr, sub, tr in zip(addresses, subs,
                                            transports or
                                            [None] * len(addresses))]
        super().__init__(clients, "asgd", session_id,
                         transports=transports)

    def push_pull(self, grads: PyTree) -> PyTree:
        subs = self._plan.split(grads)
        seq = self._next_seq()
        cid = self._client_id
        thunks = [
            (lambda c=c, sub=sub: c.push_pull_tagged(sub, cid, seq))
            for c, sub in zip(self._shard_clients, subs)]
        return self._plan.join(self._scatter(thunks))

    def push_pull_n(self, grad_sum: PyTree, n: int) -> PyTree:
        """Aggregated grad push over the fleet (see
        ``ShardedEASGD.exchange_n``): one tagged sub-push per shard,
        reassembling the fresh center."""
        subs = self._plan.split(grad_sum)
        seq = self._next_seq()
        cid = self._client_id
        n = int(n)
        thunks = [
            (lambda c=c, sub=sub: c.push_pull_tagged(sub, cid, seq, n))
            for c, sub in zip(self._shard_clients, subs)]
        return self._plan.join(self._scatter(thunks))

    def set_lr(self, lr: float) -> None:
        """Fenced broadcast — every shard's optimizer applies updates,
        so the schedule must reach all of them, and it must not
        interleave with a concurrent worker's K-way push (the
        single-center store serializes set_lr vs push_pull under one
        lock; a bare broadcast would let one logical update apply with
        the old lr on some leaf ranges and the new lr on others).
        set_lr is idempotent, so the fence's validation-retry is
        safe."""
        self.fenced_op("asgd_set_lr", float(lr))

    def fenced_center(self) -> tuple[PyTree, dict]:
        outs, vclock = self.fenced_read("asgd_get_center")
        return self._plan.join(outs), vclock

    def get_center(self) -> PyTree:
        return self.fenced_center()[0]

    def get_opt_state(self):
        raise RuntimeError(
            "sharded ASGD has no single-tree opt_state (class "
            "docstring); the rule checkpoints the worker's own "
            "opt_state structure instead")

    @property
    def n_updates(self) -> int:
        return int(self._shard_clients[0].call("stats")
                   .get("n_updates", 0))


# ---------------------------------------------------------------------------
# Shard fleet supervision (tmlocal --shards K, bench, preflight smoke)
# ---------------------------------------------------------------------------


class ShardProcessGroup:
    """Spawn K real shard processes and supervise them: a shard that
    dies is relaunched on its port (budget ``max_restarts`` per shard),
    and the clients' per-shard session rejoin re-seeds its leaf range
    on their next op — the server-restart matrix, per shard.

    Requires/exports ``THEANOMPI_TPU_SERVICE_KEY`` (a missing key is
    generated and exported exactly like a standalone ``tmserver``).
    The child processes inherit this environment, monitor dir
    included, so each shard writes its own ``service/*`` telemetry."""

    def __init__(self, n_shards: int, host: str = "127.0.0.1",
                 max_restarts: int = 1, platform: str | None = "cpu",
                 ready_timeout_s: float = 180.0):
        if n_shards < 1:
            raise ValueError(f"n_shards must be >= 1, got {n_shards}")
        self.host = host
        self.max_restarts = int(max_restarts)
        self.platform = platform
        _authkey(generate=True)  # ensure + export the shared key
        self._lock = make_lock("ShardProcessGroup._lock")
        self._stopping = threading.Event()
        self._ports: list[int] = []
        # THEANOMPI_TPU_UNIX_SOCKETS=1 puts the whole same-host fleet
        # on AF_UNIX listeners (one socket file per shard); a port is
        # still allocated per shard so a platform without AF_UNIX
        # silently falls back to the TCP form.
        use_unix = (os.environ.get("THEANOMPI_TPU_UNIX_SOCKETS") == "1"
                    and rpc.have_af_unix())
        self._socks: list[str | None] = []
        self._procs: list[subprocess.Popen] = []  # guarded_by: self._lock
        self._restarts: dict[int, int] = {}       # guarded_by: self._lock
        for i in range(n_shards):
            port = _free_port()
            self._ports.append(port)
            self._socks.append(
                f"/tmp/tmshard_{os.getpid()}_{i}.sock" if use_unix
                else None)
            self._procs.append(self._spawn(i, port))
        self._wait_ready(ready_timeout_s)
        self._watcher = threading.Thread(
            target=self._watch, daemon=True, name="shard-group-watcher")
        self._watcher.start()

    @property
    def addresses(self) -> list[str]:
        return [f"{rpc.UNIX_PREFIX}{s}" if s else f"{self.host}:{p}"
                for s, p in zip(self._socks, self._ports)]

    @property
    def server_addr(self) -> str:
        """The comma-joined form the launcher/rules consume."""
        return ",".join(self.addresses)

    def _spawn(self, index: int, port: int) -> subprocess.Popen:
        sock = self._socks[index] if self._socks else None
        host = f"{rpc.UNIX_PREFIX}{sock}" if sock else self.host
        cmd = [sys.executable, "-m", "theanompi_tpu.parallel.shards",
               "--host", host, "--port", str(port),
               "--shard-index", str(index)]
        if self.platform:
            cmd += ["--platform", self.platform]
        return subprocess.Popen(cmd, env=dict(os.environ))

    def _wait_ready(self, timeout_s: float) -> None:
        deadline = time.monotonic() + timeout_s
        for i, addr in enumerate(self.addresses):
            while True:
                c, info = None, None
                try:
                    c = ServiceClient(addr)
                    info = c.call("shard_info")
                except Exception:
                    with self._lock:
                        rc = self._procs[i].poll()
                    if rc is not None:
                        self.stop()
                        raise RuntimeError(
                            f"shard {i} died during startup (rc={rc})")
                    if time.monotonic() > deadline:
                        self.stop()
                        raise RuntimeError(
                            f"shard {i} at {addr} never came up "
                            f"within {timeout_s}s")
                    time.sleep(0.3)
                finally:
                    # probe clients must not accumulate: a failed call
                    # would otherwise leak one authenticated
                    # connection per 0.3s retry
                    if c is not None:
                        c.close()
                if info is None:
                    continue
                if info.get("shard") != i:
                    # a stale process squatting on the port: fail
                    # LOUDLY and immediately — retrying would just
                    # convert a mis-wired fleet into a misleading
                    # 'never came up' timeout
                    self.stop()
                    raise RuntimeError(
                        f"address {addr} answered as shard "
                        f"{info.get('shard')!r}, expected shard {i} — "
                        "another process is listening on that port")
                break

    def _watch(self) -> None:
        while not self._stopping.wait(0.5):
            with self._lock:
                procs = list(self._procs)
            for i, proc in enumerate(procs):
                if proc.poll() is None or self._stopping.is_set():
                    continue
                with self._lock:
                    n = self._restarts.get(i, 0)
                    if n >= self.max_restarts:
                        continue  # budget spent: leave the corpse
                    self._restarts[i] = n + 1
                    self._procs[i] = self._spawn(i, self._ports[i])
                print(f"[shards] shard {i} died (rc={proc.returncode}); "
                      f"relaunched on port {self._ports[i]} "
                      f"({n + 1}/{self.max_restarts})",
                      file=sys.stderr, flush=True)
                monitor.inc("service/shard_restarts_total", shard=i)

    def restart_counts(self) -> dict[int, int]:
        with self._lock:
            return dict(self._restarts)

    def kill_shard(self, index: int) -> None:
        """Hard-kill one shard (fault-matrix smoke); the watcher
        relaunches it within a poll interval if budget remains."""
        with self._lock:
            self._procs[index].kill()

    def wait_restarted(self, index: int, timeout_s: float = 60.0) -> None:
        """Block until shard ``index`` answers pings again."""
        deadline = time.monotonic() + timeout_s
        addr = self.addresses[index]
        while True:
            c = None
            try:
                c = ServiceClient(addr)
                c.call("shard_info")
                return
            except Exception:
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"shard {index} did not come back within "
                        f"{timeout_s}s")
                time.sleep(0.3)
            finally:
                if c is not None:
                    c.close()

    def stop(self) -> None:
        self._stopping.set()
        if getattr(self, "_watcher", None) is not None \
                and self._watcher.is_alive():
            self._watcher.join(timeout=5)
        with self._lock:
            procs = list(self._procs)
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=5)
        for s in getattr(self, "_socks", []):
            if s is not None:  # a hard-killed shard leaves its file
                try:
                    os.unlink(s)
                except OSError:
                    pass

    def __enter__(self) -> "ShardProcessGroup":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="theanompi-tpu sharded parameter service — one "
                    "shard of a partitioned center (docs/DESIGN.md)")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--shard-index", type=int, default=0)
    ap.add_argument("--platform", default=None,
                    help="jax platform for the shard's merge arithmetic "
                         "(e.g. 'cpu' so the shard never claims a chip)")
    args = ap.parse_args(argv)
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    print(f"[shards] shard {args.shard_index} listening on "
          f"{args.host}:{args.port}", flush=True)
    # same telemetry posture as a standalone tmserver: request-driven
    # progress, no stall watchdog, a per-process file suffix so K
    # shards sharing a monitor dir never clobber each other
    with monitor.session(stall_after=float("inf"),
                         name=f"shard{args.shard_index}_{os.getpid()}"):
        monitor.progress(phase="serving")
        serve_shard(args.host, args.port, args.shard_index)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
