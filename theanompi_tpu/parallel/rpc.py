"""One event plane — the selector-driven RPC substrate (ISSUE 11).

Every serving plane in this repo — param service, shard fleet, ingest
readers, eval serving, decode — used to run the same
thread-per-connection serve loop.  PR 9 measured exactly where that
dies: N recv threads in one process collapse ~1000→40 pulls/s at N=12
(the GIL convoy: every IO wake pays the 5 ms switch interval against
whichever thread holds the GIL), and arXiv:1810.11112's
characterization says communication *concurrency*, not bandwidth, is
what dominates at scale.  A host that should front a million
connections cannot spend a thread (and a convoy ticket) per socket.

This module replaces all five loops with ONE substrate, two
interchangeable implementations behind the same :func:`serve`:

* ``loop='selector'`` (default) — **the event plane**: one IO thread
  owns a ``selectors`` loop over every established connection (accept,
  frame reassembly, scatter-gather writes); blocking work
  (``service.handle``) runs on small per-op executor pools (a default
  pool sized by the plane's own admission bound, plus a tiny control
  pool so latency-critical ops — fence freeze/release, ping — can
  never starve behind parked mutations).  Single-digit threads per
  process at rest, independent of connection count.
* ``loop='threaded'`` — the legacy thread-per-connection loop, kept
  verbatim-compatible for the migration window so every pin can run on
  both substrates (``THEANOMPI_TPU_RPC_LOOP``).

What is deliberately byte-compatible with the old plane (so every
existing client keeps working unmodified):

* the ``multiprocessing.connection`` chunk framing (4-byte ``!i``
  length prefix, ``-1`` + ``!Q`` for >2 GiB chunks);
* the HMAC challenge/response handshake — reimplemented here only to
  add a **deadline**: a client that connects and never answers the
  challenge is reaped after ``THEANOMPI_TPU_RPC_HANDSHAKE_TIMEOUT_S``
  instead of leaking a handler (threaded) or an fd (selector) until
  shutdown, on BOTH loops identically;
* wire-v2 negotiation (``wire.accept_hello``), typed ``("err", ...)``
  replies, the ``shutdown`` op, and per-connection serial request
  order (replies are FIFO per stream, which the ingest client's
  pipelined fetch and the gossip at-most-once discipline both rely
  on).

What is new:

* **connection multiplexing** — a client may add ``"mux": True`` to
  its wire hello; the selector loop then treats the connection as many
  logical streams, each chunk preceded by a 4-byte stream-id envelope
  chunk.  Replies carry the same envelope, streams are served
  concurrently (requests are serial only *within* a stream), and one
  socket + ONE client-side reader thread replaces N sockets + N
  convoying recv threads (:class:`MuxConnection`).
* **scatter-gather zero-copy writes** — a v2 reply is queued as its
  ``encode_frame`` memoryviews and written with ``socket.sendmsg``
  (length prefixes and array buffers as separate iovecs): the arrays'
  bytes go from the store's numpy buffers to the kernel with no
  coalescing copy.
* **backpressure-aware write queues** — per-connection bounded byte
  budget; a worker whose reply would overflow it blocks (bounded) until
  the socket drains, so one slow client back-pressures its own
  requests instead of ballooning server memory.

Per-plane metric names and fault sites stay where they were: the
caller passes an :class:`RpcHooks` whose literal emissions live in the
plane's own module (``service/*`` vs ``serving/*``), which keeps the
TM403/404 docs-coverage lint honest.  This module's own telemetry is
the ``rpc/*`` family (docs/OBSERVABILITY.md "RPC substrate").
"""

from __future__ import annotations

import hmac as _hmac
import os
import pickle
import selectors
import socket
import struct
import threading
import time
from collections import deque
from typing import Any, Callable

from theanompi_tpu import monitor
from theanompi_tpu.analysis.lockgraph import make_condition, make_lock
from theanompi_tpu.monitor import trace as _trace
from theanompi_tpu.parallel import shm, wire

__all__ = [
    "serve", "RpcHooks", "MuxConnection", "HandshakeTimeout",
    "wait_readable", "set_nodelay", "unix_path", "have_af_unix",
]

# -- address forms ----------------------------------------------------------

#: same-host fleets may listen on an AF_UNIX socket instead of TCP
#: loopback: ``serve(host="unix:/path")`` and the same string as a
#: client address.  Platforms without AF_UNIX silently fall back to
#: TCP (``127.0.0.1`` + the given port) — the degradation contract
#: every lane here follows.
UNIX_PREFIX = "unix:"


def unix_path(host) -> str | None:
    """The socket path of a ``unix:/path`` address form, or None for
    every TCP form."""
    if isinstance(host, str) and host.startswith(UNIX_PREFIX):
        return host[len(UNIX_PREFIX):]
    return None


def have_af_unix() -> bool:
    return hasattr(socket, "AF_UNIX")

# -- knobs ------------------------------------------------------------------

#: handshake deadline (both loops): a connect that has not completed
#: the HMAC challenge/response within this window is reaped — an
#: un-negotiated dropped connect must not hold a handler/fd until
#: shutdown
def _handshake_timeout_s() -> float:
    return float(os.environ.get(
        "THEANOMPI_TPU_RPC_HANDSHAKE_TIMEOUT_S", "10"))


def _default_loop() -> str:
    loop = os.environ.get("THEANOMPI_TPU_RPC_LOOP", "selector")
    if loop not in ("selector", "threaded"):
        raise ValueError(
            f"THEANOMPI_TPU_RPC_LOOP must be 'selector' or 'threaded', "
            f"got {loop!r}")
    return loop


def _default_workers() -> int:
    """Default executor width.  The right bound is the plane's own
    admission bound (callers pass it); this fallback covers planes
    without one.  Threads spawn on demand and this is a CAP, not a
    pre-spawn."""
    return int(os.environ.get("THEANOMPI_TPU_RPC_WORKERS", "16"))


#: per-connection write-queue budget: a worker blocks (bounded) once a
#: client's unsent replies exceed this many bytes
_WRITEQ_BYTES = int(os.environ.get(
    "THEANOMPI_TPU_RPC_WRITEQ_BYTES", str(256 << 20)))
#: how long a reply may stay blocked on a full write queue before the
#: connection is declared dead (a stalled client must not park a
#: worker forever)
_WRITEQ_TIMEOUT_S = float(os.environ.get(
    "THEANOMPI_TPU_RPC_WRITEQ_TIMEOUT_S", "60"))

#: chunk ceilings mirror the wire module's decoder ceilings
_MAX_CHUNK = wire.MAX_BUFFER_BYTES

#: iovecs per sendmsg call (IOV_MAX is >=1024 on Linux; stay well under)
_SENDMSG_IOVS = 64

_RECV_SIZE = 1 << 18

# multiprocessing.connection chunk framing
_LEN = struct.Struct("!i")
_LEN8 = struct.Struct("!Q")
_ENVELOPE = struct.Struct(">I")

# the stdlib handshake protocol constants (multiprocessing.connection;
# stable across 3.x — re-declared defensively so a rename upstream
# cannot silently change our wire format)
try:  # pragma: no cover - import paths
    from multiprocessing.connection import (  # type: ignore
        CHALLENGE, FAILURE, MESSAGE_LENGTH, WELCOME,
    )
except ImportError:  # pragma: no cover
    CHALLENGE, WELCOME = b"#CHALLENGE#", b"#WELCOME#"
    FAILURE, MESSAGE_LENGTH = b"#FAILURE#", 20

from multiprocessing import AuthenticationError


class HandshakeTimeout(ConnectionError):
    """A peer connected but did not complete the HMAC handshake within
    the deadline — reaped, never served."""


# ---------------------------------------------------------------------------
# Plane hooks: per-plane metric names / fault sites stay in plane code
# ---------------------------------------------------------------------------


class RpcHooks:
    """Telemetry + fault seams a serving plane plugs into the shared
    loop.  Default: no-op (the substrate itself still emits ``rpc/*``).
    Concrete hooks live next to their metric-catalog rows
    (``parallel/service.py``, ``serving/server.py``) so every emission
    keeps a literal series name the TM403/404 lint can see."""

    #: plane tag for the substrate's own rpc/* series labels
    plane = "rpc"

    def on_connect(self) -> None:
        """An authenticated connection was established."""

    def on_disconnect(self) -> None:
        """A counted connection went away (incl. abrupt RST)."""

    def on_request(self, op: str, ms: float) -> None:
        """One request handled AND its reply fully serialized."""

    def on_error(self, op: str) -> None:
        """A request answered with a typed ``err`` reply (service
        exception, malformed request, wire decode failure, or a reply
        that failed to serialize — ``op`` names which)."""

    def on_negotiate(self, opts: wire.WireOptions) -> None:
        """A connection switched to wire v2."""

    def fire(self, op: str) -> None:
        """Per-request fault site (may raise/delay per the plan)."""


# ---------------------------------------------------------------------------
# HMAC handshake with a deadline (shared by both loops)
# ---------------------------------------------------------------------------


def set_nodelay(conn_or_sock) -> None:
    """Disable Nagle on a socket or a ``Connection``: every message
    here is a complete request or reply, and batching them behind
    delayed ACKs only adds tail latency.  Best-effort (non-TCP fds)."""
    try:
        fileno = conn_or_sock.fileno()
        s = socket.socket(fileno=os.dup(fileno))
        try:
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        finally:
            s.close()
    except OSError:
        pass


def _conn_recv_deadline(conn, deadline: float, maxlength: int) -> bytes:
    remaining = deadline - time.monotonic()
    if remaining <= 0 or not conn.poll(remaining):
        raise HandshakeTimeout(
            "peer did not answer the HMAC handshake within the "
            f"{_handshake_timeout_s():.0f}s deadline")
    return conn.recv_bytes(maxlength)


def handshake_server_conn(conn, authkey: bytes, timeout_s: float) -> None:
    """Server side of the mutual HMAC handshake over a ``Connection``
    (threaded loop), byte-identical to what ``Listener.accept`` does —
    plus the deadline.  Raises :class:`HandshakeTimeout` or
    ``AuthenticationError``; the caller reaps the connection."""
    deadline = time.monotonic() + timeout_s
    message = os.urandom(MESSAGE_LENGTH)
    conn.send_bytes(CHALLENGE + message)
    digest = _hmac.new(authkey, message, "md5").digest()
    response = _conn_recv_deadline(conn, deadline, 256)
    if not _hmac.compare_digest(response, digest):
        conn.send_bytes(FAILURE)
        raise AuthenticationError("digest received was wrong")
    conn.send_bytes(WELCOME)
    # mutual: now answer the client's challenge
    message = _conn_recv_deadline(conn, deadline, 256)
    if not message.startswith(CHALLENGE):
        raise AuthenticationError(f"message = {message!r}")
    digest = _hmac.new(authkey, message[len(CHALLENGE):], "md5").digest()
    conn.send_bytes(digest)
    response = _conn_recv_deadline(conn, deadline, 256)
    if response != WELCOME:
        raise AuthenticationError("digest sent was rejected")


def _sock_recv_exact(sock: socket.socket, n: int,
                     deadline: float) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise HandshakeTimeout(
                "peer did not answer the HMAC handshake within the "
                f"{_handshake_timeout_s():.0f}s deadline")
        sock.settimeout(remaining)
        try:
            chunk = sock.recv(n - len(buf))
        except socket.timeout:
            raise HandshakeTimeout(
                "peer did not answer the HMAC handshake within the "
                f"{_handshake_timeout_s():.0f}s deadline") from None
        if not chunk:
            raise EOFError("peer closed during handshake")
        buf += chunk
    return bytes(buf)


def _sock_recv_chunk(sock: socket.socket, deadline: float,
                     maxlength: int) -> bytes:
    (size,) = _LEN.unpack(_sock_recv_exact(sock, 4, deadline))
    if size == -1:
        (size,) = _LEN8.unpack(_sock_recv_exact(sock, 8, deadline))
    if size < 0 or size > maxlength:
        raise AuthenticationError(f"bad handshake message length {size}")
    return _sock_recv_exact(sock, size, deadline)


def _sock_send_chunk(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload)) + payload)


def handshake_server_sock(sock: socket.socket, authkey: bytes,
                          timeout_s: float) -> None:
    """Server handshake over a raw socket (selector loop)."""
    deadline = time.monotonic() + timeout_s
    message = os.urandom(MESSAGE_LENGTH)
    _sock_send_chunk(sock, CHALLENGE + message)
    digest = _hmac.new(authkey, message, "md5").digest()
    response = _sock_recv_chunk(sock, deadline, 256)
    if not _hmac.compare_digest(response, digest):
        _sock_send_chunk(sock, FAILURE)
        raise AuthenticationError("digest received was wrong")
    _sock_send_chunk(sock, WELCOME)
    message = _sock_recv_chunk(sock, deadline, 256)
    if not message.startswith(CHALLENGE):
        raise AuthenticationError(f"message = {message!r}")
    digest = _hmac.new(authkey, message[len(CHALLENGE):], "md5").digest()
    _sock_send_chunk(sock, digest)
    response = _sock_recv_chunk(sock, deadline, 256)
    if response != WELCOME:
        raise AuthenticationError("digest sent was rejected")


# ---------------------------------------------------------------------------
# A tiny elastic daemon pool (the per-op executors)
# ---------------------------------------------------------------------------


class _DaemonPool:
    """Spawn-on-demand daemon worker pool.

    ``concurrent.futures.ThreadPoolExecutor`` threads are non-daemon:
    a handler legitimately parked in a blocking service op (a
    freeze-blocked shard mutation) would wedge interpreter exit, which
    is exactly the failure the old loop's daemon handler threads
    avoided.  This pool keeps that property: daemon threads, created
    only when every existing worker is busy, capped at ``max_workers``
    (the plane's admission bound — in-flight work bounds thread count,
    connection count never does)."""

    def __init__(self, name: str, max_workers: int):
        if max_workers < 1:
            raise ValueError(f"need >= 1 worker, got {max_workers}")
        self.name = name
        self._max = int(max_workers)
        self._lock = make_lock(f"_DaemonPool.{name}")
        self._cond = make_condition(self._lock, f"_DaemonPool.{name}.cond")
        self._tasks: deque = deque()  # guarded_by: self._lock
        self._idle = 0                # guarded_by: self._lock
        self._n = 0                   # guarded_by: self._lock
        self._spawned = 0             # guarded_by: self._lock
        self._closed = False          # guarded_by: self._lock

    def submit(self, fn: Callable[[], None]) -> None:
        with self._cond:
            if self._closed:
                raise RuntimeError(f"pool {self.name} is shut down")
            self._tasks.append(fn)
            if self._idle > 0:
                self._cond.notify()
                return
            if self._n < self._max:
                self._n += 1
                self._spawned += 1
                t = threading.Thread(
                    target=self._worker, daemon=True,
                    name=f"{self.name}-{self._spawned}")
                t.start()
            # else: every worker busy and at cap — the task waits its
            # turn (the queue is bounded by in-flight streams, each of
            # which has at most one request here)

    def _worker(self) -> None:
        while True:
            with self._cond:
                while not self._tasks and not self._closed:
                    self._idle += 1
                    self._cond.wait()
                    self._idle -= 1
                if self._closed:
                    self._n -= 1
                    return
                fn = self._tasks.popleft()
            try:
                fn()
            except Exception as e:  # a task must never kill a worker
                print(f"[rpc] {self.name} task failed: "
                      f"{type(e).__name__}: {e}", flush=True)

    def shutdown(self) -> None:
        """Stop accepting work and wake every idle worker to exit.
        Pending tasks are dropped (their connections are closing);
        busy workers exit after their current task."""
        with self._cond:
            self._closed = True
            self._tasks.clear()
            self._cond.notify_all()

    def join(self, timeout_s: float = 5.0) -> None:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if self._n == 0:
                    return
            time.sleep(0.01)


def _control_ops(service) -> frozenset:
    """Ops routed to the control pool: latency-critical / never-block
    ops that must not starve behind parked mutations (the shard fence's
    freeze/release while the default pool holds freeze-blocked
    exchanges — the distributed form of the dedicated-fence-connection
    rationale in docs/DESIGN.md)."""
    return frozenset({"ping"}) | frozenset(
        getattr(service, "RPC_CONTROL_OPS", ()))


# ---------------------------------------------------------------------------
# The threaded loop (legacy substrate, migration window)
# ---------------------------------------------------------------------------


def _serve_threaded(service, host: str, port: int,
                    ready_event: threading.Event | None,
                    stop_event: threading.Event,
                    authkey: bytes, hooks: RpcHooks,
                    backlog: int = 64) -> None:
    """One handler thread per connection — the PR-9-era loop, with the
    handshake moved OFF the accept thread and under the deadline (the
    old in-accept handshake let one silent client wedge all accepts,
    and an un-negotiated dropped connect leaked its handler)."""
    from multiprocessing.connection import Connection, Listener

    path = unix_path(host)
    if path is not None and not have_af_unix():  # pragma: no cover
        path, host = None, "127.0.0.1"  # silent TCP fallback
    if path is not None:
        try:  # a stale socket file from a killed predecessor
            os.unlink(path)
        except OSError:
            pass
        listener = Listener(path, "AF_UNIX", backlog=backlog)
    else:
        listener = Listener((host, port), backlog=backlog)  # auth: below
    if ready_event is not None:
        ready_event.set()
    conns: set[Connection] = set()
    conns_lock = make_lock("rpc._serve_threaded.conns_lock")

    def handle_conn(conn: Connection):
        try:
            handshake_server_conn(conn, authkey, _handshake_timeout_s())
        except (HandshakeTimeout, AuthenticationError, EOFError,
                OSError):
            monitor.inc("rpc/handshake_reaped_total", plane=hooks.plane,
                        loop="threaded")
            try:
                conn.close()
            except OSError:
                pass
            with conns_lock:
                conns.discard(conn)
            return
        set_nodelay(conn)
        hooks.on_connect()
        monitor.inc("rpc/connections_total", plane=hooks.plane,
                    loop="threaded")
        # per-connection protocol state: None = v1 pickle; a
        # successful wire_hello switches BOTH directions to v2 framing
        wire_opts: wire.WireOptions | None = None
        # trace grant from the hello: only then may the peer send the
        # TRACE_OP context envelope (without it the op falls through to
        # service.handle and earns the ordinary unknown-op error)
        trace_on = False

        def reply(payload, op: str = "reply"):
            """True = sent; 'degraded' = serialize failure converted
            to an err diagnostic (charged to ``op``); False = peer
            gone."""
            try:
                if wire_opts is None:
                    conn.send(payload)
                else:
                    wire.send_msg(conn, payload, wire_opts)
                return True
            except (EOFError, OSError):
                return False
            except Exception as e:
                # reply failed to SERIALIZE/ENCODE (both transports
                # build the full message before any byte hits the
                # wire) — the client must still get a diagnostic
                hooks.on_error(op)
                try:
                    err = ("err", f"{type(e).__name__}: {e}")
                    if wire_opts is None:
                        conn.send(err)
                    else:
                        wire.send_msg(conn, err, wire_opts)
                    return "degraded"
                except Exception:
                    return False

        try:
            while True:
                if wire_opts is None:
                    try:
                        msg = conn.recv()
                    except (EOFError, OSError):
                        return
                    except Exception as e:
                        if isinstance(e, TypeError) and conn.closed:
                            # the shutdown path closed this connection
                            # out from under a blocked recv (the
                            # stdlib reads from a None handle); an
                            # OPEN conn's TypeError is a corrupt
                            # pickle and gets the diagnostic below
                            return
                        hooks.on_error("malformed")
                        if not reply(("err",
                                      f"{type(e).__name__}: {e}")):
                            return
                        continue
                else:
                    try:
                        msg = wire.recv_msg(conn, wire_opts)
                    except wire.WireDecodeError as e:
                        hooks.on_error("wire_decode")
                        ok = reply(("err",
                                    f"{type(e).__name__}: {e}"))
                        if not ok or not getattr(
                                e, "frame_drained", False):
                            return
                        continue
                    except (EOFError, OSError):
                        return
                    except TypeError:
                        if conn.closed:
                            return
                        raise  # a genuine bug — don't mask it
                if not isinstance(msg, tuple) or not msg:
                    hooks.on_error("malformed")
                    if not reply(("err", "malformed request")):
                        return
                    continue
                op, *args = msg
                if op == wire.HELLO_OP:
                    # confirm v2 + options on the CURRENT protocol,
                    # then switch framing.  allow_mux=False: one
                    # handler thread cannot demultiplex — the client
                    # falls back to one socket per stream.  allow_shm:
                    # the finally below closes the lane channel, so
                    # this loop may grant it.
                    try:
                        negotiated, hello_reply, _ = wire.accept_hello(
                            args[0] if args else None, allow_mux=False,
                            allow_shm=True)
                    except wire.WireProtocolError as e:
                        if not reply(("err",
                                      f"{type(e).__name__}: {e}")):
                            return
                        continue
                    if not reply(("ok", hello_reply)):
                        return
                    wire_opts = negotiated
                    trace_on = bool(hello_reply.get("trace"))
                    hooks.on_negotiate(negotiated)
                    continue
                if op == "shutdown":
                    reply(("ok", None))
                    stop_event.set()
                    try:  # unblock accept() so the serve loop exits
                        if path is not None:
                            s = socket.socket(socket.AF_UNIX,
                                              socket.SOCK_STREAM)
                            s.settimeout(2)
                            s.connect(path)
                            s.close()
                        else:
                            socket.create_connection(
                                (host if host != "0.0.0.0"
                                 else "127.0.0.1",
                                 port), timeout=2).close()
                    except OSError:
                        pass
                    return
                ctx = None
                if op == wire.TRACE_OP and trace_on and len(args) >= 2:
                    ctx, op, *args = args
                t0 = time.monotonic()
                try:
                    hooks.fire(op)
                    if ctx is not None:
                        # the span exists only on traced requests, so
                        # the untraced hot path (and its metric stream)
                        # is byte-identical to the pre-trace build
                        with _trace.attach_wire(ctx), \
                                monitor.span("rpc_handle", op=op):
                            result = service.handle(op, *args)
                    else:
                        result = service.handle(op, *args)
                except Exception as e:  # surfaced client-side
                    hooks.on_error(op)
                    if not reply(("err", f"{type(e).__name__}: {e}")):
                        return
                    continue
                sent = reply(("ok", result), op=op)
                if not sent:
                    return  # peer gone; nothing to tell it
                if sent is True:
                    # a degraded (serialize-failed) reply was already
                    # charged as an error — not also a success
                    hooks.on_request(op, (time.monotonic() - t0) * 1e3)
        finally:
            ch = getattr(wire_opts, "shm", None)
            if ch is not None:
                # connection teardown releases every lease whose ack
                # never came back — the lane must not wait out the
                # lease timeout for an orderly disconnect
                ch.close()
            try:
                conn.close()
            except OSError:
                pass
            with conns_lock:
                conns.discard(conn)
            hooks.on_disconnect()

    try:
        with listener:
            while not stop_event.is_set():
                try:
                    conn = listener.accept()
                except OSError:
                    if stop_event.is_set():
                        return
                    raise
                # register BEFORE the handler thread starts: a conn
                # accepted just as shutdown lands must still be in
                # the close sweep
                with conns_lock:
                    conns.add(conn)
                threading.Thread(target=handle_conn, args=(conn,),
                                 daemon=True).start()
    finally:
        # faithful shutdown: drop established connections so an
        # embedded service restart looks like a process restart
        with conns_lock:
            live = list(conns)
        for c in live:
            try:
                c.close()
            except OSError:
                pass
        if path is not None:
            try:
                os.unlink(path)
            except OSError:
                pass


# ---------------------------------------------------------------------------
# The selector loop (the event plane)
# ---------------------------------------------------------------------------


class _ChunkParser:
    """Incremental multiprocessing.connection chunk framing: feed
    bytes, yields complete chunks.  Owned by the IO thread."""

    __slots__ = ("_acc", "_want", "_long")

    def __init__(self):
        self._acc = bytearray()
        self._want = -1  # <0: reading a length prefix
        self._long = False

    def feed(self, data: bytes) -> list[bytes]:
        self._acc += data
        out: list[bytes] = []
        acc = self._acc
        while True:
            if self._want < 0:
                need = 8 if self._long else 4
                if len(acc) < need:
                    break
                if self._long:
                    (size,) = _LEN8.unpack_from(acc)
                    self._long = False
                else:
                    (size,) = _LEN.unpack_from(acc)
                    if size == -1:
                        del acc[:4]
                        self._long = True
                        continue
                del acc[:need]
                if size < 0 or size > _MAX_CHUNK:
                    raise wire.WireDecodeError(
                        f"peer chunk declares {size} bytes "
                        f"(> {_MAX_CHUNK}); closing connection")
                self._want = size
            if len(acc) < self._want:
                break
            out.append(bytes(acc[:self._want]))
            del acc[:self._want]
            self._want = -1
        return out


class _Stream:
    """One logical request/reply stream (stream 0 = an unmuxed
    connection).  Frame-reassembly fields are IO-thread-owned; the
    serial-dispatch fields are shared with workers under the
    connection's stream lock."""

    __slots__ = ("sid", "head", "nbufs", "bufs", "busy", "pending")

    def __init__(self, sid: int):
        self.sid = sid
        self.head: bytes | None = None
        self.nbufs = 0
        self.bufs: list | None = None
        self.busy = False      # guarded_by: conn._slock
        self.pending = deque()  # guarded_by: conn._slock

    def reset_frame(self) -> None:
        self.head, self.nbufs, self.bufs = None, 0, None


class _SelConn:
    """Per-connection state for the selector loop.

    Ownership: frame parsing (``parser``/``streams``/``cur_sid``/
    ``wire_opts``/``mux``) is touched only by the IO thread; the write
    queue and the per-stream dispatch queues are the two seams shared
    with worker threads, each under its own lock.  ``wire_opts`` is
    read by workers when encoding replies — safe because it is written
    exactly once (at hello time) strictly before any request of the
    negotiated protocol can be dispatched."""

    def __init__(self, sock: socket.socket, server: "_SelectorServer"):
        self.sock = sock
        self.fd = sock.fileno()
        self.server = server
        self.parser = _ChunkParser()
        self.wire_opts: wire.WireOptions | None = None
        self.mux = False
        # trace grant — written once at hello (IO thread) strictly
        # before any enveloped request, read by workers: same
        # ordering argument as wire_opts above
        self.trace = False
        self.cur_sid: int | None = None
        self.streams: dict[int, _Stream] = {}
        self.events = selectors.EVENT_READ
        #: the actual send seam: guards ``out`` and the socket write.
        #: Lock order: _outlock -> _wlock (never the reverse).
        self._outlock = make_lock("rpc._SelConn._outlock")
        self.out: deque = deque()   # guarded_by: self._outlock
        self._wlock = make_lock("rpc._SelConn._wlock")
        self._wcond = make_condition(self._wlock,
                                     "rpc._SelConn._wcond")
        self._wq: deque = deque()   # guarded_by: self._wlock
        self._wbytes = 0            # guarded_by: self._wlock
        self._wclosed = False       # guarded_by: self._wlock
        self._slock = make_lock("rpc._SelConn._slock")

    # -- worker-side write API -----------------------------------------

    def enqueue(self, chunks: list, sid: int | None) -> int:
        """Queue one reply message (its chunks become iovecs) and wake
        the IO thread.  Blocks while the connection's unsent bytes
        exceed the budget — the backpressure seam.  Returns the bytes
        queued; raises ``ConnectionError`` if the peer is gone or the
        queue stays full past the deadline."""
        # one envelope per CHUNK (not per message) — the client reader
        # demuxes chunk-by-chunk, exactly mirroring the request side
        items: list = []
        for c in chunks:
            n = c.nbytes if isinstance(c, memoryview) else len(c)
            if sid is not None:
                items.append(_LEN.pack(4) + _ENVELOPE.pack(sid))
            if n > 0x7FFFFFFF:
                items.append(_LEN.pack(-1) + _LEN8.pack(n))
            else:
                items.append(_LEN.pack(n))
            if n:
                items.append(c)
        nbytes = sum(i.nbytes if isinstance(i, memoryview) else len(i)
                     for i in items)
        deadline = time.monotonic() + _WRITEQ_TIMEOUT_S
        with self._wcond:
            stalled = False
            while (self._wbytes + nbytes > _WRITEQ_BYTES
                   and self._wbytes > 0 and not self._wclosed):
                if not stalled:
                    stalled = True
                    monitor.inc("rpc/backpressure_stalls_total",
                                plane=self.server.hooks.plane)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise ConnectionError(
                        "write queue full for "
                        f"{_WRITEQ_TIMEOUT_S:.0f}s (stalled client); "
                        "dropping connection")
                self._wcond.wait(remaining)
            if self._wclosed:
                raise ConnectionError("connection closed")
            self._wq.extend(items)
            self._wbytes += nbytes
        # fast path: send from THIS worker thread when no other thread
        # holds the send seam — the common unloaded case then skips
        # the wake-pipe → select → sendmsg round trip entirely (a
        # measured ~0.4 ms/request on this box).  A held lock or a
        # partial write falls back to the IO thread.
        if self._outlock.acquire(blocking=False):
            try:
                residue = self._send_locked()
            except OSError as e:
                self.server.request_close(self)
                raise ConnectionError(f"send failed: {e}") from e
            finally:
                self._outlock.release()
            if residue:
                self.server.request_flush(self)
        else:
            self.server.request_flush(self)
        return nbytes

    def _send_locked(self) -> bool:  # requires_lock: self._outlock
        """Drain the queue and scatter-gather write as much as the
        socket accepts (``sendmsg`` over the frames' memoryviews — the
        zero-copy path).  Returns True when unsent bytes remain (the
        caller arms EVENT_WRITE via the IO thread).  Raises ``OSError``
        on a dead socket — the caller routes the close."""
        with self._wlock:
            if self._wq:
                self.out.extend(self._wq)
                self._wq.clear()
        out = self.out
        sent_total = 0
        try:
            while out:
                iovs = []
                for item in out:
                    iovs.append(item)
                    if len(iovs) >= _SENDMSG_IOVS:
                        break
                try:
                    n = self.sock.sendmsg(iovs)
                except (BlockingIOError, InterruptedError):
                    break
                sent_total += n
                while n and out:
                    head = out[0]
                    size = (head.nbytes if isinstance(head, memoryview)
                            else len(head))
                    if n >= size:
                        out.popleft()
                        n -= size
                    else:
                        mv = (head if isinstance(head, memoryview)
                              else memoryview(head))
                        out[0] = mv[n:]
                        n = 0
        finally:
            if sent_total:
                self.wrote(sent_total)
        return bool(out)

    def wrote(self, nbytes: int) -> None:
        with self._wcond:
            self._wbytes -= nbytes
            self._wcond.notify_all()

    def close_write(self) -> None:
        with self._wcond:
            self._wclosed = True
            self._wq.clear()
            self._wcond.notify_all()


class _SelectorServer:
    """The event plane: one IO thread (the ``serve`` caller), a
    handshake pool, and the per-op executor pools."""

    def __init__(self, service, host: str, port: int,
                 stop_event: threading.Event, authkey: bytes,
                 hooks: RpcHooks, max_workers: int,
                 backlog: int = 64):
        self.service = service
        self.hooks = hooks
        self.stop_event = stop_event
        self.authkey = authkey
        self._control = _control_ops(service)
        plane = hooks.plane
        self.pool = _DaemonPool(f"rpc-worker-{plane}", max_workers)
        self.ctl_pool = _DaemonPool(f"rpc-ctl-{plane}",
                                    max(2, min(4, max_workers)))
        self.hs_pool = _DaemonPool(f"rpc-hs-{plane}", 8)
        self.sel = selectors.DefaultSelector()
        path = unix_path(host)
        if path is not None and not have_af_unix():  # pragma: no cover
            path, host = None, "127.0.0.1"  # silent TCP fallback
        self._unix_path = path
        if path is not None:
            try:  # a stale socket file from a killed predecessor
                os.unlink(path)
            except OSError:
                pass
            self.listener = socket.socket(socket.AF_UNIX,
                                          socket.SOCK_STREAM)
            self.listener.bind(path)
        else:
            self.listener = socket.socket(socket.AF_INET,
                                          socket.SOCK_STREAM)
            self.listener.setsockopt(socket.SOL_SOCKET,
                                     socket.SO_REUSEADDR, 1)
            self.listener.bind((host, port))
        self.listener.listen(backlog)
        self.listener.setblocking(False)
        self.sel.register(self.listener, selectors.EVENT_READ, "accept")
        # wake pipe: workers/handshakes signal the IO thread
        self._wr, self._ww = os.pipe()
        os.set_blocking(self._wr, False)
        os.set_blocking(self._ww, False)
        self.sel.register(self._wr, selectors.EVENT_READ, "wake")
        self._plock = make_lock("rpc._SelectorServer._plock")
        self._pending_ready: list = []   # guarded_by: self._plock
        self._pending_flush: list = []   # guarded_by: self._plock
        self._pending_close: list = []   # guarded_by: self._plock
        self.conns: dict[int, _SelConn] = {}  # io-thread owned

    # -- cross-thread signalling ---------------------------------------

    def _wake(self) -> None:
        try:
            os.write(self._ww, b"x")
        except (BlockingIOError, OSError):
            pass  # pipe full = a wake is already pending, or closing

    def register_ready(self, sock: socket.socket) -> None:
        with self._plock:
            self._pending_ready.append(sock)
        self._wake()

    def request_flush(self, conn: _SelConn) -> None:
        with self._plock:
            self._pending_flush.append(conn)
        self._wake()

    def request_close(self, conn: _SelConn) -> None:
        with self._plock:
            self._pending_close.append(conn)
        self._wake()

    # -- accept + handshake --------------------------------------------

    def _accept(self) -> None:
        while True:
            try:
                sock, _ = self.listener.accept()
            except (BlockingIOError, OSError):
                return
            try:
                self.hs_pool.submit(
                    lambda s=sock: self._handshake(s))
            except RuntimeError:  # shutting down
                sock.close()
                return

    def _handshake(self, sock: socket.socket) -> None:
        try:
            handshake_server_sock(sock, self.authkey,
                                  _handshake_timeout_s())
        except (HandshakeTimeout, AuthenticationError, EOFError,
                OSError):
            monitor.inc("rpc/handshake_reaped_total",
                        plane=self.hooks.plane, loop="selector")
            try:
                sock.close()
            except OSError:
                pass
            return
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        sock.setblocking(False)
        self.register_ready(sock)

    # -- the IO loop ----------------------------------------------------

    def run(self) -> None:
        try:
            while not self.stop_event.is_set():
                for key, events in self.sel.select(0.25):
                    what = key.data
                    if what == "accept":
                        self._accept()
                    elif what == "wake":
                        self._drain_wake()
                    else:
                        conn = what
                        if events & selectors.EVENT_WRITE:
                            self._flush(conn)
                        if events & selectors.EVENT_READ:
                            self._read(conn)
        finally:
            self._shutdown()

    def _drain_wake(self) -> None:
        try:
            while os.read(self._wr, 4096):
                pass
        except (BlockingIOError, OSError):
            pass
        with self._plock:
            ready, self._pending_ready = self._pending_ready, []
            flush, self._pending_flush = self._pending_flush, []
            close, self._pending_close = self._pending_close, []
        for sock in ready:
            if self.stop_event.is_set():
                sock.close()
                continue
            conn = _SelConn(sock, self)
            self.conns[conn.fd] = conn
            self.sel.register(sock, selectors.EVENT_READ, conn)
            self.hooks.on_connect()
            monitor.inc("rpc/connections_total",
                        plane=self.hooks.plane, loop="selector")
        for conn in flush:
            if conn.fd in self.conns:
                self._flush(conn)
        for conn in close:
            if conn.fd in self.conns:
                self._close_conn(conn)

    def _read(self, conn: _SelConn) -> None:
        try:
            data = conn.sock.recv(_RECV_SIZE)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._close_conn(conn)
            return
        if not data:
            self._close_conn(conn)  # EOF — incl. RST'd mid-frame peers
            return
        try:
            chunks = conn.parser.feed(data)
        except wire.WireDecodeError:
            self._close_conn(conn)
            return
        for chunk in chunks:
            if not self._on_chunk(conn, chunk):
                self._close_conn(conn)
                return

    def _on_chunk(self, conn: _SelConn, chunk: bytes) -> bool:
        """One framed chunk; False = unrecoverable, close."""
        if conn.mux:
            if conn.cur_sid is None:
                if len(chunk) != 4:
                    return False  # envelope desync
                (conn.cur_sid,) = _ENVELOPE.unpack(chunk)
                return True
            sid, conn.cur_sid = conn.cur_sid, None
        else:
            sid = 0
        st = conn.streams.get(sid)
        if st is None:
            st = conn.streams[sid] = _Stream(sid)
            monitor.add_gauge("rpc/open_streams", 1.0,
                              plane=self.hooks.plane)
        if conn.mux and not chunk and st.head is None:
            # zero-length chunk outside a frame = client stream close
            del conn.streams[sid]
            monitor.add_gauge("rpc/open_streams", -1.0,
                              plane=self.hooks.plane)
            return True
        if conn.wire_opts is None:
            try:
                # the legacy v1 protocol IS pickle — same documented
                # authkey-gated trust surface the old loop's
                # Connection.recv() had (docs/DESIGN.md security
                # note); the v2 path decodes with allow_pickle=False
                msg = pickle.loads(chunk)  # lint: ok TM302
            except Exception as e:
                # corrupt/unpicklable v1 request: typed diagnostic
                # instead of silently killing the connection
                self.hooks.on_error("malformed")
                return self._queue_err(conn, st,
                                       f"{type(e).__name__}: {e}")
            return self._dispatch(conn, st, msg)
        if st.head is None:
            try:
                _, nbufs, _ = wire.parse_header(chunk)
            except wire.WireDecodeError as e:
                # unparseable header: following chunks are
                # unidentifiable — same close-the-connection policy
                # as the threaded loop's undrainable frame
                self.hooks.on_error("wire_decode")
                self._queue_err(conn, st, f"{type(e).__name__}: {e}")
                return False
            if nbufs:
                st.head, st.nbufs, st.bufs = chunk, nbufs, []
                return True
            head, bufs = chunk, []
        else:
            st.bufs.append(chunk)
            if len(st.bufs) < st.nbufs:
                return True
            head, bufs = st.head, st.bufs
            st.reset_frame()
        try:
            msg = wire.decode_frame(head, bufs, conn.wire_opts)
        except wire.WireDecodeError as e:
            # every declared buffer was consumed (chunk framing keeps
            # the stream aligned) — the connection survives
            self.hooks.on_error("wire_decode")
            return self._queue_err(conn, st,
                                   f"{type(e).__name__}: {e}")
        wire.account_recv(msg, len(head), sum(len(b) for b in bufs))
        return self._dispatch(conn, st, msg)

    #: sentinel op for a pre-built reply routed through the stream's
    #: serial queue — an error for a PIPELINED bad request must queue
    #: behind the in-flight request's reply, or FIFO-matched clients
    #: (the ingest fetch loop) would pair replies with the wrong pulls
    _REPLY_OP = "__rpc_reply__"

    def _queue_err(self, conn: _SelConn, st: _Stream,
                   diag: str) -> bool:
        return self._submit(conn, st, self._REPLY_OP, ("err", diag))

    def _dispatch(self, conn: _SelConn, st: _Stream, msg) -> bool:
        if not isinstance(msg, tuple) or not msg:
            self.hooks.on_error("malformed")
            # via the stream's serial queue, like every error reply —
            # replying ahead of an in-flight pipelined request would
            # mispair a FIFO-matched client's replies
            return self._queue_err(conn, st, "malformed request")
        op, *args = msg
        if op == wire.HELLO_OP:
            # negotiation runs inline on the IO thread (cheap, and it
            # must be ordered with the framing switch): reply on the
            # CURRENT protocol, then switch.  allow_mux=True — this
            # loop demultiplexes.
            try:
                negotiated, hello_reply, mux = wire.accept_hello(
                    args[0] if args else None, allow_mux=True,
                    allow_shm=True)
            except wire.WireProtocolError as e:
                return self._reply_io(conn, st.sid,
                                      ("err",
                                       f"{type(e).__name__}: {e}"))
            ok = self._reply_io(conn, st.sid, ("ok", hello_reply))
            conn.wire_opts = negotiated
            conn.trace = bool(hello_reply.get("trace"))
            if mux:
                conn.mux = True
                # stream 0 was only the pre-mux channel — retire it
                # (and its gauge count, or every mux grant would leak
                # +1 in rpc/open_streams)
                if conn.streams.pop(0, None) is not None:
                    monitor.add_gauge("rpc/open_streams", -1.0,
                                      plane=self.hooks.plane)
                monitor.inc("rpc/mux_connections_total",
                            plane=self.hooks.plane)
            self.hooks.on_negotiate(negotiated)
            return ok
        if op == "shutdown":
            self._reply_io(conn, st.sid, ("ok", None))
            self._flush(conn)
            self.stop_event.set()
            return True
        ctx = None
        if op == wire.TRACE_OP and conn.trace and len(args) >= 2:
            # caller's trace context rides as an envelope; only
            # unwrapped when the hello granted it (otherwise the op
            # falls through to the service's unknown-op error)
            ctx, op, *args = args
        return self._submit(conn, st, op, args, ctx)

    def _submit(self, conn: _SelConn, st: _Stream, op, args,
                ctx=None) -> bool:
        with conn._slock:
            if st.busy:
                st.pending.append((op, args, ctx))
                return True
            st.busy = True
        pool = self.ctl_pool if op in self._control else self.pool
        try:
            pool.submit(
                lambda: self._run_stream(conn, st, op, args, ctx))
        except RuntimeError:  # shutting down
            return False
        return True

    # -- worker side ------------------------------------------------------

    def _run_stream(self, conn: _SelConn, st: _Stream, op, args,
                    ctx=None) -> None:
        """Execute requests of ONE stream serially (replies stay FIFO
        per stream; streams of one connection run concurrently)."""
        while True:
            if op == self._REPLY_OP:
                self._reply(conn, st.sid, args)  # pre-built diagnostic
            else:
                self._run_one(conn, st.sid, op, args, ctx)
            with conn._slock:
                if st.pending:
                    op, args, ctx = st.pending.popleft()
                    continue
                st.busy = False
                return

    def _run_one(self, conn: _SelConn, sid: int, op, args,
                 ctx=None) -> None:
        t0 = time.monotonic()
        try:
            self.hooks.fire(op)
            if ctx is not None:
                with _trace.attach_wire(ctx), \
                        monitor.span("rpc_handle", op=op):
                    result = self.service.handle(op, *args)
            else:
                with monitor.span("rpc_handle", op=op):
                    result = self.service.handle(op, *args)
        except Exception as e:  # surfaced client-side
            self.hooks.on_error(op)
            self._reply(conn, sid, ("err", f"{type(e).__name__}: {e}"))
            return
        sent = self._reply(conn, sid, ("ok", result), op=op)
        if sent is True:
            self.hooks.on_request(op, (time.monotonic() - t0) * 1e3)

    def _reply(self, conn: _SelConn, sid: int, payload,
               op: str = "reply"):
        """Encode + enqueue one reply.  True = queued; 'degraded' = a
        serialize/encode failure converted to an err diagnostic
        (charged to ``op``); False = peer gone."""
        try:
            chunks, stats = self._encode(conn, payload)
        except Exception as e:
            self.hooks.on_error(op)
            try:
                chunks, stats = self._encode(
                    conn, ("err", f"{type(e).__name__}: {e}"))
            except Exception:
                self.request_close(conn)
                return False
            try:
                conn.enqueue(chunks, sid if conn.mux else None)
            except ConnectionError:
                self.request_close(conn)
                return False
            return "degraded"
        try:
            conn.enqueue(chunks, sid if conn.mux else None)
        except ConnectionError:
            self.request_close(conn)
            return False
        if stats is not None:
            wire.account_send(stats)
        return True

    def _encode(self, conn: _SelConn, payload):
        if conn.wire_opts is None:
            return [pickle.dumps(payload)], None
        head, bufs, stats = wire.encode_frame(payload, conn.wire_opts)
        return [head, *bufs], stats

    def _reply_io(self, conn: _SelConn, sid: int, payload) -> bool:
        """Reply from the IO thread (hello/shutdown/decode errors) —
        must never block on backpressure, so it bypasses the budget
        wait (these replies are tiny)."""
        try:
            chunks, _ = self._encode(conn, payload)
        except Exception:
            return False
        items: list = []
        for c in chunks:
            n = c.nbytes if isinstance(c, memoryview) else len(c)
            if conn.mux:
                items.append(_LEN.pack(4) + _ENVELOPE.pack(sid))
            items.append(_LEN.pack(n) if n <= 0x7FFFFFFF
                         else _LEN.pack(-1) + _LEN8.pack(n))
            if n:
                items.append(c)
        # count the bytes into the budget (no blocking — the IO thread
        # must never stall — but _send_locked's wrote() decrements by
        # everything sent, so uncounted items would drive the budget
        # negative and quietly disable backpressure)
        nbytes = sum(i.nbytes if isinstance(i, memoryview) else len(i)
                     for i in items)
        with conn._wcond:
            if conn._wclosed:
                return False
            conn._wbytes += nbytes
        with conn._outlock:
            conn.out.extend(items)
        self._flush(conn)
        return self.conns.get(conn.fd) is conn

    # -- write path -------------------------------------------------------

    def _flush(self, conn: _SelConn) -> None:
        """IO-thread write: drain + send, then arm/disarm EVENT_WRITE
        for whatever the socket would not take."""
        try:
            with conn._outlock:
                residue = conn._send_locked()
        except OSError:
            self._close_conn(conn)
            return
        want = selectors.EVENT_READ
        if residue:
            want |= selectors.EVENT_WRITE
        if want != conn.events and self.conns.get(conn.fd) is conn:
            conn.events = want
            self.sel.modify(conn.sock, want, conn)

    # -- teardown ---------------------------------------------------------

    def _close_conn(self, conn: _SelConn) -> None:
        # identity check, not just fd membership: a deferred
        # request_close can land after this conn died AND a new
        # connection reused its fd number — tearing down the
        # newcomer would zombie it (unflushable, double-decremented
        # gauge, leaked selector entry)
        if self.conns.get(conn.fd) is not conn:
            return
        del self.conns[conn.fd]
        try:
            self.sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        conn.close_write()
        try:
            conn.sock.close()
        except OSError:
            pass
        n_streams = len(conn.streams)
        conn.streams.clear()
        if n_streams:
            monitor.add_gauge("rpc/open_streams", -float(n_streams),
                              plane=self.hooks.plane)
        ch = getattr(conn.wire_opts, "shm", None)
        if ch is not None:
            # release every lease this connection's acks never covered
            # (lane teardown contract — same as the threaded loop)
            ch.close()
        self.hooks.on_disconnect()

    def _shutdown(self) -> None:
        try:
            self.sel.unregister(self.listener)
        except (KeyError, ValueError):
            pass
        self.listener.close()
        if self._unix_path is not None:
            try:
                os.unlink(self._unix_path)
            except OSError:
                pass
        for conn in list(self.conns.values()):
            self._close_conn(conn)
        for pool in (self.pool, self.ctl_pool, self.hs_pool):
            pool.shutdown()
        for pool in (self.pool, self.ctl_pool, self.hs_pool):
            pool.join(timeout_s=2.0)
        try:
            self.sel.unregister(self._wr)
        except (KeyError, ValueError):
            pass
        os.close(self._wr)
        os.close(self._ww)
        self.sel.close()


# ---------------------------------------------------------------------------
# The one serve() every plane calls
# ---------------------------------------------------------------------------


def serve(service, host: str = "0.0.0.0", port: int = 0, *,
          ready_event: threading.Event | None = None,
          stop_event: threading.Event | None = None,
          authkey: bytes,
          hooks: RpcHooks | None = None,
          loop: str | None = None,
          max_workers: int | None = None,
          backlog: int = 64) -> None:
    """Run ``service`` (anything with ``handle(op, *args)``) behind the
    RPC substrate until ``stop_event`` (or a ``shutdown`` op).

    ``host`` may be the ``unix:/path`` address form: the listener
    binds an AF_UNIX socket at ``/path`` (``port`` ignored) and the
    same string works as a client address everywhere a ``host:port``
    does.  Platforms without AF_UNIX silently fall back to TCP
    loopback; Nagle never applies to unix sockets, so the
    TCP_NODELAY latency contract is preserved by construction.

    ``loop`` picks the substrate (``THEANOMPI_TPU_RPC_LOOP``, default
    ``selector``).  ``max_workers`` caps the default executor pool —
    pass the plane's own admission bound (serving queue, ingest
    max_inflight) so in-flight work, never connection count, bounds
    thread count."""
    if stop_event is None:
        stop_event = threading.Event()  # so the shutdown op works
    hooks = hooks or RpcHooks()
    loop = loop or _default_loop()
    if loop == "threaded":
        _serve_threaded(service, host, port, ready_event, stop_event,
                        authkey, hooks, backlog=backlog)
        return
    server = _SelectorServer(
        service, host, port, stop_event, authkey, hooks,
        max_workers=(max_workers if max_workers is not None
                     else _default_workers()),
        backlog=backlog)
    if ready_event is not None:
        ready_event.set()
    server.run()


# ---------------------------------------------------------------------------
# Client side: multiplexed transport (many streams, one socket)
# ---------------------------------------------------------------------------


class _ChunkQueue:
    """Inbound chunk buffer for one client stream (reader thread
    produces, the stream's user consumes)."""

    def __init__(self):
        self._lock = make_lock("rpc._ChunkQueue._lock")
        self._cond = make_condition(self._lock,
                                    "rpc._ChunkQueue._cond")
        self._items: deque = deque()          # guarded_by: self._lock
        self._err: BaseException | None = None  # guarded_by: self._lock

    def put(self, chunk: bytes) -> None:
        with self._cond:
            self._items.append(chunk)
            self._cond.notify_all()

    def put_err(self, err: BaseException) -> None:
        with self._cond:
            if self._err is None:
                self._err = err
            self._cond.notify_all()

    def poll(self, timeout: float | None = 0.0) -> bool:
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._cond:
            while True:
                if self._items:
                    return True
                if self._err is not None:
                    return True  # the recv will raise
                if deadline is None:
                    self._cond.wait()
                    continue
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)

    def get(self) -> bytes:
        with self._cond:
            while not self._items:
                if self._err is not None:
                    raise self._err
                self._cond.wait()
            return self._items.popleft()


class MuxStream:
    """Connection-like view of one logical stream on a
    :class:`MuxConnection` — the subset ``ServiceClient`` and
    ``wire.send_msg``/``recv_msg`` use (``send``/``recv``/
    ``send_bytes``/``recv_bytes``/``poll``/``close``)."""

    def __init__(self, transport: "MuxConnection", sid: int,
                 q: _ChunkQueue, gen: int):
        self._transport = transport
        self.sid = sid
        self._q = q
        self._gen = gen
        self.closed = False

    def send_bytes(self, buf) -> None:
        self._transport._send(self.sid, buf, self._gen)

    def send(self, obj) -> None:
        self.send_bytes(pickle.dumps(obj, protocol=2))

    def recv_bytes(self, maxlength: int | None = None) -> bytes:
        chunk = self._q.get()
        if maxlength is not None and len(chunk) > maxlength:
            raise OSError("bad message length")
        return chunk

    def recv(self):
        # client-side decode of a reply from the server this client
        # authenticated to — the same trust the stdlib Connection.recv
        # path has always had; mux data traffic itself is v2-framed
        return pickle.loads(self.recv_bytes())  # lint: ok TM302

    def poll(self, timeout: float | None = 0.0) -> bool:
        return self._q.poll(timeout)

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            self._transport._close_stream(self.sid, self._gen)
            self._q.put_err(EOFError("stream closed"))


class MuxConnection:
    """Client transport: ONE authenticated socket + ONE reader thread
    carrying many logical streams (the GIL-convoy fix on the client
    side — N convoying recv threads become one select-free reader).

    ``connect_stream()`` hands out Connection-like streams; pass the
    transport to ``ServiceClient(..., transport=...)`` and K clients
    share the socket.  Against a server that does not grant mux (the
    threaded loop, an old tmserver) every ``connect_stream`` silently
    falls back to a dedicated authenticated socket — same behavior as
    today, so callers never need to know which substrate answered."""

    def __init__(self, address, authkey: bytes | None = None,
                 wire_opts: wire.WireOptions | None = None):
        if isinstance(address, str):
            p = unix_path(address)
            if p is not None:
                # a str address IS the AF_UNIX form the stdlib
                # Client/Listener understand
                address = p
            else:
                host, _, port = address.rpartition(":")
                address = (host or "127.0.0.1", int(port))
        self.address = address
        if authkey is None:
            from theanompi_tpu.parallel.service import _authkey

            authkey = _authkey()
        self._authkey = authkey
        self._want = (wire_opts if wire_opts is not None
                      else wire.WireOptions.from_env())
        self._lock = make_lock("rpc.MuxConnection._lock")
        #: write-interleave lock: one (envelope, chunk) pair at a time
        self._wlock = make_lock("rpc.MuxConnection._wlock")
        self._conn = None           # guarded_by: self._lock
        self._mux: bool | None = None  # guarded_by: self._lock
        self._wire: wire.WireOptions | None = None  # guarded_by: self._lock
        self._trace = False         # guarded_by: self._lock
        #: offer the shared-memory lane on (re)connect; flipped off by
        #: disable_shm() after a typed refusal, and every stream of
        #: this transport reconnects in-band
        self._shm_on = True         # guarded_by: self._lock
        self._streams: dict[int, _ChunkQueue] = {}  # guarded_by: self._lock
        self._next_sid = 1          # guarded_by: self._lock
        self._gen = 0               # guarded_by: self._lock
        self._closed = False        # guarded_by: self._lock
        with self._lock:
            self._connect_locked()

    # -- connection management -----------------------------------------

    def _connect_locked(self) -> None:  # requires_lock: self._lock
        from multiprocessing.connection import Client

        conn = Client(self.address, authkey=self._authkey)
        set_nodelay(conn)
        offer = shm.client_offer() if self._shm_on else None
        try:
            conn.send((wire.HELLO_OP,
                       dict(wire.hello_payload(self._want,
                                               shm_offer=offer),
                            mux=True)))
            status, payload = conn.recv()
        except Exception:
            conn.close()
            raise
        granted = (status == "ok" and isinstance(payload, dict)
                   and payload.get("version") == wire.WIRE_VERSION
                   and payload.get("mux"))
        if not granted:
            # dedicated-socket fallback: this probe connection is
            # already v2-switched server-side with no stream to own
            # it — drop it; connect_stream opens plain sockets
            conn.close()
            self._mux = False
            self._conn = None
            self._wire = None
            return
        self._mux = True
        self._conn = conn
        self._wire = wire.WireOptions(
            compression=payload.get("compression", "none"),
            dtype=payload.get("dtype", "f32"),
            allow_pickle=self._want.allow_pickle,
            shm=shm.client_channel(offer, payload))
        # the shared hello negotiated for every stream on this socket;
        # ServiceClient reads it when it skips its own hello
        self._trace = bool(payload.get("trace"))
        self._gen += 1
        threading.Thread(
            target=self._read_loop, args=(conn, self._gen),
            daemon=True,
            name=(f"rpc-mux-reader-"
                  f"{self.address[1] if isinstance(self.address, tuple) else 'unix'}"
                  f"-g{self._gen}"),
        ).start()

    @property
    def mux(self) -> bool:
        with self._lock:
            return bool(self._mux)

    @property
    def trace(self) -> bool:
        """Whether the shared hello granted trace propagation."""
        with self._lock:
            return self._trace

    def connect_stream(self):
        """-> (conn-like, negotiated WireOptions | None).

        Mux mode: a new logical stream + the connection's negotiated
        options (the caller skips its own hello).  Fallback mode: a
        fresh dedicated authenticated socket and ``None`` (the caller
        negotiates as it always did).  A dead mux transport is
        re-established here — the reconnect seam ``ServiceClient``'s
        retry loop drives."""
        with self._lock:
            if self._closed:
                raise ConnectionError("transport closed")
            if self._mux and self._conn is None:
                # dead transport: re-establish (a server restart may
                # also downgrade us to the non-mux fallback below)
                self._connect_locked()
            if not self._mux:
                from multiprocessing.connection import Client

                conn = Client(self.address, authkey=self._authkey)
                set_nodelay(conn)
                return conn, None
            sid = self._next_sid
            self._next_sid += 1
            q = _ChunkQueue()
            self._streams[sid] = q
            return MuxStream(self, sid, q, self._gen), self._wire

    def _read_loop(self, conn, gen: int) -> None:
        """The one reader: envelope chunk → payload chunk → route."""
        try:
            while True:
                env = conn.recv_bytes(4)
                chunk = conn.recv_bytes(_MAX_CHUNK)
                (sid,) = _ENVELOPE.unpack(env)
                with self._lock:
                    q = self._streams.get(sid)
                if q is not None:
                    q.put(chunk)
        except (EOFError, OSError, TypeError) as e:
            # TypeError: close() pulled the handle out from under a
            # blocked recv (the stdlib quirk service.py documents)
            err = (e if isinstance(e, (EOFError, OSError))
                   else EOFError("transport closed"))
            with self._lock:
                if self._gen != gen:
                    return  # a newer transport owns the streams now
                self._conn = None
                streams, self._streams = self._streams, {}
                w, self._wire = self._wire, None
            ch = getattr(w, "shm", None)
            if ch is not None:
                ch.close()  # leases the dead peer never acked
            for q in streams.values():
                q.put_err(ConnectionResetError(
                    f"mux transport to {self.address} lost: {err}"))
            try:
                conn.close()
            except OSError:
                pass

    # -- stream-side internals -----------------------------------------

    def _send(self, sid: int, buf, gen: int) -> None:
        with self._lock:
            conn = self._conn
            if conn is None or gen != self._gen \
                    or sid not in self._streams:
                raise ConnectionResetError(
                    f"mux transport to {self.address} is gone; "
                    "reconnect via connect_stream()")
        try:
            with self._wlock:
                conn.send_bytes(_ENVELOPE.pack(sid))
                conn.send_bytes(buf)
        except (OSError, EOFError, ValueError) as e:
            raise ConnectionResetError(
                f"mux transport to {self.address} lost mid-send: {e}"
            ) from e

    def _close_stream(self, sid: int, gen: int) -> None:
        with self._lock:
            self._streams.pop(sid, None)
            conn = self._conn if gen == self._gen else None
        if conn is not None:
            try:
                with self._wlock:
                    conn.send_bytes(_ENVELOPE.pack(sid))
                    conn.send_bytes(b"")  # server-side stream retire
            except (OSError, EOFError, ValueError):
                pass

    def disable_shm(self) -> None:
        """Degrade this transport to in-band frames after a typed
        :class:`wire.ShmRefusal`: drop the current connection (its
        streams fail with ``ConnectionResetError``, so their owners
        reconnect through their ordinary retry loops) and never offer
        the lane again from this transport."""
        with self._lock:
            if not self._shm_on:
                return
            self._shm_on = False
            conn, self._conn = self._conn, None
            streams, self._streams = self._streams, {}
            w, self._wire = self._wire, None
        ch = getattr(w, "shm", None)
        if ch is not None:
            ch.close()
        for q in streams.values():
            q.put_err(ConnectionResetError(
                f"shm lane to {self.address} disabled; reconnect"))
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        with self._lock:
            self._closed = True
            conn, self._conn = self._conn, None
            streams, self._streams = self._streams, {}
            w, self._wire = self._wire, None
        ch = getattr(w, "shm", None)
        if ch is not None:
            ch.close()
        for q in streams.values():
            q.put_err(EOFError("transport closed"))
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def wait_readable(conns, timeout: float) -> list:
    """``multiprocessing.connection.wait`` generalized over
    :class:`MuxStream` objects (which have no fileno to select on):
    real connections go through the stdlib wait; when any stream is in
    the set, fall back to a fine-grained poll sweep.  Used by the
    ingest client's pipelined fetch loop so it can mix plain and
    muxed reader pipes."""
    from multiprocessing.connection import wait as _wait

    plain = [c for c in conns if not isinstance(c, MuxStream)]
    muxed = [c for c in conns if isinstance(c, MuxStream)]
    if not muxed:
        return _wait(plain, timeout=timeout)
    deadline = time.monotonic() + timeout
    while True:
        ready = [c for c in muxed if c.poll(0)]
        if plain:
            ready += _wait(plain, timeout=0)
        if ready:
            return ready
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return []
        time.sleep(min(0.002, remaining))
