"""Tensor parallelism over the mesh's ``model`` axis (GSPMD).

Beyond reference parity (the reference is data-parallel only,
SURVEY.md §2.11) — this is the framework's Megatron-style TP path for
the transformer family, built the idiomatic XLA way: annotate the
parameter shardings, let the compiler insert the collectives (the
scaling-book recipe).  Two deliberate styles coexist:

* **explicit SPMD (shard_map)** where the algorithm needs manual
  control — ring attention over ``seq``, psum gradient exchange over
  ``data`` (parallel/bsp.py, parallel/sequence.py);
* **automatic GSPMD (jit + NamedSharding)** where XLA partitions
  matmuls better than hand-written collectives — TP: QKV/MLP-in
  kernels column-sharded ``P(None, 'model')``, attn-out/MLP-out
  row-sharded ``P('model', None)``, the all-reduce after each pair
  inserted by the compiler.

Data parallelism composes for free: the batch is sharded over
``data``, parameters are replicated over ``data`` and sharded over
``model``, and the gradient all-reduce over ``data`` is likewise
compiler-inserted — one jit, a (data x model) mesh, no axis names in
the model code.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from theanompi_tpu.parallel.bsp import TrainState
from theanompi_tpu.parallel.mesh import AXIS_MODEL

PyTree = Any


def transformer_tp_specs(params: PyTree) -> PyTree:
    """Megatron sharding rules for ``TransformerLMNet`` parameters.

    Per block: ``q_proj``/``k_proj``/``v_proj`` and ``mlp_up`` are
    column-parallel — output dim over ``model``, so each head's Q, K
    and V land on one shard (requires ``n_heads % tp == 0``);
    ``o_proj`` and ``mlp_down`` are row-parallel — input dim over
    ``model``, their products all-reduced by the compiler.  Embeddings,
    norms, positional table and the LM head stay replicated (small
    next to the block weights).
    """
    col = {"q_proj", "k_proj", "v_proj", "mlp_up"}
    row = {"o_proj", "mlp_down"}

    def rule(path, leaf) -> P:
        keys = [getattr(k, "key", getattr(k, "name", None)) for k in path]
        in_block = any(isinstance(k, str) and k.startswith("Block_")
                       for k in keys)
        if not in_block or leaf.ndim == 0:
            return P()
        dense = next((k for k in keys if k in col | row), None)
        if dense in col:
            # kernel (in, out) -> out sharded; bias (out,) -> sharded
            return P(None, AXIS_MODEL) if leaf.ndim == 2 else P(AXIS_MODEL)
        if dense in row:
            # kernel (in, out) -> in sharded; bias stays replicated
            # (added after the all-reduced product)
            return P(AXIS_MODEL, None) if leaf.ndim == 2 else P()
        return P()

    return jax.tree_util.tree_map_with_path(rule, params)


def opt_state_specs(tx, opt_state_template: PyTree,
                    param_specs: PyTree) -> PyTree:
    """Spec tree matching an optimizer state: param-like leaves (the
    momentum/trace buffers) carry the param's spec, bookkeeping leaves
    (counts, injected hyperparams) are replicated.  Shared by every
    param-sharded step builder (TP/PP/MoE)."""
    grafted = optax.tree_map_params(
        tx, lambda _leaf, spec: spec, opt_state_template, param_specs)
    return jax.tree.map(
        lambda x: x if isinstance(x, P) else P(),
        grafted, is_leaf=lambda x: isinstance(x, P))


def shard_train_state(params: PyTree, model_state: PyTree, mesh: Mesh,
                      param_specs: PyTree,
                      tx: optax.GradientTransformation) -> TrainState:
    """Build a TrainState with params placed per their TP specs and the
    optimizer state created FROM the sharded params — the full-size
    momentum buffers are never materialized on any single device
    (``zeros_like`` of a sharded array inherits its sharding; the
    explicit re-put per spec is belt and braces)."""
    params = jax.tree.map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        params, param_specs)
    opt_state = optax.tree_map_params(
        tx,
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        tx.init(params),
        param_specs,
    )
    rep = NamedSharding(mesh, P())
    import jax.numpy as jnp

    return TrainState(
        step=jax.device_put(jnp.zeros((), jnp.int32), rep),
        params=params,
        opt_state=opt_state,
        model_state=jax.tree.map(lambda x: jax.device_put(x, rep),
                                 {} if model_state is None else model_state),
    )


def _gspmd_step(loss_fn: Callable, tx: optax.GradientTransformation,
                grad_scale: float = 1.0):
    """The shared one-iteration step body for the GSPMD builders.
    ``grad_scale`` realizes the reference's sum-mode (``cdd``) exchange:
    the global-batch mean gradient times the data-axis size equals the
    sum of per-worker mean gradients."""
    from theanompi_tpu.parallel.bsp import apply_update, grad_and_metrics

    def step(state: TrainState, batch, rng):
        grads, new_ms, metrics = grad_and_metrics(
            loss_fn, state.params, state.model_state, batch, rng)
        if grad_scale != 1.0:
            grads = jax.tree.map(lambda g: g * grad_scale, grads)
        return apply_update(tx, state, grads, new_ms), metrics

    return step


def make_gspmd_train_step(
    loss_fn: Callable,
    tx: optax.GradientTransformation,
    donate: bool = True,
    grad_scale: float = 1.0,
):
    """One jitted training step with NO manual collectives: shardings
    flow in from the committed state/batch arrays and GSPMD inserts the
    TP all-reduces (row-parallel products) and the DP gradient
    all-reduce.  ``loss_fn(params, model_state, batch, rng)`` computes
    the GLOBAL-batch mean loss (the batch is one logical array here,
    unlike the per-shard view inside shard_map)."""
    step = _gspmd_step(loss_fn, tx, grad_scale)
    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_gspmd_multi_step(
    loss_fn: Callable,
    tx: optax.GradientTransformation,
    donate: bool = True,
    grad_scale: float = 1.0,
):
    """``lax.scan`` of k GSPMD steps in one program (the TP analogue of
    ``parallel/bsp.make_bsp_multi_step``): ``stacked_batch`` carries a
    leading steps axis, rngs are ``fold_in(rng, i)`` per sub-step,
    metrics come back stacked ``(k,)``."""
    import jax.numpy as jnp

    step = _gspmd_step(loss_fn, tx, grad_scale)

    def multi(state: TrainState, stacked, rng):
        def body(carry, xs):
            i, batch = xs
            return step(carry, batch, jax.random.fold_in(rng, i))

        k = jax.tree.leaves(stacked)[0].shape[0]
        return jax.lax.scan(body, state, (jnp.arange(k), stacked))

    return jax.jit(multi, donate_argnums=(0,) if donate else ())


def make_gspmd_eval_step(eval_fn: Callable):
    def step(state: TrainState, batch):
        return eval_fn(state.params, state.model_state, batch)

    return jax.jit(step)
