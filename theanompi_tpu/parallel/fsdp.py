"""Fully-sharded data parallelism (FSDP / ZeRO-3 class) via GSPMD.

Beyond-parity surface: the reference's four rules all replicate
parameters on every worker (SURVEY.md §2.11 — its NCCL/MPI exchangers
move grads or whole param sets); nothing in its zoo shards the
parameters themselves.  On TPU, parameter sharding is not an exchanger
subsystem but a PLACEMENT decision handed to the compiler: commit
every parameter (and therefore its optimizer twin) to a 1/N shard of
the ``data`` axis, write the training step as the plain unsharded
math, and let GSPMD insert the all-gathers right before each weight's
use and a reduce-scatter for its gradient — per-layer, overlapped with
compute, freed after use.  That per-layer gather/free schedule is what
hand-written FSDP implementations build manually; XLA derives it from
the shardings.

Contrast with ``parallel/zero.py`` (ZeRO-1): there the params stay
replicated and only the flat optimizer vector is sharded, with the
collectives written out by hand in a ``shard_map``.  Here params,
momentum, and every param-shaped buffer live sharded at rest —
per-device state memory drops from ~3P to ~3P/N — and no collective
appears in the step's source at all.

Design notes:

* Sharding axis per leaf: the LARGEST dim divisible by the data-axis
  size (ties → earliest dim).  Leaves with no divisible dim (scalars,
  small biases, odd shapes) stay replicated — they are a vanishing
  fraction of parameter bytes.
* The step math is identical to an unsharded single-device step over
  the global batch, so its oracle in tests is literal: same loss, same
  params, no tolerance games beyond dtype noise.
* 'cdd' (sum) semantics: grads of the global-mean loss times N — the
  same trajectory the shard_map BSP step produces when summing
  per-shard grads.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from theanompi_tpu.parallel.bsp import (
    TrainState,
    _donate_argnums,
    accumulate_microbatch_grads,
    apply_update,
    grad_and_metrics,
)
from theanompi_tpu.parallel.exchanger import (
    _leaf_nbytes,
    bucket_ranges,
    emit_bucket_gauges,
    validate_bucket_count,
)
from theanompi_tpu.parallel.mesh import AXIS_DATA

PyTree = Any


def _bucket_barrier_tag():
    """Boundary marker for one gradient bucket under GSPMD: identity
    forward; the backward wraps the bucket's cotangents in ONE
    ``optimization_barrier``.  FSDP's reduce-scatters are
    compiler-inserted (there is no program point to issue a hand
    collective at — see make_bsp_fsdp_step's bf16 note), so bucketing
    here is purely a SCHEDULING fence: the barrier keeps each
    bucket's gradient collectives a unit the all-reduce combiner
    cannot coalesce across, so the lowered program keeps per-bucket
    collective groups interleaved with backward compute instead of
    one merged trailing block.  Numerically the identity — pinned
    bit-equal to the unbucketed step."""

    @jax.custom_vjp
    def tag(leaves):
        return leaves

    def fwd(leaves):
        return leaves, None

    def bwd(_, cts):
        return (jax.lax.optimization_barrier(cts),)

    tag.defvjp(fwd, bwd)
    return tag


def _with_bucket_barriers(loss_fn, params_template: PyTree,
                          exchange_buckets: int):
    """Wrap ``loss_fn`` so every param bucket passes through a
    boundary tag — shared by all three FSDP cadences (the accum scan
    calls loss_fn per microbatch; wrapping at the builder keeps the
    bucket structure in every backward)."""
    t_leaves, _ = jax.tree.flatten(params_template)
    ranges = bucket_ranges([_leaf_nbytes(l) for l in t_leaves],
                           exchange_buckets)

    def wrapped(params, model_state, batch, rng):
        leaves, treedef = jax.tree.flatten(params)
        emit_bucket_gauges("fsdp", ranges, leaves, "f32")
        new_leaves = []
        for lo, hi in ranges:
            new_leaves.extend(_bucket_barrier_tag()(
                tuple(leaves[lo:hi])))
        return loss_fn(jax.tree.unflatten(treedef, new_leaves),
                       model_state, batch, rng)

    return wrapped


def fsdp_specs(params: PyTree, mesh: jax.sharding.Mesh,
               axis: str = AXIS_DATA) -> PyTree:
    """Per-leaf PartitionSpecs: shard the largest divisible dim."""
    n = mesh.shape[axis]

    def spec(leaf) -> P:
        shape = getattr(leaf, "shape", ())
        divisible = [d for d in range(len(shape)) if shape[d] % n == 0
                     and shape[d] >= n]
        if not divisible:
            return P()
        best = max(divisible, key=lambda d: shape[d])
        return P(*([None] * best + [axis]))

    return jax.tree.map(spec, params)


def fsdp_state_sharding(tx: optax.GradientTransformation, params: PyTree,
                        specs: PyTree, mesh: jax.sharding.Mesh):
    """TrainState-shaped NamedSharding tree: params per ``specs``,
    param-like optimizer buffers alongside them (optax.tree_map_params
    knows which), everything else replicated."""

    def ns(spec_tree):
        return jax.tree.map(lambda s: NamedSharding(mesh, s), spec_tree,
                            is_leaf=lambda x: isinstance(x, P))

    opt_template = jax.eval_shape(tx.init, params)
    rep = NamedSharding(mesh, P())
    opt_sharding = optax.tree_map_params(
        tx, lambda _, s: s, opt_template, ns(specs),
        transform_non_params=lambda _: rep)
    # model_state/step: a single replicated sharding acts as a pytree
    # PREFIX for the whole subtree (jit out_shardings semantics)
    return TrainState(step=rep, params=ns(specs), opt_state=opt_sharding,
                      model_state=rep)


def init_fsdp_state(params: PyTree, tx: optax.GradientTransformation,
                    model_state: PyTree, mesh: jax.sharding.Mesh,
                    specs: PyTree) -> TrainState:
    """Commit params to their shards, then build the optimizer state
    FROM the sharded params — ``zeros_like`` inherits sharding, so
    momentum materializes sharded and full-size optimizer state never
    exists on any device."""
    placed = jax.tree.map(
        lambda x, s: jax.device_put(jnp.asarray(x), NamedSharding(mesh, s)),
        params, specs)
    opt_state = jax.jit(tx.init)(placed)
    rep = NamedSharding(mesh, P())
    ms = jax.device_put(model_state if model_state is not None else {}, rep)
    step = jax.device_put(jnp.zeros((), jnp.int32), rep)
    return TrainState(step=step, params=placed, opt_state=opt_state,
                      model_state=ms)


def make_bsp_fsdp_step(
    loss_fn,
    tx: optax.GradientTransformation,
    mesh: jax.sharding.Mesh,
    params_template: PyTree,
    avg: bool = True,
    donate: bool = True,
    donate_batch: bool = True,
    batch_partition: P = P(AXIS_DATA),
    multi: bool = False,
    accum: bool = False,
    specs: PyTree | None = None,
    exchange_dtype: str = "f32",
    error_feedback: bool = False,
    exchange_buckets: int = 1,
):
    """Build the FSDP training step (plus the stacked cadences).

    ``step(state, batch, rng) -> (state, metrics)`` — the body is the
    plain global-batch math; all distribution lives in the committed
    input shardings and the ``out_shardings`` pin that keeps the new
    state on its shards (without it the partitioner may replicate the
    updated params, silently un-sharding the state after one step).

    ``multi=True``: ``lax.scan`` of the full step over a stacked batch
    with per-substep rng folds — same trajectory as k separate calls.
    ``accum=True``: microbatch gradient accumulation, one update.

    ``batch_partition`` documents the layout the caller stages batches
    with (``shard_batch``); under GSPMD the step itself needs no
    per-axis knowledge — it is recorded here so callers share one
    signature with the shard_map builders.
    """
    if accum and multi:
        raise ValueError("accum and multi are mutually exclusive "
                         "stacked cadences")
    # the bf16-exchange seam (parallel/bsp.py / parallel/zero.py) does
    # not exist here BY CONSTRUCTION: the step is plain global math and
    # GSPMD inserts the reduce-scatters wherever the backward needs
    # them — there is no program point between "gradient produced" and
    # "collective issued" to quantize at.  A cast after value_and_grad
    # would sit AFTER the compiler's collective in the dataflow and
    # compress nothing.  Explicit parameters so the config layer's
    # rejection has one enforced home.
    if exchange_dtype != "f32" or error_feedback:
        raise ValueError(
            "fsdp_sharding's gradient collectives are compiler-inserted "
            "at full precision; exchange_dtype='bf16'/error_feedback "
            "have no seam here — use zero_sharding or plain BSP for "
            "the compressed exchange")
    validate_bucket_count(exchange_buckets)
    if exchange_buckets > 1:
        # per-bucket optimization_barrier fences in the backward —
        # GSPMD still owns the collectives (the bf16 note above), the
        # fences only pin their per-bucket grouping.  Applied at the
        # builder so every cadence (incl. the accum scan's
        # per-microbatch backward) carries the bucket structure.
        loss_fn = _with_bucket_barriers(loss_fn, params_template,
                                        exchange_buckets)
    n = mesh.shape[AXIS_DATA]
    # one placement contract: callers that already derived specs (the
    # model layer stores them as param_specs for checkpoint-resume
    # re-placement) pass them in, so the step's shardings and the
    # resume path can never diverge
    if specs is None:
        specs = fsdp_specs(params_template, mesh, AXIS_DATA)
    state_sharding = fsdp_state_sharding(tx, params_template, specs, mesh)
    # explicit in_shardings, not inference-from-committed-arrays: the
    # donation matcher pairs donated inputs to outputs by GLOBAL
    # shape/dtype, so without declared shardings a donated 1/N param
    # shard can be aliased to a same-global-shape REPLICATED output
    # (e.g. a BN param vs its batch_stats twin) and the program dies
    # at runtime with a buffer-size mismatch.  Batch shardings are a
    # pytree prefix: one sharding covers every batch leaf.
    batch_spec = (P(None, *batch_partition) if (multi or accum)
                  else batch_partition)
    batch_sharding = NamedSharding(mesh, batch_spec)
    rep = NamedSharding(mesh, P())

    def one_step(state: TrainState, batch, rng):
        grads, new_ms, metrics = grad_and_metrics(
            loss_fn, state.params, state.model_state, batch, rng)
        if not avg:  # 'cdd': sum-of-per-shard-grads trajectory
            grads = jax.tree.map(lambda g: g * n, grads)
        return apply_update(tx, state, grads, new_ms), metrics

    if multi:
        def fn(state, stacked, rng):
            def body(carry, xs):
                i, batch = xs
                return one_step(carry, batch, jax.random.fold_in(rng, i))

            k = jax.tree.leaves(stacked)[0].shape[0]
            return jax.lax.scan(body, state, (jnp.arange(k), stacked))
    elif accum:
        def fn(state, stacked, rng):
            def add(gsum, grads):
                return jax.tree.map(
                    lambda s, g: s + g.astype(jnp.float32), gsum, grads)

            gz = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), state.params)
            new_ms, gsum, metrics, a = accumulate_microbatch_grads(
                loss_fn, state.params, state.model_state, stacked, rng,
                gz, add)
            grads = jax.tree.map(
                lambda g, p: (g / a).astype(p.dtype), gsum, state.params)
            if not avg:
                grads = jax.tree.map(lambda g: g * n, grads)
            return apply_update(tx, state, grads, new_ms), metrics
    else:
        fn = one_step

    # the stacked cadences donate the staged batch like parallel/bsp.py
    # (same copy-done rationale + the same opt-out for batch replayers)
    dn = _donate_argnums(donate, donate_batch and (accum or multi))
    return jax.jit(fn,
                   in_shardings=(state_sharding, batch_sharding, rep),
                   out_shardings=(state_sharding, None),
                   donate_argnums=dn)


