"""Device-mesh construction and sharding helpers.

TPU-native replacement for the reference's process/comm runtime
(reference layout ``theanompi/lib/base.py`` — rank/size bookkeeping,
GPU context init, NCCL clique bootstrap over MPI; SURVEY.md §2.6.  The
reference mount was empty this round, so citations are to SURVEY.md,
not file:line).

Design: instead of one OS process per device with explicit rank/size
state, we build a single :class:`jax.sharding.Mesh` with named axes and
let XLA schedule collectives over ICI.  The reference only ever used
data parallelism (SURVEY.md §2.11), so the default mesh is 1-D over
``data`` — but every axis the task cares about (model/tensor, pipeline,
sequence, expert) is reserved here so that enabling it later is a
config change, not a rewrite.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Canonical axis names.  Keep in sync with MeshSpec fields below.
AXIS_DATA = "data"          # data parallel (the reference's only axis)
AXIS_MODEL = "model"        # tensor parallel
AXIS_PIPE = "pipe"          # pipeline parallel
AXIS_SEQ = "seq"            # sequence/context parallel (ring attention)
AXIS_EXPERT = "expert"      # expert parallel (MoE)

ALL_AXES = (AXIS_DATA, AXIS_MODEL, AXIS_PIPE, AXIS_SEQ, AXIS_EXPERT)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical parallelism degrees.  ``data=-1`` means "all remaining"."""

    data: int = -1
    model: int = 1
    pipe: int = 1
    seq: int = 1
    expert: int = 1

    def degrees(self, n_devices: int) -> dict[str, int]:
        fixed = self.model * self.pipe * self.seq * self.expert
        data = self.data
        if data == -1:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            data = n_devices // fixed
        total = data * fixed
        if total != n_devices:
            raise ValueError(
                f"mesh degrees {data}x{fixed} != device count {n_devices}"
            )
        return {
            AXIS_DATA: data,
            AXIS_MODEL: self.model,
            AXIS_PIPE: self.pipe,
            AXIS_SEQ: self.seq,
            AXIS_EXPERT: self.expert,
        }


def make_training_mesh(
    spec: MeshSpec | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a named mesh over ``devices`` (default: all local devices).

    Axes of degree 1 are kept in the mesh: a size-1 named axis costs
    nothing at runtime but lets model code annotate shardings uniformly
    (e.g. always ``P('data', None)`` for batches) regardless of which
    degrees are actually >1 this run.
    """
    spec = spec or MeshSpec()
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    degrees = spec.degrees(len(devices))
    shape = tuple(degrees[a] for a in ALL_AXES)
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, ALL_AXES)


def data_mesh(n: int | None = None,
              devices: Sequence[jax.Device] | None = None) -> Mesh:
    """Pure data-parallel mesh over ``n`` devices (reference parity mode)."""
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if n is not None:
        if n > len(devices):
            raise ValueError(f"requested {n} devices but only {len(devices)} available")
        devices = devices[:n]
    return make_training_mesh(MeshSpec(data=len(devices)), devices)


def is_multiprocess(mesh: Mesh) -> bool:
    """True iff ``mesh`` spans devices of more than one controller
    process (multi-host launch under ``jax.distributed``)."""
    procs = {d.process_index for d in mesh.devices.flat}
    return len(procs) > 1


def host_rank() -> int:
    return jax.process_index()


def host_count() -> int:
    return jax.process_count()


def batch_spec(mesh: Mesh) -> P:
    """PartitionSpec sharding the leading (batch) dim over data(+seq is
    left to attention ops; batch rides ``data`` only)."""
    del mesh
    return P(AXIS_DATA)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, batch_spec(mesh))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def data_axis_size(mesh: Mesh) -> int:
    return mesh.shape[AXIS_DATA]


def local_batch(global_batch: int, mesh: Mesh) -> int:
    n = data_axis_size(mesh)
    if global_batch % n != 0:
        raise ValueError(f"global batch {global_batch} not divisible by data={n}")
    return global_batch // n


def shard_batch(batch, mesh: Mesh, spec: P | None = None):
    """Place a host batch (pytree of arrays with a leading batch dim)
    onto the mesh, sharded over the data axis (or an explicit ``spec``
    — e.g. ``P('data', 'seq')`` for time-sharded LM batches).

    The moral equivalent of the reference's per-rank H2D staging of its
    data shard (SURVEY.md §3.4) — here a single ``device_put`` with a
    NamedSharding splits the global batch across chips.

    Multi-host: when the mesh spans processes, ``batch`` must be this
    host's *slice* of the global batch (``Dataset.host_train_batches``)
    and the global array is assembled with
    ``jax.make_array_from_process_local_data`` — each host feeds only
    its addressable shards; no host ever addresses remote devices.
    """
    sh = NamedSharding(mesh, spec if spec is not None else batch_spec(mesh))
    if is_multiprocess(mesh):
        return jax.tree.map(
            lambda x: jax.make_array_from_process_local_data(
                sh, np.asarray(x)), batch)
    return jax.tree.map(lambda x: jax.device_put(x, sh), batch)


def replicate(tree, mesh: Mesh):
    sh = replicated(mesh)
    if is_multiprocess(mesh):
        # every host holds the full value; each contributes its local
        # replicas (device_put cannot address remote devices)
        return jax.tree.map(
            lambda x: jax.make_array_from_process_local_data(
                sh, np.asarray(x)), tree)
    return jax.tree.map(lambda x: jax.device_put(x, sh), tree)


def log2_int(n: int) -> int:
    b = int(math.log2(n))
    if 2**b != n:
        raise ValueError(f"{n} is not a power of two")
    return b
