"""Wasserstein GAN — the reference zoo's two-network training loop
(``theanompi/models/wasserstein_gan.py``, SURVEY.md §2.8 — mount
empty, no file:line): DCGAN-shaped generator + critic trained with
the WGAN recipe (Arjovsky et al. 2017) — RMSprop, ``n_critic`` critic
updates per generator update, critic weights clipped to ``[-c, c]``.

TPU-native design: the reference alternated separately-compiled
Theano functions from Python; here the WHOLE round — ``n_critic``
critic updates (``lax.scan``) followed by one generator update, with
every gradient psum-ed over the data axis — is ONE jitted SPMD
program, so the inner loop never bounces to the host and XLA overlaps
the ICI collectives with backprop.

The model keeps the standard contract (``compile_iter_fns`` /
``train_iter`` / ``val_epoch`` / ``save`` / ``load``), so
``run_bsp_session`` and the launchers drive it unchanged; its state is
a two-optimizer ``WGANState`` instead of the classifier
``TrainState``.  Metric names: ``loss`` is the negated critic loss —
the Wasserstein-distance estimate (lower = distributions closer);
``error`` carries the generator loss so the recorder's two columns
stay meaningful.
"""

from __future__ import annotations

import os
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct
from jax.sharding import PartitionSpec as P

from theanompi_tpu.data.cifar10 import Cifar10_data
from theanompi_tpu.models import layers as L
from theanompi_tpu.models.base import ModelConfig, TpuModel
from theanompi_tpu.parallel.mesh import AXIS_DATA, replicate
from theanompi_tpu.utils.helper_funcs import load_params_npz, save_params_npz
from theanompi_tpu.utils.recorder import Recorder

PyTree = Any


class Generator(nn.Module):
    """z → 32x32x3 image in [-1, 1] (DCGAN-shaped upsampling stack)."""

    width: int = 128
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, z):
        z = z.astype(self.dtype)
        x = L.Dense(4 * 4 * self.width * 2, kernel_init=L.gaussian_init(0.02),
                    dtype=self.dtype)(z)
        x = nn.relu(x)
        x = x.reshape((x.shape[0], 4, 4, self.width * 2))
        for w in (self.width * 2, self.width):          # 4→8→16
            x = nn.ConvTranspose(w, (4, 4), strides=(2, 2), padding="SAME",
                                 kernel_init=L.gaussian_init(0.02),
                                 dtype=self.dtype)(x)
            x = nn.relu(x)
        x = nn.ConvTranspose(3, (4, 4), strides=(2, 2), padding="SAME",
                             kernel_init=L.gaussian_init(0.02),
                             dtype=self.dtype)(x)      # 16→32
        return jnp.tanh(x).astype(jnp.float32)


class Critic(nn.Module):
    """32x32x3 image → scalar score (no sigmoid — Wasserstein critic)."""

    width: int = 128
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        for w in (self.width // 2, self.width, self.width * 2):  # 32→16→8→4
            x = L.Conv(w, (4, 4), strides=(2, 2),
                       kernel_init=L.gaussian_init(0.02),
                       dtype=self.dtype)(x)
            x = nn.leaky_relu(x, 0.2)
        x = x.reshape((x.shape[0], -1))
        x = L.Dense(1, kernel_init=L.gaussian_init(0.02),
                    dtype=self.dtype)(x)
        return x.astype(jnp.float32)[:, 0]


@struct.dataclass
class WGANState:
    step: jax.Array
    gen_params: PyTree
    gen_opt: PyTree
    critic_params: PyTree
    critic_opt: PyTree


class WGANCifar_data(Cifar10_data):
    """CIFAR images scaled to the generator's tanh range [-1, 1]
    (instead of the classifier mean/std normalization):
    ((px/255) - 0.5) / 0.5 == px/127.5 - 1."""

    mean = (0.5, 0.5, 0.5)
    std = (0.5, 0.5, 0.5)


def clip_params(params: PyTree, c: float) -> PyTree:
    """The WGAN weight clip — Lipschitz constraint on the critic."""
    return jax.tree.map(lambda p: jnp.clip(p, -c, c), params)


class Wasserstein_GAN(TpuModel):
    """WGAN over CIFAR-shaped images; BSP data-parallel."""

    name = "wgan"
    latent_dim = 100
    n_critic = 5
    clip_c = 0.01

    @classmethod
    def default_config(cls) -> ModelConfig:
        return ModelConfig(
            batch_size=64,
            n_epochs=50,
            learning_rate=5e-5,     # RMSprop, constant (WGAN recipe)
            momentum=0.0,
            weight_decay=0.0,
            lr_schedule="constant",
            print_freq=20,
        )

    def __init__(self, config: ModelConfig | None = None, mesh=None,
                 verbose: bool = True, shard_rank: int = 0,
                 shard_size: int = 1, data=None, width: int = 64):
        # shared contract scaffolding, then the two-network state the
        # single-module TrainState path can't express
        self._init_scaffold(config, mesh, verbose, shard_rank, shard_size,
                            data)
        # one fused round consumes a FRESH real minibatch per critic
        # update (the WGAN recipe) plus none for the generator, so the
        # data pipeline feeds n_critic * batch_size images per step
        self.global_batch = self.batch_size * self.n_workers * self.n_critic

        dtype = self._compute_dtype()
        self.generator = Generator(width=width * 2, dtype=dtype)
        self.critic = Critic(width=width * 2, dtype=dtype)
        self.module = self.generator  # for introspection/tabulate

        rng = jax.random.key(self.config.seed)
        g_rng, c_rng = jax.random.split(rng)
        z = jnp.zeros((2, self.latent_dim), jnp.float32)
        x = jnp.zeros((2, *self.data.sample_shape), jnp.float32)
        gen_params = self.generator.init(g_rng, z)["params"]
        critic_params = self.critic.init(c_rng, x)["params"]

        self.gen_tx = optax.rmsprop(self._base_lr)
        self.critic_tx = optax.rmsprop(self._base_lr)

        state = WGANState(
            step=jnp.zeros((), jnp.int32),
            gen_params=gen_params,
            gen_opt=self.gen_tx.init(gen_params),
            critic_params=clip_params(critic_params, self.clip_c),
            critic_opt=self.critic_tx.init(critic_params),
        )
        self.state = replicate(state, self.mesh)

    def build_data(self):
        return WGANCifar_data(data_dir=self.config.data_dir,
                              seed=self.config.seed)

    # -- the fused WGAN round ------------------------------------------------

    def compile_iter_fns(self, sync_type: str = "avg") -> None:
        self._reject_grad_accum("WGAN round step")
        self._reject_zero_sharding("WGAN round step")
        gen, critic = self.generator, self.critic
        gen_tx, critic_tx = self.gen_tx, self.critic_tx
        n_critic, clip_c, latent = self.n_critic, self.clip_c, self.latent_dim
        # gradient exchange honors the same strategy/sync knobs as every
        # other model ('cdd' = sum with caller-pre-scaled LR; 'nccl16'
        # etc. = bf16-compressed exchange)
        from theanompi_tpu.parallel.exchanger import BSP_Exchanger

        exchanger = BSP_Exchanger(
            strategy=self.config.exchange_strategy,
            avg=(sync_type != "cdd"),
            exchange_what="grads",
            exchange_dtype=(None if self.config.exchange_dtype == "f32"
                            else self.config.exchange_dtype),
        )

        def pmean(t):
            return jax.tree.map(lambda x: jax.lax.pmean(x, AXIS_DATA), t)

        def critic_loss(cp, gp, x_real, z):
            x_fake = gen.apply({"params": gp}, z)
            f_fake = critic.apply({"params": cp}, x_fake)
            f_real = critic.apply({"params": cp}, x_real)
            return jnp.mean(f_fake) - jnp.mean(f_real)

        def gen_loss(gp, cp, z):
            x_fake = gen.apply({"params": gp}, z)
            return -jnp.mean(critic.apply({"params": cp}, x_fake))

        def shard_step(state: WGANState, batch, rng):
            x_real = batch[0]
            rng = jax.random.fold_in(rng, jax.lax.axis_index(AXIS_DATA))
            c_rngs = jax.random.split(jax.random.fold_in(rng, 0), n_critic)
            g_rng = jax.random.fold_in(rng, 1)
            # each critic update sees a fresh real minibatch (WGAN
            # recipe): split the shard's n_critic*b rows into slices
            b = x_real.shape[0] // n_critic
            x_slices = x_real[:b * n_critic].reshape(
                (n_critic, b) + x_real.shape[1:])

            def critic_iter(carry, inp):
                cp, copt = carry
                c_rng, x_slice = inp
                z = jax.random.normal(c_rng, (b, latent))
                loss, grads = jax.value_and_grad(critic_loss)(
                    cp, state.gen_params, x_slice, z)
                grads = exchanger.exchange(grads)
                updates, copt = critic_tx.update(grads, copt, cp)
                cp = clip_params(optax.apply_updates(cp, updates), clip_c)
                return (cp, copt), loss

            (cp, copt), c_losses = jax.lax.scan(
                critic_iter, (state.critic_params, state.critic_opt),
                (c_rngs, x_slices))

            z = jax.random.normal(g_rng, (b, latent))
            g_loss_val, g_grads = jax.value_and_grad(gen_loss)(
                state.gen_params, cp, z)
            g_grads = exchanger.exchange(g_grads)
            g_updates, gopt = gen_tx.update(g_grads, state.gen_opt,
                                            state.gen_params)
            gp = optax.apply_updates(state.gen_params, g_updates)

            # W-distance estimate = −(last critic loss); both pmean-ed
            metrics = pmean({"loss": -c_losses[-1], "error": g_loss_val})
            new_state = WGANState(step=state.step + 1, gen_params=gp,
                                  gen_opt=gopt, critic_params=cp,
                                  critic_opt=copt)
            return new_state, metrics

        sharded = jax.shard_map(shard_step, mesh=self.mesh,
                                in_specs=(P(), P(AXIS_DATA), P()),
                                out_specs=(P(), P()), check_vma=False)
        self.train_step = jax.jit(sharded, donate_argnums=(0,))

        def eval_shard(state: WGANState, batch, rng):
            x_real = batch[0]
            rng = jax.random.fold_in(rng, jax.lax.axis_index(AXIS_DATA))
            z = jax.random.normal(rng, (x_real.shape[0], latent))
            w = -critic_loss(state.critic_params, state.gen_params, x_real, z)
            return pmean({"loss": w, "error": jnp.zeros(())})

        eval_sharded = jax.shard_map(eval_shard, mesh=self.mesh,
                                     in_specs=(P(), P(AXIS_DATA), P()),
                                     out_specs=P(), check_vma=False)
        self.eval_step = jax.jit(eval_sharded)

    def val_iter(self, count: int, recorder: Recorder, batch=None) -> dict:
        # same self-timing contract as TpuModel.val_iter (val_epoch's
        # caller no longer wraps validation in its own recorder section)
        recorder.start()
        metrics = self.eval_step(self.state, batch, self._next_rng())
        recorder.end("calc")
        return metrics

    def generate(self, n: int, seed: int = 0) -> np.ndarray:
        """Sample n images from the generator (host-side convenience)."""
        z = jax.random.normal(jax.random.key(seed), (n, self.latent_dim))
        x = self.generator.apply({"params": self.state.gen_params}, z)
        return np.asarray(x)

    # -- contract odds and ends for the two-network state --------------------

    @property
    def params(self) -> PyTree:
        return {"generator": self.state.gen_params,
                "critic": self.state.critic_params}

    def adjust_hyperp(self, epoch: int) -> float:
        return self._base_lr  # WGAN: constant RMSprop LR

    def save(self, path: str | None = None) -> str:
        path = path or os.path.join(self.config.snapshot_dir,
                                    f"{self.name}_params.npz")
        save_params_npz(path, self.params)
        return path

    def load(self, path: str) -> None:
        like = jax.tree.map(np.asarray, self.params)
        loaded = load_params_npz(path, like)
        loaded = jax.tree.map(jnp.asarray, loaded)
        self.state = self.state.replace(
            gen_params=replicate(loaded["generator"], self.mesh),
            critic_params=replicate(loaded["critic"], self.mesh),
        )
