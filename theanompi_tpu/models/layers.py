"""Shared layer library for the model zoo.

Parity rebuild of the reference's ``theanompi/models/layers2.py``
(SURVEY.md §2.8 — mount empty, no file:line): Conv (with channel
grouping), pooling, LRN, BatchNorm, Dropout, FC, softmax head, plus
the era-appropriate weight initializers.  Built on flax.linen; the
grouped convolution that the reference routed to cuDNN groups maps to
XLA's ``feature_group_count``, and LRN is composed from XLA ops
(theanompi_tpu.ops.lrn).

Everything is NHWC and defaults to float32 params with configurable
compute dtype — pass ``dtype=jnp.bfloat16`` to run the matmul/conv
FLOPs on the MXU in bf16 while keeping fp32 master params.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from theanompi_tpu.ops.lrn import lrn

Dtype = Any

# -- reference-era initializers (gaussian std + constant bias) --


def gaussian_init(std: float = 0.01):
    def init(key, shape, dtype=jnp.float32):
        return std * jax.random.normal(key, shape, dtype)
    return init


def constant_init(v: float = 0.0):
    def init(key, shape, dtype=jnp.float32):
        return jnp.full(shape, v, dtype)
    return init


he_init = nn.initializers.he_normal
xavier_init = nn.initializers.xavier_uniform


class Conv(nn.Module):
    """Convolution with optional channel grouping + LRN + pooling —
    mirroring the reference's fused ConvPoolLRN layer blocks."""

    features: int
    kernel: tuple[int, int]
    strides: tuple[int, int] = (1, 1)
    padding: str | Sequence[tuple[int, int]] = "SAME"
    groups: int = 1
    use_bias: bool = True
    kernel_init: Callable = nn.initializers.he_normal()
    bias_init: Callable = constant_init(0.0)
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        return nn.Conv(
            features=self.features,
            kernel_size=self.kernel,
            strides=self.strides,
            padding=self.padding,
            feature_group_count=self.groups,
            use_bias=self.use_bias,
            kernel_init=self.kernel_init,
            bias_init=self.bias_init,
            dtype=self.dtype,
        )(x)


def max_pool(x, window: int = 3, stride: int = 2, padding="VALID"):
    return nn.max_pool(x, (window, window), (stride, stride), padding)


def avg_pool(x, window: int = 3, stride: int = 2, padding="VALID"):
    return nn.avg_pool(x, (window, window), (stride, stride), padding)


def global_avg_pool(x):
    return jnp.mean(x, axis=(1, 2))


class LRN(nn.Module):
    """Cross-channel local response normalization (AlexNet/GoogLeNet)."""

    n: int = 5
    k: float = 2.0
    alpha: float = 1e-4
    beta: float = 0.75

    @nn.compact
    def __call__(self, x):
        return lrn(x, self.n, self.k, self.alpha, self.beta)


class BatchNorm(nn.Module):
    """BN with the running stats in the 'batch_stats' collection.

    Cross-replica note: per-shard batch stats are averaged over the
    data axis by the BSP step (parallel/bsp.py pmean of model_state),
    which matches the reference's per-worker BN closely enough while
    keeping state replicated.  ``axis_name`` switches to TRUE
    cross-replica stats (pmean of mean/var inside the BN), mirroring
    the knob ResNet wires from ModelConfig.sync_bn (resnet50.py uses
    flax nn.BatchNorm directly; this wrapper exposes the same choice
    to zoo models built from the layer toolkit): required when the
    per-shard batch is too small for its statistics to serve eval.

    WIRING OBLIGATION (ADVICE r4): ``ModelConfig.sync_bn`` does NOT
    reach this wrapper automatically — a ``build_module()`` that uses
    it must pass ``axis_name=self._bn_axis()`` (models/base.py), or
    ``sync_bn=True`` silently keeps per-shard stats.  Today only the
    ResNet family threads the knob; ``TpuModel`` warns at compile when
    a ``uses_batchnorm`` model has a small per-shard batch and
    ``sync_bn`` off.  Regression:
    tests/test_model_zoo.py::TestLayersBatchNormSyncWiring."""

    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Dtype = jnp.float32
    axis_name: str | None = None

    @nn.compact
    def __call__(self, x):
        return nn.BatchNorm(
            use_running_average=self.use_running_average,
            momentum=self.momentum,
            epsilon=self.epsilon,
            dtype=self.dtype,
            axis_name=self.axis_name,
        )(x)


class Dense(nn.Module):
    features: int
    kernel_init: Callable = gaussian_init(0.005)
    bias_init: Callable = constant_init(0.0)
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        return nn.Dense(
            self.features,
            kernel_init=self.kernel_init,
            bias_init=self.bias_init,
            dtype=self.dtype,
        )(x)


class Dropout(nn.Module):
    rate: float = 0.5

    @nn.compact
    def __call__(self, x, train: bool):
        return nn.Dropout(self.rate, deterministic=not train)(x)


# -- loss / metric heads (the reference's softmax layer + error calc) --


def softmax_cross_entropy(logits: jax.Array, labels: jax.Array,
                          label_smoothing: float = 0.0) -> jax.Array:
    """Mean CE over the batch; labels are integer class ids.

    ``label_smoothing=eps`` mixes the one-hot target with uniform:
    target = (1-eps)*onehot + eps/K — the standard regularizer of the
    modern 90-epoch ResNet recipes (0.1)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    ll = jnp.take_along_axis(logp, labels[:, None].astype(jnp.int32), axis=1)
    nll = -jnp.mean(ll)
    if label_smoothing:
        eps = label_smoothing
        # -mean over batch of [ (1-eps)*logp_y + eps * mean_k logp_k ]
        return (1.0 - eps) * nll - eps * jnp.mean(logp)
    return nll


def error_rate(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Top-1 error (the reference's per-iteration 'error')."""
    return jnp.mean((jnp.argmax(logits, axis=-1) != labels).astype(jnp.float32))


def topk_error(logits: jax.Array, labels: jax.Array, k: int = 5) -> jax.Array:
    """Top-k error (the reference tracked top-5 for ImageNet).

    k is clamped to the class count: a top5-tracking recipe pointed at
    a <5-class dataset (e.g. a tiny smoke config inheriting the
    ResNet-50 recipe's ``track_top5=True``) must degrade to top-K over
    all classes, not crash in ``lax.top_k`` (round-3 verdict weak #3).
    The clamp is static — ``logits.shape[-1]`` is a trace-time
    constant — so it costs nothing under jit."""
    k = min(k, logits.shape[-1])
    topk = jax.lax.top_k(logits, k)[1]
    hit = jnp.any(topk == labels[:, None], axis=-1)
    return 1.0 - jnp.mean(hit.astype(jnp.float32))
