"""Shared layer library for the model zoo.

Parity rebuild of the reference's ``theanompi/models/layers2.py``
(SURVEY.md §2.8 — mount empty, no file:line): Conv (with channel
grouping), pooling, LRN, BatchNorm, Dropout, FC, softmax head, plus
the era-appropriate weight initializers.  Built on flax.linen; the
grouped convolution that the reference routed to cuDNN groups maps to
XLA's ``feature_group_count``, and LRN is composed from XLA ops
(theanompi_tpu.ops.lrn).

Everything is NHWC and defaults to float32 params with configurable
compute dtype — pass ``dtype=jnp.bfloat16`` to run the matmul/conv
FLOPs on the MXU in bf16 while keeping fp32 master params.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
from flax.linen import dtypes as _flax_dtypes
from jax import lax

from theanompi_tpu.ops.fused_bn import scale_bias_act
from theanompi_tpu.ops.lrn import lrn

Dtype = Any

# -- reference-era initializers (gaussian std + constant bias) --


def gaussian_init(std: float = 0.01):
    def init(key, shape, dtype=jnp.float32):
        return std * jax.random.normal(key, shape, dtype)
    return init


def constant_init(v: float = 0.0):
    def init(key, shape, dtype=jnp.float32):
        return jnp.full(shape, v, dtype)
    return init


he_init = nn.initializers.he_normal
xavier_init = nn.initializers.xavier_uniform


class Conv(nn.Module):
    """Convolution with optional channel grouping + LRN + pooling —
    mirroring the reference's fused ConvPoolLRN layer blocks."""

    features: int
    kernel: tuple[int, int]
    strides: tuple[int, int] = (1, 1)
    padding: str | Sequence[tuple[int, int]] = "SAME"
    groups: int = 1
    use_bias: bool = True
    kernel_init: Callable = nn.initializers.he_normal()
    bias_init: Callable = constant_init(0.0)
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        return nn.Conv(
            features=self.features,
            kernel_size=self.kernel,
            strides=self.strides,
            padding=self.padding,
            feature_group_count=self.groups,
            use_bias=self.use_bias,
            kernel_init=self.kernel_init,
            bias_init=self.bias_init,
            dtype=self.dtype,
        )(x)


def max_pool(x, window: int = 3, stride: int = 2, padding="VALID"):
    return nn.max_pool(x, (window, window), (stride, stride), padding)


def avg_pool(x, window: int = 3, stride: int = 2, padding="VALID"):
    return nn.avg_pool(x, (window, window), (stride, stride), padding)


def global_avg_pool(x):
    return jnp.mean(x, axis=(1, 2))


class LRN(nn.Module):
    """Cross-channel local response normalization (AlexNet/GoogLeNet)."""

    n: int = 5
    k: float = 2.0
    alpha: float = 1e-4
    beta: float = 0.75

    @nn.compact
    def __call__(self, x):
        return lrn(x, self.n, self.k, self.alpha, self.beta)


class BatchNormAct(nn.Module):
    """BatchNorm with a fusable activation/residual epilogue.

    Drop-in for ``nn.BatchNorm`` (+ a following relu / residual add):
    the variable layout is IDENTICAL to flax's — params ``scale``/
    ``bias``, batch_stats ``mean``/``var`` — so a module that pins the
    instance name (``name='BatchNorm_0'``) swaps implementations
    without moving a single leaf of the param tree, and checkpoints
    stay loadable across the ``impl`` knob.

    ``impl='xla'`` (default) reproduces today's unfused composition
    bit-for-bit: flax-style normalize (f32 stats, fast variance,
    ``maximum(0, E[x^2]-E[x]^2)``), cast to the compute dtype, then
    ``+ residual`` and relu as separate ops for XLA to fuse as it sees
    fit.  ``impl='pallas'`` folds the affine
    (``scale*rsqrt(var+eps)``, ``bias - mean*scale_eff``) and runs the
    whole epilogue as ONE Pallas stream over the activation
    (ops/fused_bn.py) — the batch-stat reductions stay XLA either way.
    This is the seam the MFU account's 5.81 ms of loop-fusion HBM
    traffic funnels through (artifacts/fusion_deepdive.json).

    ``act`` is ``None`` or ``'relu'``; ``residual`` (same shape as x)
    is added before the activation — the bottleneck-exit
    ``relu(bn(y) + shortcut)`` pattern.
    """

    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Dtype | None = None
    param_dtype: Dtype = jnp.float32
    axis_name: str | None = None
    act: str | None = None
    impl: str = "xla"            # 'xla' | 'pallas' (ModelConfig.bn_act_impl)
    scale_init: Callable = nn.initializers.ones
    bias_init: Callable = nn.initializers.zeros

    @nn.compact
    def __call__(self, x, residual=None):
        features = x.shape[-1]
        scale = self.param("scale", self.scale_init, (features,),
                           self.param_dtype)
        bias = self.param("bias", self.bias_init, (features,),
                          self.param_dtype)
        ra_mean = self.variable("batch_stats", "mean",
                                lambda s: jnp.zeros(s, jnp.float32),
                                (features,))
        ra_var = self.variable("batch_stats", "var",
                               lambda s: jnp.ones(s, jnp.float32),
                               (features,))
        if self.use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            # flax _compute_stats semantics: f32 reductions, fast
            # variance clipped at zero, mean+mean2 stacked into ONE
            # pmean when cross-replica (sync_bn)
            xf = x.astype(jnp.float32)
            axes = tuple(range(x.ndim - 1))
            mean = xf.mean(axes)
            mean2 = (xf * xf).mean(axes)
            if self.axis_name is not None and not self.is_initializing():
                mean, mean2 = lax.pmean(jnp.stack([mean, mean2]),
                                        self.axis_name)
            var = jnp.maximum(0.0, mean2 - mean * mean)
            if not self.is_initializing():
                ra_mean.value = (self.momentum * ra_mean.value
                                 + (1 - self.momentum) * mean)
                ra_var.value = (self.momentum * ra_var.value
                                + (1 - self.momentum) * var)
        out_dtype = _flax_dtypes.canonicalize_dtype(x, scale, bias,
                                                    dtype=self.dtype)
        if self.impl == "xla":
            # exactly flax _normalize + the models' epilogue ops, so
            # the default path is numerically unchanged
            mul = lax.rsqrt(var + self.epsilon) * scale
            y = (x - mean) * mul + bias
            y = jnp.asarray(y, out_dtype)
            if residual is not None:
                y = y + residual
            if self.act == "relu":
                y = nn.relu(y)
            return y
        scale_eff = scale * lax.rsqrt(var + self.epsilon)
        bias_eff = bias - mean * scale_eff
        return scale_bias_act(x, scale_eff, bias_eff, residual=residual,
                              act=self.act, impl=self.impl,
                              out_dtype=out_dtype)


class BiasAct(nn.Module):
    """Per-channel bias + activation — the conv epilogue of the BN-free
    zoo members (VGG, GoogLeNet).  With ``impl='pallas'`` the bias add
    and relu run as one fused stream (``scale=1`` through
    ops/fused_bn.py); ``impl='xla'`` matches ``nn.Conv``'s own bias-add
    (compute-dtype add) followed by relu.  NOTE: fusing moves the bias
    param from ``Conv_*/bias`` to this module's ``bias`` — the param
    TREE differs between a model built with fusion on vs off (unlike
    BatchNormAct, whose layout is pinned), so flip the knob at model
    build, not mid-run.
    """

    features: int
    bias_init: Callable = nn.initializers.zeros
    act: str | None = "relu"
    impl: str = "xla"

    @nn.compact
    def __call__(self, x):
        bias = self.param("bias", self.bias_init, (self.features,),
                          jnp.float32)
        if self.impl == "xla":
            y = x + bias.astype(x.dtype)
            return nn.relu(y) if self.act == "relu" else y
        return scale_bias_act(x, jnp.ones_like(bias), bias, act=self.act,
                              impl=self.impl, out_dtype=x.dtype)


class BatchNorm(nn.Module):
    """BN with the running stats in the 'batch_stats' collection.

    Cross-replica note: per-shard batch stats are averaged over the
    data axis by the BSP step (parallel/bsp.py pmean of model_state),
    which matches the reference's per-worker BN closely enough while
    keeping state replicated.  ``axis_name`` switches to TRUE
    cross-replica stats (pmean of mean/var inside the BN), mirroring
    the knob ResNet wires from ModelConfig.sync_bn (resnet50.py uses
    flax nn.BatchNorm directly; this wrapper exposes the same choice
    to zoo models built from the layer toolkit): required when the
    per-shard batch is too small for its statistics to serve eval.

    WIRING OBLIGATION (ADVICE r4): ``ModelConfig.sync_bn`` does NOT
    reach this wrapper automatically — a ``build_module()`` that uses
    it must pass ``axis_name=self._bn_axis()`` (models/base.py), or
    ``sync_bn=True`` silently keeps per-shard stats.  The ResNet
    family and the BN-variant toolkit zoo (``ModelConfig.batch_norm``:
    VGG16/VGG19, GoogLeNet, AlexNet) all thread the knob — any NEW
    zoo model using this wrapper inherits the obligation.  ``TpuModel``
    warns at compile when a ``uses_batchnorm`` model has a small
    per-shard batch and ``sync_bn`` off.  Regression:
    tests/test_model_zoo.py::TestLayersBatchNormSyncWiring and
    ::TestZooBatchNormVariants (per-model bn_axis threading)."""

    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Dtype = jnp.float32
    axis_name: str | None = None
    #: optional fused epilogue (BatchNormAct): act None|'relu', impl
    #: 'xla'|'pallas'.  The inner module is pinned to the name flax
    #: auto-assigned before this seam existed ('BatchNorm_0'), so the
    #: param tree is byte-identical to the old nn.BatchNorm wrapper.
    act: str | None = None
    impl: str = "xla"

    @nn.compact
    def __call__(self, x, residual=None):
        return BatchNormAct(
            use_running_average=self.use_running_average,
            momentum=self.momentum,
            epsilon=self.epsilon,
            dtype=self.dtype,
            axis_name=self.axis_name,
            act=self.act,
            impl=self.impl,
            name="BatchNorm_0",
        )(x, residual=residual)


class Dense(nn.Module):
    features: int
    kernel_init: Callable = gaussian_init(0.005)
    bias_init: Callable = constant_init(0.0)
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        return nn.Dense(
            self.features,
            kernel_init=self.kernel_init,
            bias_init=self.bias_init,
            dtype=self.dtype,
        )(x)


class Dropout(nn.Module):
    rate: float = 0.5

    @nn.compact
    def __call__(self, x, train: bool):
        return nn.Dropout(self.rate, deterministic=not train)(x)


# -- loss / metric heads (the reference's softmax layer + error calc) --


def softmax_cross_entropy(logits: jax.Array, labels: jax.Array,
                          label_smoothing: float = 0.0) -> jax.Array:
    """Mean CE over the batch; labels are integer class ids.

    ``label_smoothing=eps`` mixes the one-hot target with uniform:
    target = (1-eps)*onehot + eps/K — the standard regularizer of the
    modern 90-epoch ResNet recipes (0.1)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    ll = jnp.take_along_axis(logp, labels[:, None].astype(jnp.int32), axis=1)
    nll = -jnp.mean(ll)
    if label_smoothing:
        eps = label_smoothing
        # -mean over batch of [ (1-eps)*logp_y + eps * mean_k logp_k ]
        return (1.0 - eps) * nll - eps * jnp.mean(logp)
    return nll


def error_rate(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Top-1 error (the reference's per-iteration 'error')."""
    return jnp.mean((jnp.argmax(logits, axis=-1) != labels).astype(jnp.float32))


def topk_error(logits: jax.Array, labels: jax.Array, k: int = 5) -> jax.Array:
    """Top-k error (the reference tracked top-5 for ImageNet).

    k is clamped to the class count: a top5-tracking recipe pointed at
    a <5-class dataset (e.g. a tiny smoke config inheriting the
    ResNet-50 recipe's ``track_top5=True``) must degrade to top-K over
    all classes, not crash in ``lax.top_k`` (round-3 verdict weak #3).
    The clamp is static — ``logits.shape[-1]`` is a trace-time
    constant — so it costs nothing under jit."""
    k = min(k, logits.shape[-1])
    topk = jax.lax.top_k(logits, k)[1]
    hit = jnp.any(topk == labels[:, None], axis=-1)
    return 1.0 - jnp.mean(hit.astype(jnp.float32))
