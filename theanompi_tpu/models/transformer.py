"""Causal transformer LM — the long-context / sequence-parallel
flagship.

Not a reference-parity model (the reference predates attention,
SURVEY.md §2.11/§5.7); this is the model family that exercises the
framework's first-class long-context path: the TIME dimension is
sharded over the mesh's ``seq`` axis and attention runs via
``parallel.sequence`` (ring / all-gather / ulysses), so context length
scales with chips.  Everything else rides the same spine as the CNN
zoo — the model keeps the full reference contract and trains through
``run_bsp_session`` with the batch sharded ``P('data', 'seq')`` and
gradients exchanged over BOTH axes.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from theanompi_tpu.data.lm import SeqLM_data
from theanompi_tpu.models import layers as L
from theanompi_tpu.models.base import ModelConfig, TpuModel
from theanompi_tpu.parallel.mesh import AXIS_DATA, AXIS_SEQ
from theanompi_tpu.parallel.sequence import (
    attention_reference,
    sequence_attention,
)


class Block(nn.Module):
    """Pre-LN transformer block with sequence-parallel attention.

    Round-2 note: the attention projections are three named Dense
    modules (``q_proj``/``k_proj``/``v_proj``), not one fused qkv —
    required for clean tensor-parallel column sharding.  This changed
    the param tree (old ``Dense_N`` snapshots no longer load) and the
    per-projection xavier fan differs from the fused kernel's, so
    pre-change training curves are not bit-reproducible."""

    d_model: int
    n_heads: int
    d_ff: int
    sp_strategy: str = "ring"
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, seq_axis: str | None = None):
        b, t, _ = x.shape
        d_head = self.d_model // self.n_heads
        h = nn.LayerNorm(dtype=self.dtype)(x)
        # separate (named) Q/K/V projections: under tensor parallelism
        # each is column-sharded over 'model' so every head's Q, K and
        # V live on ONE shard — a fused qkv kernel sharded in
        # contiguous chunks would straddle the split points and force
        # an all-to-all per block (parallel/tensor.py rules)
        proj = lambda name: nn.Dense(  # noqa: E731
            self.d_model, use_bias=False, kernel_init=L.xavier_init(),
            dtype=self.dtype, name=name)(h)
        shape = (b, t, self.n_heads, d_head)
        q = proj("q_proj").reshape(shape)
        k = proj("k_proj").reshape(shape)
        v = proj("v_proj").reshape(shape)
        if seq_axis is not None:
            o = sequence_attention(q, k, v, axis_name=seq_axis, causal=True,
                                   strategy=self.sp_strategy)
        else:
            o = attention_reference(q, k, v, causal=True)
        o = o.reshape((b, t, self.d_model))
        x = x + nn.Dense(self.d_model, use_bias=False,
                         kernel_init=L.xavier_init(), dtype=self.dtype,
                         name="o_proj")(o)
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = nn.Dense(self.d_ff, kernel_init=L.he_init(), dtype=self.dtype,
                     name="mlp_up")(h)
        h = nn.gelu(h)
        x = x + nn.Dense(self.d_model, kernel_init=L.xavier_init(),
                         dtype=self.dtype, name="mlp_down")(h)
        return x


class TransformerLMNet(nn.Module):
    """Token ids (B, T_local) -> logits (B, T_local, vocab).

    ``seq_axis`` is a CALL-time argument (not a module field) so the
    same parameters serve both the sharded training path (inside
    shard_map, where positions offset by the shard index) and
    unsharded init/inference.
    """

    vocab: int = 256
    n_layers: int = 2
    d_model: int = 128
    n_heads: int = 4
    d_ff: int = 512
    max_len: int = 2048
    sp_strategy: str = "ring"
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, tokens, train: bool = False,
                 seq_axis: str | None = None):
        t_local = tokens.shape[1]
        offset = (lax.axis_index(seq_axis) * t_local
                  if seq_axis is not None else 0)
        x = nn.Embed(self.vocab, self.d_model,
                     embedding_init=L.gaussian_init(0.02))(tokens)
        pos_emb = self.param("pos_emb", L.gaussian_init(0.02),
                             (self.max_len, self.d_model))
        x = x + lax.dynamic_slice_in_dim(pos_emb, offset, t_local)[None]
        x = x.astype(self.dtype)
        for _ in range(self.n_layers):
            x = Block(self.d_model, self.n_heads, self.d_ff,
                      self.sp_strategy, self.dtype)(x, seq_axis=seq_axis)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        logits = nn.Dense(self.vocab, kernel_init=L.xavier_init(),
                          dtype=self.dtype)(x)
        return logits.astype(jnp.float32)


class TransformerLM(TpuModel):
    """LM over (data x seq)-sharded batches; reference model contract."""

    name = "transformer_lm"
    sp_strategy = "ring"
    batch_partition = P(AXIS_DATA, AXIS_SEQ)   # (B, T) over (data, seq)
    #: mesh axis the TIME dimension is sharded over inside the step
    #: (None = full attention; the TP variant sets None)
    seq_axis: str | None = AXIS_SEQ

    @classmethod
    def default_config(cls) -> ModelConfig:
        return ModelConfig(
            batch_size=16,
            n_epochs=5,
            learning_rate=0.1,
            momentum=0.9,
            weight_decay=0.0,
            lr_schedule="constant",
            print_freq=20,
        )

    def __init__(self, *args, vocab: int = 256, seq_len: int = 128,
                 n_layers: int = 2, d_model: int = 128, n_heads: int = 4,
                 **kwargs):
        self._net_cfg = dict(vocab=vocab, seq_len=seq_len, n_layers=n_layers,
                             d_model=d_model, n_heads=n_heads)
        super().__init__(*args, **kwargs)

    def _input_dtype(self):
        return jnp.int32

    def build_data(self):
        c = self._net_cfg
        return SeqLM_data(vocab=c["vocab"], seq_len=c["seq_len"],
                          seed=self.config.seed)

    def build_module(self) -> nn.Module:
        c = self._net_cfg
        return TransformerLMNet(
            vocab=c["vocab"], n_layers=c["n_layers"], d_model=c["d_model"],
            n_heads=c["n_heads"], d_ff=4 * c["d_model"],
            max_len=max(2048, c["seq_len"]), sp_strategy=self.sp_strategy,
            dtype=self._compute_dtype())

    # -- (data x seq) SPMD wiring -------------------------------------------

    def loss_fn(self, params, model_state, batch, rng):
        tokens, targets = batch
        logits = self.module.apply({"params": params}, tokens, train=True,
                                   seq_axis=self.seq_axis,
                                   rngs={"dropout": rng})
        v = logits.shape[-1]
        loss = L.softmax_cross_entropy(logits.reshape(-1, v),
                                       targets.reshape(-1))
        err = L.error_rate(logits.reshape(-1, v), targets.reshape(-1))
        return loss, (model_state, {"loss": loss, "error": err})

    def eval_fn(self, params, model_state, batch):
        tokens, targets = batch
        logits = self.module.apply({"params": params}, tokens, train=False,
                                   seq_axis=self.seq_axis)
        v = logits.shape[-1]
        return {"loss": L.softmax_cross_entropy(logits.reshape(-1, v),
                                                targets.reshape(-1)),
                "error": L.error_rate(logits.reshape(-1, v),
                                      targets.reshape(-1))}


class TransformerLM_TP(TransformerLM):
    """Tensor-parallel LM over a (data x model) mesh.

    Megatron-style TP the GSPMD way (parallel/tensor.py): block
    weights are sharded over ``model`` (Q/K/V/MLP-up column-wise,
    attn-out/MLP-down row-wise), the step is ONE plain jit and the
    compiler inserts every collective — both the TP all-reduces and
    the data-axis gradient all-reduce.  Attention runs unsharded in
    time (``seq_axis=None``); heads are what ``model`` splits, so this
    composes with DP, not SP.
    """

    name = "transformer_lm_tp"
    batch_partition = P(AXIS_DATA)   # tokens (B, T): batch over 'data'
    seq_axis = None                  # full attention; 'model' splits heads

    def _create_state(self, params, model_state):
        """Shard params per the Megatron specs and build the optimizer
        state FROM the sharded tree — full-size momentum buffers never
        exist on any device."""
        from theanompi_tpu.parallel.tensor import (
            shard_train_state,
            transformer_tp_specs,
        )

        self.param_specs = transformer_tp_specs(params)
        return shard_train_state(params, model_state, self.mesh,
                                 self.param_specs, self.tx)

    def load(self, path: str) -> None:
        """Contract ``load`` that PRESERVES the TP sharding (the base
        implementation would re-replicate params while the optimizer
        state stays sharded).  The template is shape/dtype-only — no
        cross-device gather of the sharded weights."""
        from theanompi_tpu.utils.helper_funcs import load_params_npz
        from jax.sharding import NamedSharding

        template = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            self.state.params)
        params = load_params_npz(path, template)
        sharded = jax.tree.map(
            lambda x, spec: jax.device_put(
                jnp.asarray(x), NamedSharding(self.mesh, spec)),
            params, self.param_specs)
        self.state = self.state.replace(params=sharded)

    def compile_iter_fns(self, sync_type: str = "avg") -> None:
        """TP path: plain jit, shardings from the committed arrays.
        The global-batch mean gradient IS the averaged (``avg``)
        exchange; ``cdd`` (the reference's summed exchange, used with a
        pre-scaled LR) is realized by scaling grads by the data-axis
        size."""
        from theanompi_tpu.parallel.mesh import data_axis_size
        from theanompi_tpu.parallel.tensor import (
            make_gspmd_eval_step,
            make_gspmd_multi_step,
            make_gspmd_train_step,
        )

        scale = float(data_axis_size(self.mesh)) if sync_type == "cdd" \
            else 1.0
        self.train_step = make_gspmd_train_step(self.loss_fn, self.tx,
                                                grad_scale=scale)
        if self.config.steps_per_call > 1:
            self.train_step_multi = make_gspmd_multi_step(
                self.loss_fn, self.tx, grad_scale=scale)
        self.eval_step = make_gspmd_eval_step(self.eval_fn)

