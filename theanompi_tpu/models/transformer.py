"""Causal transformer LM — the long-context / sequence-parallel
flagship.

Not a reference-parity model (the reference predates attention,
SURVEY.md §2.11/§5.7); this is the model family that exercises the
framework's first-class long-context path: the TIME dimension is
sharded over the mesh's ``seq`` axis and attention runs via
``parallel.sequence`` (ring / all-gather / ulysses), so context length
scales with chips.  Everything else rides the same spine as the CNN
zoo — the model keeps the full reference contract and trains through
``run_bsp_session`` with the batch sharded ``P('data', 'seq')`` and
gradients exchanged over BOTH axes.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from theanompi_tpu.data.lm import SeqLM_data
from theanompi_tpu.models import layers as L
from theanompi_tpu.models.base import ModelConfig, TpuModel
from theanompi_tpu.parallel.mesh import (
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_PIPE,
    AXIS_SEQ,
)
from theanompi_tpu.ops.attention import fused_attention
from theanompi_tpu.parallel.sequence import (
    sequence_attention,
)

#: param-tree keys whose tensors are NOT applied as per-token matmuls —
#: the embedding gather and the positional add contribute ~0 FLOPs, and
#: the standard 6N convention drops them
_NON_MATMUL_KEYS = frozenset({"embedding", "pos_emb"})


def _lm_train_flops(params, n_layers: int, seq_len: int, d_model: int,
                    expert_mask=None, n_experts: int = 1) -> float:
    """Trained FLOPs per SAMPLE (= per sequence) in the 2xMAC units the
    CNN zoo and the chip-rate probes share: the standard 6·n_active
    per trained token (fwd 2 + bwd 4) over matmul-applied params —
    embedding/positional tables are excluded (gather + add, ~0 FLOPs)
    — plus the attention score/PV term 12·n_layers·L²·d the
    param-proportional term misses.  Computed from the REAL param count
    so CLI-resized and sharded variants stay honest; with top-1 routing
    only 1/n_experts of each expert tensor is active per token (pass
    the MoE's ``expert_mask``)."""
    from jax import tree_util as jtu

    flat = jtu.tree_flatten_with_path(params)[0]
    flags = (jax.tree.leaves(expert_mask) if expert_mask is not None
             else [False] * len(flat))
    active = 0
    for (path, leaf), is_exp in zip(flat, flags):
        keys = {getattr(k, "key", None) for k in path} | \
               {getattr(k, "name", None) for k in path}
        if keys & _NON_MATMUL_KEYS:
            continue
        active += int(leaf.size) // (n_experts if is_exp else 1)
    return float(6 * active * seq_len
                 + 12 * n_layers * seq_len * seq_len * d_model)


class Block(nn.Module):
    """Pre-LN transformer block with sequence-parallel attention.

    Round-2 note: the attention projections are three named Dense
    modules (``q_proj``/``k_proj``/``v_proj``), not one fused qkv —
    required for clean tensor-parallel column sharding.  This changed
    the param tree (old ``Dense_N`` snapshots no longer load) and the
    per-projection xavier fan differs from the fused kernel's, so
    pre-change training curves are not bit-reproducible."""

    d_model: int
    n_heads: int
    d_ff: int
    sp_strategy: str = "ring"
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, seq_axis: str | None = None):
        b, t, _ = x.shape
        d_head = self.d_model // self.n_heads
        h = nn.LayerNorm(dtype=self.dtype)(x)
        # separate (named) Q/K/V projections: under tensor parallelism
        # each is column-sharded over 'model' so every head's Q, K and
        # V live on ONE shard — a fused qkv kernel sharded in
        # contiguous chunks would straddle the split points and force
        # an all-to-all per block (parallel/tensor.py rules)
        proj = lambda name: nn.Dense(  # noqa: E731
            self.d_model, use_bias=False, kernel_init=L.xavier_init(),
            dtype=self.dtype, name=name)(h)
        shape = (b, t, self.n_heads, d_head)
        q = proj("q_proj").reshape(shape)
        k = proj("k_proj").reshape(shape)
        v = proj("v_proj").reshape(shape)
        if seq_axis is not None:
            o = sequence_attention(q, k, v, axis_name=seq_axis, causal=True,
                                   strategy=self.sp_strategy)
        else:
            # full local attention: the fused Pallas kernel on TPU
            # (ops/attention.py; XLA oracle elsewhere/oversize)
            o = fused_attention(q, k, v, causal=True)
        o = o.reshape((b, t, self.d_model))
        x = x + nn.Dense(self.d_model, use_bias=False,
                         kernel_init=L.xavier_init(), dtype=self.dtype,
                         name="o_proj")(o)
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = nn.Dense(self.d_ff, kernel_init=L.he_init(), dtype=self.dtype,
                     name="mlp_up")(h)
        h = nn.gelu(h)
        x = x + nn.Dense(self.d_model, kernel_init=L.xavier_init(),
                         dtype=self.dtype, name="mlp_down")(h)
        return x


class TransformerLMNet(nn.Module):
    """Token ids (B, T_local) -> logits (B, T_local, vocab).

    ``seq_axis`` is a CALL-time argument (not a module field) so the
    same parameters serve both the sharded training path (inside
    shard_map, where positions offset by the shard index) and
    unsharded init/inference.
    """

    vocab: int = 256
    n_layers: int = 2
    d_model: int = 128
    n_heads: int = 4
    d_ff: int = 512
    max_len: int = 2048
    sp_strategy: str = "ring"
    dtype: jnp.dtype = jnp.float32
    #: jax.checkpoint each block: recompute activations in the
    #: backward instead of storing them (ModelConfig.remat)
    remat: bool = False

    @nn.compact
    def __call__(self, tokens, train: bool = False,
                 seq_axis: str | None = None):
        t_local = tokens.shape[1]
        offset = (lax.axis_index(seq_axis) * t_local
                  if seq_axis is not None else 0)
        x = nn.Embed(self.vocab, self.d_model,
                     embedding_init=L.gaussian_init(0.02))(tokens)
        pos_emb = self.param("pos_emb", L.gaussian_init(0.02),
                             (self.max_len, self.d_model))
        x = x + lax.dynamic_slice_in_dim(pos_emb, offset, t_local)[None]
        x = x.astype(self.dtype)
        # static_argnums counts the bound method's args with the module
        # at 0, so seq_axis (a mesh-axis NAME, not data) is arg 2.
        # Explicit names pin the param tree to the non-remat layout
        # (nn.remat's class rename would otherwise key params under
        # CheckpointBlock_i, breaking snapshots and the TP specs).
        block_cls = (nn.remat(Block, static_argnums=(2,))
                     if self.remat else Block)
        for i in range(self.n_layers):
            x = block_cls(self.d_model, self.n_heads, self.d_ff,
                          self.sp_strategy, self.dtype,
                          name=f"Block_{i}")(x, seq_axis)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        logits = nn.Dense(self.vocab, kernel_init=L.xavier_init(),
                          dtype=self.dtype)(x)
        return logits.astype(jnp.float32)


class TransformerLM(TpuModel):
    """LM over (data x seq)-sharded batches; reference model contract."""

    name = "transformer_lm"
    sp_strategy = "ring"
    batch_partition = P(AXIS_DATA, AXIS_SEQ)   # (B, T) over (data, seq)
    #: mesh axis the TIME dimension is sharded over inside the step
    #: (None = full attention; the TP variant sets None)
    seq_axis: str | None = AXIS_SEQ
    #: exports of this family may serve the autoregressive decode path
    #: (theanompi_tpu/decode — single-flax-module param tree; the
    #: PP/MoE variants assemble diverging trees and stay eval-only)
    decode_capable = True

    @classmethod
    def default_config(cls) -> ModelConfig:
        return ModelConfig(
            batch_size=16,
            n_epochs=5,
            learning_rate=0.1,
            momentum=0.9,
            weight_decay=0.0,
            lr_schedule="constant",
            print_freq=20,
        )

    def __init__(self, *args, vocab: int = 256, seq_len: int = 128,
                 n_layers: int = 2, d_model: int = 128, n_heads: int = 4,
                 **kwargs):
        self._net_cfg = dict(vocab=vocab, seq_len=seq_len, n_layers=n_layers,
                             d_model=d_model, n_heads=n_heads)
        super().__init__(*args, **kwargs)
        self.train_flops_per_sample = _lm_train_flops(
            self.state.params, n_layers, seq_len, d_model)

    def _input_dtype(self):
        return jnp.int32

    def _resolved_seq_axis(self) -> str | None:
        """The seq axis the step should ACTUALLY shard time over.

        A size-1 ``seq`` axis (any pure-DP mesh — ``data_mesh`` always
        carries all five named axes) must degrade to ``None`` so
        attention takes the fused local path (ops/attention.py Pallas
        kernel) instead of ``ring_attention`` with a 1-hop ring, which
        materializes the FULL (B, H, T, T) score matrix per block: at
        b=16 t=2048 that was 768 MB of HLO temp PER BLOCK — the
        round-3 on-chip lm_b16_s2048 OOM — and a throughput hit at
        every size.  Ring-with-n=1 and full attention are the same
        math, so this is a routing fix, not a semantics change
        (equivalence covered by tests/test_transformer_sp.py).
        """
        ax = self.seq_axis
        if ax is None or self.mesh is None:
            return ax
        return ax if dict(self.mesh.shape).get(ax, 1) > 1 else None

    def build_data(self):
        c = self._net_cfg
        return SeqLM_data(vocab=c["vocab"], seq_len=c["seq_len"],
                          seed=self.config.seed)

    def build_module(self) -> nn.Module:
        c = self._net_cfg
        return TransformerLMNet(
            vocab=c["vocab"], n_layers=c["n_layers"], d_model=c["d_model"],
            n_heads=c["n_heads"], d_ff=4 * c["d_model"],
            max_len=max(2048, c["seq_len"]), sp_strategy=self.sp_strategy,
            dtype=self._compute_dtype(), remat=self.config.remat)

    # -- (data x seq) SPMD wiring -------------------------------------------

    def loss_fn(self, params, model_state, batch, rng):
        tokens, targets = batch
        logits = self.module.apply({"params": params}, tokens, train=True,
                                   seq_axis=self._resolved_seq_axis(),
                                   rngs={"dropout": rng})
        v = logits.shape[-1]
        loss = L.softmax_cross_entropy(logits.reshape(-1, v),
                                       targets.reshape(-1),
                                       self.config.label_smoothing)
        err = L.error_rate(logits.reshape(-1, v), targets.reshape(-1))
        return loss, (model_state, {"loss": loss, "error": err})

    def eval_fn(self, params, model_state, batch):
        tokens, targets = batch
        logits = self.module.apply({"params": params}, tokens, train=False,
                                   seq_axis=self._resolved_seq_axis())
        v = logits.shape[-1]
        return {"loss": L.softmax_cross_entropy(logits.reshape(-1, v),
                                                targets.reshape(-1)),
                "error": L.error_rate(logits.reshape(-1, v),
                                      targets.reshape(-1))}


class TransformerLM_TP(TransformerLM):
    """Tensor-parallel LM over a (data x model) mesh.

    Megatron-style TP the GSPMD way (parallel/tensor.py): block
    weights are sharded over ``model`` (Q/K/V/MLP-up column-wise,
    attn-out/MLP-down row-wise), the step is ONE plain jit and the
    compiler inserts every collective — both the TP all-reduces and
    the data-axis gradient all-reduce.  Attention runs unsharded in
    time (``seq_axis=None``); heads are what ``model`` splits, so this
    composes with DP, not SP.
    """

    name = "transformer_lm_tp"
    batch_partition = P(AXIS_DATA)   # tokens (B, T): batch over 'data'
    seq_axis = None                  # full attention; 'model' splits heads

    def _create_state(self, params, model_state):
        """Shard params per the Megatron specs and build the optimizer
        state FROM the sharded tree — full-size momentum buffers never
        exist on any device."""
        from theanompi_tpu.parallel.mesh import AXIS_MODEL
        from theanompi_tpu.parallel.tensor import (
            shard_train_state,
            transformer_tp_specs,
        )

        tp = self.mesh.shape[AXIS_MODEL]
        c = self._net_cfg
        d_ff = 4 * c["d_model"]
        if c["n_heads"] % tp or d_ff % tp:
            raise ValueError(
                f"tensor parallelism {tp} must divide n_heads="
                f"{c['n_heads']} and d_ff={d_ff}: otherwise heads/hidden "
                "straddle shards and GSPMD silently inserts per-block "
                "reshards instead of the Megatron pattern")
        self.param_specs = transformer_tp_specs(params)
        return shard_train_state(params, model_state, self.mesh,
                                 self.param_specs, self.tx)

    # load()/adopt_restored_state(): the base implementations re-place
    # per self.param_specs (models/base.py) — nothing TP-specific left

    def compile_iter_fns(self, sync_type: str = "avg") -> None:
        """TP path: plain jit, shardings from the committed arrays.
        The global-batch mean gradient IS the averaged (``avg``)
        exchange; ``cdd`` (the reference's summed exchange, used with a
        pre-scaled LR) is realized by scaling grads by the data-axis
        size."""
        from theanompi_tpu.parallel.mesh import data_axis_size
        from theanompi_tpu.parallel.tensor import (
            make_gspmd_eval_step,
            make_gspmd_multi_step,
            make_gspmd_train_step,
        )

        self._reject_grad_accum("GSPMD tensor-parallel step")
        self._reject_zero_sharding("GSPMD tensor-parallel step (its "
                                   "optimizer state is already sharded "
                                   "like the params)")
        scale = float(data_axis_size(self.mesh)) if sync_type == "cdd" \
            else 1.0
        self.train_step = make_gspmd_train_step(self.loss_fn, self.tx,
                                                grad_scale=scale)
        if self.config.steps_per_call > 1:
            self.train_step_multi = make_gspmd_multi_step(
                self.loss_fn, self.tx, grad_scale=scale)
        self.eval_step = make_gspmd_eval_step(self.eval_fn)



class TransformerLM_PP(TpuModel):
    """Pipeline-parallel LM over a (data x pipe) mesh (GPipe-style).

    The blocks live STACKED on a leading layer axis sharded
    ``P('pipe')`` — each stage owns ``n_layers / pipe`` blocks — and
    microbatches flow stage-to-stage via ``ppermute`` inside the
    jitted step (parallel/pipeline.py); jax transposes the schedule
    for the backward pass.  Embedding/positional tables are replicated
    and their gradients psum-ed over ``pipe`` (only stage 0's compute
    path touches them); the final norm + LM head run identically on
    every stage from the broadcast pipeline output.

    Like the WGAN, this model diverges from the single-flax-module
    TrainState path, so it assembles its pieces on the shared
    ``_init_scaffold`` (models/base.py).
    """

    name = "transformer_lm_pp"
    batch_partition = P(AXIS_DATA)

    @classmethod
    def default_config(cls) -> ModelConfig:
        return TransformerLM.default_config()

    def __init__(self, config: ModelConfig | None = None, mesh=None,
                 verbose: bool = True, shard_rank: int = 0,
                 shard_size: int = 1, data=None, vocab: int = 256,
                 seq_len: int = 128, n_layers: int = 4, d_model: int = 128,
                 n_heads: int = 4, n_microbatches: int = 4):
        self._net_cfg = dict(vocab=vocab, seq_len=seq_len,
                             n_layers=n_layers, d_model=d_model,
                             n_heads=n_heads)
        self.n_microbatches = n_microbatches
        self._init_scaffold(config, mesh, verbose, shard_rank, shard_size,
                            data)
        n_stages = self.mesh.shape[AXIS_PIPE]
        if n_layers % n_stages != 0:
            raise ValueError(f"n_layers={n_layers} not divisible by "
                             f"pipe={n_stages} stages")
        local_batch = self.global_batch // self.mesh.shape[AXIS_DATA]
        if local_batch % n_microbatches != 0:
            raise ValueError(
                f"per-data-shard batch {local_batch} not divisible by "
                f"{n_microbatches} microbatches")

        from theanompi_tpu.parallel.pipeline import stack_stages
        from theanompi_tpu.parallel.tensor import shard_train_state

        dtype = self._compute_dtype()
        d = d_model
        self.embed_mod = nn.Embed(vocab, d,
                                  embedding_init=L.gaussian_init(0.02))
        self.block_mod = Block(d, n_heads, 4 * d, dtype=dtype)
        self.ln_mod = nn.LayerNorm(dtype=dtype)
        self.head_mod = nn.Dense(vocab, kernel_init=L.xavier_init(),
                                 dtype=dtype)

        rng = jax.random.key(self.config.seed)
        tok = jnp.zeros((2, seq_len), jnp.int32)
        x = jnp.zeros((2, seq_len, d), jnp.float32)
        params = {
            "embed": self.embed_mod.init(rng, tok)["params"],
            "pos_emb": L.gaussian_init(0.02)(
                jax.random.fold_in(rng, 1), (seq_len, d)),
            "blocks": stack_stages([
                self.block_mod.init(jax.random.fold_in(rng, 10 + i),
                                    x)["params"]
                for i in range(n_layers)]),
            "ln_f": self.ln_mod.init(rng, x)["params"],
            "head": self.head_mod.init(jax.random.fold_in(rng, 2),
                                       x)["params"],
        }
        self.tx = self._build_optimizer(self._base_lr)
        self.param_specs = jax.tree_util.tree_map_with_path(
            lambda path, leaf: (P(AXIS_PIPE)
                                if getattr(path[0], "key", None) == "blocks"
                                else P()),
            params)
        # stage params sharded over 'pipe' from the start; optimizer
        # state built from the sharded tree (parallel/tensor.py)
        self.state = shard_train_state(params, {}, self.mesh,
                                       self.param_specs, self.tx)
        self.train_flops_per_sample = _lm_train_flops(
            params, n_layers, seq_len, d_model)
        # masked-loss convention: every param NOT owned per-stage has
        # real grads on exactly one stage (embeddings on stage 0 via
        # the inject path, head/ln_f on the last via the masked loss)
        # and zeros elsewhere -> psum over 'pipe' syncs the replicas
        self.pipe_psum_mask = jax.tree_util.tree_map_with_path(
            lambda path, leaf: getattr(path[0], "key", None) != "blocks",
            params)

    def _input_dtype(self):
        return jnp.int32

    def build_data(self):
        c = self._net_cfg
        return SeqLM_data(vocab=c["vocab"], seq_len=c["seq_len"],
                          seed=self.config.seed)

    # -- forward through the pipeline (runs inside shard_map) ---------------

    def _forward(self, params, tokens):
        from theanompi_tpu.parallel.pipeline import pipeline_apply

        b, t = tokens.shape
        d = self._net_cfg["d_model"]
        x = self.embed_mod.apply({"params": params["embed"]}, tokens)
        x = x + params["pos_emb"][None, :t]
        x = x.astype(self._compute_dtype())
        m = self.n_microbatches
        xm = x.reshape(m, b // m, t, d)

        def stage_fn(stage_params, h):
            def body(carry, layer_params):
                out = self.block_mod.apply({"params": layer_params}, carry,
                                           seq_axis=None)
                return out, None

            h, _ = lax.scan(body, h, stage_params)
            return h

        outs = pipeline_apply(stage_fn, params["blocks"], xm,
                              axis_name=AXIS_PIPE)
        h = outs.reshape(b, t, d)
        h = self.ln_mod.apply({"params": params["ln_f"]}, h)
        logits = self.head_mod.apply({"params": params["head"]}, h)
        return logits.astype(jnp.float32)

    def loss_fn(self, params, model_state, batch, rng):
        from theanompi_tpu.parallel.pipeline import last_stage_mask

        del rng  # no dropout in the block
        tokens, targets = batch
        logits = self._forward(params, tokens)
        v = logits.shape[-1]
        # masked-loss convention (parallel/pipeline.py): seed the
        # backward on the last stage only; the step psums metrics and
        # the single-stage params' grads over 'pipe'
        mask = last_stage_mask()
        loss = mask * L.softmax_cross_entropy(
            logits.reshape(-1, v), targets.reshape(-1),
            self.config.label_smoothing)
        err = mask * L.error_rate(logits.reshape(-1, v),
                                  targets.reshape(-1))
        return loss, (model_state, {"loss": loss, "error": err})

    def eval_fn(self, params, model_state, batch):
        from theanompi_tpu.parallel.pipeline import last_stage_mask

        tokens, targets = batch
        logits = self._forward(params, tokens)
        v = logits.shape[-1]
        mask = last_stage_mask()
        return {"loss": mask * L.softmax_cross_entropy(
                    logits.reshape(-1, v), targets.reshape(-1)),
                "error": mask * L.error_rate(logits.reshape(-1, v),
                                             targets.reshape(-1))}

    def compile_iter_fns(self, sync_type: str = "avg") -> None:
        from theanompi_tpu.parallel.bsp import TrainState
        from theanompi_tpu.parallel.mesh import data_axis_size
        from theanompi_tpu.parallel.pipeline import (
            make_pp_eval_step,
            make_pp_train_step,
        )
        from theanompi_tpu.parallel.tensor import opt_state_specs

        self._reject_grad_accum("pipeline/expert step")
        self._reject_zero_sharding("pipeline/expert step")
        if self.config.steps_per_call > 1:
            raise ValueError("steps_per_call>1 is not implemented for the "
                             "pipeline-parallel path")
        state_specs = TrainState(
            step=P(),
            params=self.param_specs,
            opt_state=opt_state_specs(self.tx, self.state.opt_state,
                                      self.param_specs),
            model_state={},
        )
        scale = float(data_axis_size(self.mesh)) if sync_type == "cdd" \
            else 1.0
        self.train_step = make_pp_train_step(
            self.loss_fn, self.tx, self.mesh, state_specs,
            self.pipe_psum_mask, batch_partition=self.batch_partition,
            grad_scale=scale)
        self.eval_step = make_pp_eval_step(
            self.eval_fn, self.mesh, state_specs,
            batch_partition=self.batch_partition)


class AttnBlock(nn.Module):
    """Pre-LN attention sublayer (LN + q/k/v/o + residual) — the
    attention half of ``Block``, reused by the MoE variant whose FFN
    half is the expert-parallel switch layer."""

    d_model: int
    n_heads: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, t, _ = x.shape
        d_head = self.d_model // self.n_heads
        h = nn.LayerNorm(dtype=self.dtype)(x)
        proj = lambda name: nn.Dense(  # noqa: E731
            self.d_model, use_bias=False, kernel_init=L.xavier_init(),
            dtype=self.dtype, name=name)(h)
        shape = (b, t, self.n_heads, d_head)
        o = fused_attention(proj("q_proj").reshape(shape),
                            proj("k_proj").reshape(shape),
                            proj("v_proj").reshape(shape), causal=True)
        o = o.reshape((b, t, self.d_model))
        return x + nn.Dense(self.d_model, use_bias=False,
                            kernel_init=L.xavier_init(), dtype=self.dtype,
                            name="o_proj")(o)


class TransformerLM_MoE(TpuModel):
    """Switch-MoE LM over a (data x expert) mesh.

    Every layer's FFN is a top-1-routed mixture of ``n_experts``
    expert MLPs, sharded over the ``expert`` axis (each shard owns
    ``n_experts / ep``); tokens reach their expert and return via
    ``lax.all_to_all`` inside the jitted step (parallel/expert.py).
    The batch is sharded over BOTH (data, expert) — the expert axis
    doubles as data parallelism outside the MoE layers, the standard
    TPU MoE topology.  Router load balancing uses the switch aux loss.

    Like the WGAN/PP models, diverges from the single-flax-module
    state path and assembles on ``_init_scaffold``.
    """

    name = "transformer_lm_moe"
    batch_partition = P((AXIS_DATA, AXIS_EXPERT))

    @classmethod
    def default_config(cls) -> ModelConfig:
        return TransformerLM.default_config()

    def __init__(self, config: ModelConfig | None = None, mesh=None,
                 verbose: bool = True, shard_rank: int = 0,
                 shard_size: int = 1, data=None, vocab: int = 256,
                 seq_len: int = 128, n_layers: int = 2, d_model: int = 128,
                 n_heads: int = 4, n_experts: int = 8,
                 capacity_factor: float = 1.25, aux_weight: float = 0.01):
        from theanompi_tpu.parallel.mesh import AXIS_EXPERT as AE

        self._net_cfg = dict(vocab=vocab, seq_len=seq_len,
                             n_layers=n_layers, d_model=d_model,
                             n_heads=n_heads)
        self.n_experts = n_experts
        self.capacity_factor = capacity_factor
        self.aux_weight = aux_weight
        self._init_scaffold(config, mesh, verbose, shard_rank, shard_size,
                            data)
        ep = self.mesh.shape[AE]
        if n_experts % ep != 0:
            raise ValueError(f"n_experts={n_experts} not divisible by "
                             f"expert-parallel degree {ep}")
        # tokens ride BOTH axes; recompute the data-parallel width AND
        # everything derived from it — notably the worker-scaled LR,
        # which _init_scaffold computed from the data axis alone
        self.n_workers = self.mesh.shape[AXIS_DATA] * ep
        self.global_batch = self.batch_size * self.n_workers
        if self.config.lr_scale_with_workers:
            from theanompi_tpu.utils.helper_funcs import scale_lr

            self._base_lr = scale_lr(self.config.learning_rate,
                                     self.n_workers,
                                     self.config.lr_scale_with_workers)

        from theanompi_tpu.parallel.tensor import shard_train_state

        dtype = self._compute_dtype()
        d, ff = d_model, 4 * d_model
        self.attn_mod = AttnBlock(d, n_heads, dtype=dtype)
        self.ln_mod = nn.LayerNorm(dtype=dtype)
        self.head_mod = nn.Dense(vocab, kernel_init=L.xavier_init(),
                                 dtype=dtype)
        self.embed_mod = nn.Embed(vocab, d,
                                  embedding_init=L.gaussian_init(0.02))

        rng = jax.random.key(self.config.seed)
        tok = jnp.zeros((2, seq_len), jnp.int32)
        x = jnp.zeros((2, seq_len, d), jnp.float32)

        def expert_init(key, layer):
            k1, k2 = jax.random.split(jax.random.fold_in(key, layer))
            he = (2.0 / d) ** 0.5
            xa = (6.0 / (ff + d)) ** 0.5
            return {
                "up_kernel": he * jax.random.normal(
                    k1, (n_experts, d, ff), jnp.float32),
                "up_bias": jnp.zeros((n_experts, ff), jnp.float32),
                "down_kernel": jax.random.uniform(
                    k2, (n_experts, ff, d), jnp.float32, -xa, xa),
                "down_bias": jnp.zeros((n_experts, d), jnp.float32),
            }

        params = {
            "embed": self.embed_mod.init(rng, tok)["params"],
            "pos_emb": L.gaussian_init(0.02)(
                jax.random.fold_in(rng, 1), (seq_len, d)),
            "attn": [self.attn_mod.init(jax.random.fold_in(rng, 10 + i),
                                        x)["params"]
                     for i in range(n_layers)],
            "moe_ln": [self.ln_mod.init(rng, x)["params"]
                       for _ in range(n_layers)],
            "router": [L.gaussian_init(0.02)(
                jax.random.fold_in(rng, 100 + i), (d, n_experts))
                for i in range(n_layers)],
            "experts": [expert_init(jax.random.fold_in(rng, 200), i)
                        for i in range(n_layers)],
            "ln_f": self.ln_mod.init(rng, x)["params"],
            "head": self.head_mod.init(jax.random.fold_in(rng, 2),
                                       x)["params"],
        }
        self.tx = self._build_optimizer(self._base_lr)

        def leaf_spec(path, leaf):
            in_experts = any(getattr(k, "key", None) == "experts"
                             for k in path)
            return P(AE) if in_experts else P()

        self.param_specs = jax.tree_util.tree_map_with_path(leaf_spec,
                                                            params)
        self.expert_mask = jax.tree_util.tree_map_with_path(
            lambda path, leaf: any(getattr(k, "key", None) == "experts"
                                   for k in path), params)
        self.state = shard_train_state(params, {}, self.mesh,
                                       self.param_specs, self.tx)
        self.train_flops_per_sample = _lm_train_flops(
            params, n_layers, seq_len, d_model,
            expert_mask=self.expert_mask, n_experts=n_experts)

    def _input_dtype(self):
        return jnp.int32

    def build_data(self):
        c = self._net_cfg
        return SeqLM_data(vocab=c["vocab"], seq_len=c["seq_len"],
                          seed=self.config.seed)

    # -- forward (runs inside shard_map over the (data, expert) axes) -------

    def _forward(self, params, tokens):
        from theanompi_tpu.parallel.expert import moe_ffn
        from theanompi_tpu.parallel.mesh import AXIS_EXPERT as AE

        b, t = tokens.shape
        d = self._net_cfg["d_model"]
        x = self.embed_mod.apply({"params": params["embed"]}, tokens)
        x = (x + params["pos_emb"][None, :t]).astype(self._compute_dtype())

        def apply_expert(p, tok):
            h = jnp.maximum(tok @ p["up_kernel"] + p["up_bias"], 0.0)
            return h @ p["down_kernel"] + p["down_bias"]

        aux_total = 0.0
        for layer in range(self._net_cfg["n_layers"]):
            x = self.attn_mod.apply({"params": params["attn"][layer]}, x)
            h = self.ln_mod.apply({"params": params["moe_ln"][layer]}, x)
            out, aux = moe_ffn(h.reshape(b * t, d), params["router"][layer],
                               params["experts"][layer], apply_expert,
                               capacity_factor=self.capacity_factor,
                               axis_name=AE)
            x = x + out.reshape(b, t, d)
            aux_total = aux_total + aux
        h = self.ln_mod.apply({"params": params["ln_f"]}, x)
        logits = self.head_mod.apply({"params": params["head"]}, h)
        return logits.astype(jnp.float32), aux_total

    def loss_fn(self, params, model_state, batch, rng):
        del rng
        tokens, targets = batch
        logits, aux = self._forward(params, tokens)
        v = logits.shape[-1]
        ce = L.softmax_cross_entropy(logits.reshape(-1, v),
                                     targets.reshape(-1),
                                     self.config.label_smoothing)
        err = L.error_rate(logits.reshape(-1, v), targets.reshape(-1))
        loss = ce + self.aux_weight * aux / self._net_cfg["n_layers"]
        return loss, (model_state, {"loss": ce, "error": err,
                                    "aux": aux})

    def eval_fn(self, params, model_state, batch):
        tokens, targets = batch
        logits, _ = self._forward(params, tokens)
        v = logits.shape[-1]
        return {"loss": L.softmax_cross_entropy(logits.reshape(-1, v),
                                                targets.reshape(-1)),
                "error": L.error_rate(logits.reshape(-1, v),
                                      targets.reshape(-1))}

    def compile_iter_fns(self, sync_type: str = "avg") -> None:
        from theanompi_tpu.parallel.bsp import TrainState
        from theanompi_tpu.parallel.expert import (
            make_moe_eval_step,
            make_moe_train_step,
        )
        from theanompi_tpu.parallel.tensor import opt_state_specs

        self._reject_grad_accum("pipeline/expert step")
        self._reject_zero_sharding("pipeline/expert step")
        if self.config.steps_per_call > 1:
            raise ValueError("steps_per_call>1 is not implemented for the "
                             "expert-parallel path")
        state_specs = TrainState(
            step=P(),
            params=self.param_specs,
            opt_state=opt_state_specs(self.tx, self.state.opt_state,
                                      self.param_specs),
            model_state={},
        )
        expert_mask_state = self.expert_mask
        scale = (float(self.n_workers) if sync_type == "cdd" else 1.0)
        self.train_step = make_moe_train_step(
            self.loss_fn, self.tx, self.mesh, state_specs,
            expert_mask_state, batch_partition=self.batch_partition,
            grad_scale=scale)
        self.eval_step = make_moe_eval_step(
            self.eval_fn, self.mesh, state_specs,
            batch_partition=self.batch_partition)
