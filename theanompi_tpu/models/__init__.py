"""Model zoo registry (reference ``theanompi/models/`` — SURVEY.md §2.8).

Models import lazily by (modulepath, classname) through
``theanompi_tpu.rules.resolve_model_class``; this table is the
discovery surface for launchers and docs.
"""

MODEL_ZOO = {
    "cifar10": ("theanompi_tpu.models.cifar10", "Cifar10_model"),
    "alexnet": ("theanompi_tpu.models.alex_net", "AlexNet"),
    "googlenet": ("theanompi_tpu.models.googlenet", "GoogLeNet"),
    "vgg16": ("theanompi_tpu.models.vgg16", "VGG16"),
    "resnet50": ("theanompi_tpu.models.resnet50", "ResNet50"),
    "wgan": ("theanompi_tpu.models.wasserstein_gan", "Wasserstein_GAN"),
    # beyond reference parity: long-context sequence-parallel LM
    "transformer_lm": ("theanompi_tpu.models.transformer", "TransformerLM"),
    "transformer_lm_tp": ("theanompi_tpu.models.transformer",
                          "TransformerLM_TP"),
    "transformer_lm_pp": ("theanompi_tpu.models.transformer",
                          "TransformerLM_PP"),
    "transformer_lm_moe": ("theanompi_tpu.models.transformer",
                           "TransformerLM_MoE"),
    # zoo variants (reference lasagne_model_zoo equivalents)
    "vgg19": ("theanompi_tpu.models.model_zoo", "VGG19"),
    "resnet101": ("theanompi_tpu.models.model_zoo", "ResNet101"),
    "resnet152": ("theanompi_tpu.models.model_zoo", "ResNet152"),
    # the modern large-batch recipe (LARS + warmup/cosine + s2d stem)
    "resnet50_large": ("theanompi_tpu.models.model_zoo",
                       "ResNet50_LargeBatch"),
}

__all__ = ["MODEL_ZOO"]
