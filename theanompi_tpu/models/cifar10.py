"""Cifar10 CNN — the smoke-test model (bundled recipe #1: single-worker
BSP, CPU-runnable; BASELINE.json configs[0]).

Parity counterpart of the reference's ``theanompi/models/cifar10.py``
(SURVEY.md §2.8 — mount empty, no file:line): a cuda-convnet-style
small CNN — conv/pool stacks with LRN, two dense layers, softmax —
SGD+momentum, step LR decay.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from theanompi_tpu.data.cifar10 import Cifar10_data
from theanompi_tpu.models import layers as L
from theanompi_tpu.models.base import ModelConfig, TpuModel


class Cifar10CNN(nn.Module):
    n_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = L.Conv(32, (5, 5), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = L.max_pool(x, 3, 2)
        x = L.LRN(n=3, k=1.0, alpha=5e-5, beta=0.75)(x)
        x = L.Conv(32, (5, 5), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = L.avg_pool(x, 3, 2)
        x = L.LRN(n=3, k=1.0, alpha=5e-5, beta=0.75)(x)
        x = L.Conv(64, (5, 5), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = L.avg_pool(x, 3, 2)
        x = x.reshape((x.shape[0], -1))
        x = L.Dense(64, kernel_init=L.he_init())(x)
        x = nn.relu(x)
        x = L.Dense(self.n_classes, kernel_init=L.gaussian_init(0.01))(x)
        return x.astype(jnp.float32)


class Cifar10_model(TpuModel):
    name = "cifar10"

    @classmethod
    def default_config(cls) -> ModelConfig:
        return ModelConfig(
            batch_size=128,
            n_epochs=70,
            learning_rate=0.01,
            momentum=0.9,
            weight_decay=1e-4,
            lr_schedule="step",
            lr_decay_epochs=(50, 60),
            lr_decay_factor=0.1,
            print_freq=40,
        )

    def build_module(self) -> nn.Module:
        return Cifar10CNN(dtype=self._compute_dtype())

    def build_data(self):
        return Cifar10_data(data_dir=self.config.data_dir,
                            seed=self.config.seed,
                            augment_on_device=self.config.augment_on_device)
