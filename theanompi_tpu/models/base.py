"""Model contract + generic training machinery.

The reference consumed a duck-typed model contract from every rule
(``self.params``, ``self.data``, ``batch_size``, ``n_epochs``;
``compile_iter_fns(sync_type)``, ``train_iter(count, recorder)``,
``val_iter(count, recorder)``, ``adjust_hyperp(epoch)``,
``save``/``load``, ``cleanup`` — reference ``theanompi/models/*.py``,
SURVEY.md §2.8; mount empty, no file:line).  This module keeps that
contract — it is the API-parity surface the rules and launchers see —
but implements it once, TPU-natively:

* ``compile_iter_fns`` builds ONE jitted SPMD step (forward + backward
  + psum exchange + update fused; XLA overlaps the ICI collectives
  with backprop) instead of compiling per-worker Theano functions and
  pairing them with a post-hoc exchanger.
* ``train_iter`` consumes mesh-sharded device batches from a
  double-buffered prefetcher and dispatches asynchronously; metrics
  are fetched in windows (every ``print_freq`` iters) so the host
  never serializes the device pipeline.
* The reference's 'comm' recorder section is structurally zero here —
  exchange is fused into 'calc' by design; the recorder keeps the
  column for output parity.

Subclasses define the network (a flax module taking ``(x, train)``),
the dataset, and a config; everything else is inherited.
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Any, Iterator

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from theanompi_tpu.data.base import Dataset
from theanompi_tpu.data.prefetch import DevicePrefetcher
from theanompi_tpu.models.layers import (
    error_rate,
    softmax_cross_entropy,
    topk_error,
)
from theanompi_tpu.parallel.bsp import (
    TrainState,
    make_bsp_eval_step,
    make_bsp_train_step,
)
from theanompi_tpu.parallel.exchanger import BSP_Exchanger
from theanompi_tpu.parallel.mesh import (
    data_axis_size,
    data_mesh,
    host_count,
    host_rank,
    is_multiprocess,
    replicate,
)
from theanompi_tpu.utils.helper_funcs import (
    build_optimizer,
    load_params_npz,
    save_params_npz,
    scale_lr,
    set_learning_rate,
)
from theanompi_tpu.utils.recorder import Recorder

PyTree = Any


def _stack_host_batches(host_iter: Iterator, k: int) -> Iterator:
    """Group k host batches into one stacked pytree with a leading
    steps axis (the multi-step program's scan axis); drops a ragged
    tail group."""
    group = []
    for batch in host_iter:
        group.append(batch)
        if len(group) == k:
            yield jax.tree.map(lambda *xs: np.stack(xs), *group)
            group = []


@dataclasses.dataclass
class ModelConfig:
    """One config dataclass per (model, rule) pair — SURVEY.md §5.6.

    ``batch_size`` is PER data-shard (reference semantics: per-worker);
    the global batch is ``batch_size * data_axis_size(mesh)``.
    """

    batch_size: int = 128
    n_epochs: int = 70
    learning_rate: float = 0.01
    #: optimizer family (utils.helper_funcs.OPTIMIZERS): 'sgd' is the
    #: reference recipe; 'lars' is the large-batch ResNet choice,
    #: 'adamw' the transformer one
    optimizer: str = "sgd"
    momentum: float = 0.9
    nesterov: bool = False
    weight_decay: float = 1e-4
    adam_beta1: float = 0.9
    adam_beta2: float = 0.999
    adam_eps: float = 1e-8
    rmsprop_decay: float = 0.9
    lars_trust_coefficient: float = 0.001
    lr_schedule: str = "step"       # 'step' | 'constant' | 'poly' | 'cosine'
    lr_decay_epochs: tuple = (40, 60)
    lr_decay_factor: float = 0.1
    lr_poly_power: float = 1.0
    #: linear warmup over the first N epochs (0 = off), applied before
    #: the schedule proper — the standard large-batch ramp
    warmup_epochs: int = 0
    #: label smoothing eps for the classification CE (train loss only;
    #: eval reports plain CE).  0.1 in modern 90-epoch ResNet recipes
    label_smoothing: float = 0.0
    lr_scale_with_workers: str | None = None   # None | 'linear' | 'sqrt'
    exchange_strategy: str = "psum"        # reference names accepted (nccl16...)
    exchange_what: str = "grads"
    #: ICI wire dtype of the gradient exchange: 'f32' (full precision,
    #: default) or 'bf16' — gradients are quantized to bfloat16 for the
    #: psum/reduce_scatter (HALF the per-step interconnect bytes on the
    #: pod) and restored to f32 before the average and the optimizer
    #: update, so accumulation stays f32.  The modern spelling of the
    #: reference's nccl16/asa16 strategies; works for plain BSP and
    #: zero_sharding (fsdp_sharding rejects it — its collectives are
    #: compiler-inserted with no quantization seam).  Step-vs-f32
    #: deviation is bounded by bf16's 8-bit mantissa (tolerance-pinned
    #: in tests/test_exchanger.py)
    exchange_dtype: str = "f32"
    #: carry the bf16 quantization error of each shard into its next
    #: exchange (error feedback): the residual rides
    #: ``TrainState.exchange_residual`` (per-shard, f32, checkpointed)
    #: and re-injects every bit the wire dropped, so the long-run
    #: applied-gradient sum tracks the true sum to one quantization
    #: step.  Requires exchange_dtype='bf16', exchange_what='grads',
    #: and a pure-'data' reduce axis (the residual is per-DATA-shard
    #: state); costs one extra f32 param-sized buffer per device
    exchange_error_feedback: bool = False
    #: partition the gradient exchange into this many layer-ordered,
    #: byte-balanced buckets (parallel/exchanger.bucket_ranges — a pure
    #: plan every rank derives identically) and embed each bucket's
    #: collective INTO the backward DAG, so early backward segments'
    #: psums overlap the remaining segments' gradient compute
    #: (arXiv:1802.06949's bucketed collectives, expressed as
    #: custom_vjp boundary tags for XLA's latency-hiding scheduler).
    #: 1 (default) keeps the whole-tree post-backward exchange
    #: byte-identical.  Works for plain BSP (f32/bf16/error-feedback),
    #: zero_sharding (per-bucket reduce_scatter/all_to_all — NOTE the
    #: sharded opt-state/residual layout depends on the bucket count,
    #: so resume a checkpoint under the SAME value), and fsdp_sharding
    #: (scheduling fences only; GSPMD owns the collectives).  The
    #: grad-accum cadence keeps its single post-accumulation exchange,
    #: split per bucket.  B>1 is pinned step-identical to B=1 on all
    #: three planes (tests/test_exchanger.py, test_zero.py,
    #: test_fsdp.py)
    exchange_buckets: int = 1
    compute_dtype: str = "float32"         # 'bfloat16' -> MXU-friendly compute
    #: crop/flip/normalize on DEVICE (ops/augment.py) — the host ships
    #: raw uint8 and the step augments; False = host-side augmentation
    #: (the reference's loader semantics).  Honored by the ImageNet
    #: model family's build_data.
    augment_on_device: bool = True
    #: ResNet stem flavor: 'conv7' (reference geometry) or 's2d'
    #: (exact space-to-depth re-parameterization — the TPU-friendly
    #: shape for the C=3 stem conv; models/resnet50.py)
    resnet_stem: str = "conv7"
    #: stem max-pool impl: 'xla' (reduce_window; select-and-scatter
    #: backward) or 'pallas' (argmax-saving kernel with a gather
    #: backward, ops/maxpool_pallas.py — predicted ~2x fewer backward
    #: bytes from the MFU account; flip per-recipe only after
    #: tools/bench_maxpool.py confirms on chip)
    pool_impl: str = "xla"
    #: BN/activation epilogue impl: 'xla' (today's unfused composition,
    #: default) or 'pallas' (ops/fused_bn.py — ONE stream for the BN
    #: affine + residual add + relu, targeting the account's 5.81 ms of
    #: loop-fusion HBM traffic).  ResNet family: fuses every
    #: BN(+add)+relu with the param tree unchanged.  BN-free models
    #: (VGG/GoogLeNet) route their conv bias+relu epilogues through
    #: layers.BiasAct instead — NOTE that moves the bias param out of
    #: the conv scope, so their param tree depends on this knob (pick
    #: it at build time, not mid-run).  Default-off until the queued
    #: A/B account pair (tools/xla_sweep.py) confirms on chip.
    bn_act_impl: str = "xla"
    #: donate the STAGED BATCH buffers to the stacked-cadence steps
    #: (steps_per_call / grad_accum_steps programs) so XLA reuses their
    #: HBM for outputs instead of copying around live input buffers —
    #: part of the copy-done attack (the r3 account counts 1 334
    #: copy events/step).  The prefetcher stages a fresh batch per
    #: dispatch, so donation is safe on the training path; turn off
    #: when replaying the SAME staged batch through a step twice
    #: (bench.py's pre-staged device-step leg does)
    donate_batch: bool = True
    #: cross-replica BatchNorm: compute BN batch statistics over the
    #: whole DATA axis (lax.pmean inside the BN, flax ``axis_name``)
    #: instead of per-shard.  The standard TPU-pod choice when the
    #: per-core batch is small (running stats from a 4-8 image shard
    #: are too noisy to serve eval — observed as chance-level val error
    #: with converged train loss).  Per-shard BN (False) matches the
    #: reference's per-worker semantics.  Requires a shard_map step
    #: with a live 'data' axis — incompatible with fsdp_sharding
    #: (GSPMD jit has no named axes; compile_iter_fns rejects the
    #: combination).  Honored by models whose build_module() threads
    #: ``_bn_axis()`` into their BN layers: the ResNet family
    #: (resnet50.py) and — with ``batch_norm=True`` — the whole
    #: layer-toolkit zoo (VGG16/VGG19, GoogLeNet, AlexNet), which
    #: closes the round-4 advisor's wiring obligation.  A NEW zoo
    #: model using ``layers.BatchNorm`` must still pass
    #: ``self._bn_axis()`` itself.  Models that declare
    #: ``uses_batchnorm`` warn at compile when the per-shard batch is
    #: small and this is left False.
    sync_bn: bool = False
    #: build the BatchNorm variant of the layer-toolkit CNNs (the
    #: classic vgg16_bn-style configuration): every conv's bias+relu
    #: epilogue becomes ``layers.BatchNorm`` (+relu, conv bias
    #: dropped), with ``_bn_axis()`` threaded so ``sync_bn`` is
    #: honored — the ADVICE r4 wiring obligation now holds for the
    #: whole zoo (VGG16/VGG19, GoogLeNet, AlexNet), not just ResNet.
    #: The param tree changes (BatchNorm_* scale/bias + batch_stats
    #: instead of conv bias), so flip at model build, not mid-run.
    #: No-op for models that always carry BN (ResNet) or none (LM).
    batch_norm: bool = False
    #: rematerialize transformer blocks in the backward pass
    #: (jax.checkpoint): activations are recomputed instead of stored,
    #: trading ~1/3 more FLOPs for O(n_layers) less activation HBM —
    #: the knob that lets long-context training fit
    remat: bool = False
    #: scan this many training iterations into one device program
    #: (parallel/bsp.py make_bsp_multi_step) — amortizes per-dispatch
    #: tunnel overhead; 1 = one program per batch (reference cadence)
    steps_per_call: int = 1
    #: accumulate gradients over this many microbatches before ONE
    #: optimizer update (parallel/bsp.py make_bsp_accum_step): the
    #: effective global batch is grad_accum_steps * batch_size * shards
    #: at the HBM footprint of one microbatch.  Mutually exclusive with
    #: steps_per_call > 1; BSP only
    grad_accum_steps: int = 1
    #: ZeRO-1: shard the optimizer state over the data axis
    #: (parallel/zero.py — reduce_scatter grads, update the 1/N shard,
    #: all_gather params).  Step-equal to plain BSP for elementwise
    #: optimizers; BSP only, composes with the seq axis, with
    #: grad_accum_steps, and with steps_per_call (the two stacked
    #: cadences stay mutually exclusive with each other)
    zero_sharding: bool = False
    #: FSDP (ZeRO-3 class): params AND optimizer state live 1/N per
    #: device over the data axis; the step is plain global math under
    #: GSPMD — XLA inserts per-layer all-gathers before each weight's
    #: use and reduce-scatters for its grads (parallel/fsdp.py).
    #: Trajectory equals unsharded BSP exactly.  BSP only; composes
    #: with steps_per_call OR grad_accum_steps; mutually exclusive
    #: with zero_sharding (FSDP already shards strictly more)
    fsdp_sharding: bool = False
    seed: int = 42
    data_dir: str | None = None
    snapshot_dir: str = "./snapshots"
    print_freq: int = 40
    track_top5: bool = False


class TpuModel:
    """Base model implementing the reference contract over the BSP spine."""

    name = "model"
    #: how batches land on the mesh; None = leading dim over 'data'.
    #: Sequence-parallel models override (e.g. P('data', 'seq')).
    batch_partition = None
    #: trained FLOPs per sample (fwd+bwd, ~3x fwd) — models that know
    #: theirs set it so the recorder's epoch records carry achieved
    #: TFLOP/s (utils/recorder.py); None = column omitted
    train_flops_per_sample: float | None = None

    def __init__(self, config: ModelConfig | None = None, mesh=None,
                 verbose: bool = True, shard_rank: int = 0,
                 shard_size: int = 1, data: Dataset | None = None):
        self._init_scaffold(config, mesh, verbose, shard_rank, shard_size,
                            data)
        self.module: nn.Module = self.build_module()

        rng = jax.random.key(self.config.seed)
        dummy = jnp.zeros((2, *self.data.sample_shape), self._input_dtype())
        # init traces the TRAINING path so train-only parameters (e.g.
        # GoogLeNet's aux heads) are created; flax skips running-stat
        # writes while initializing, so BN state stays at its init values
        variables = self.module.init({"params": rng, "dropout": rng}, dummy,
                                     train=True)
        variables = dict(variables)
        params = variables.pop("params")
        model_state = variables  # e.g. {'batch_stats': ...} or {}

        self.tx = self._build_optimizer(self._base_lr)
        self.state = self._create_state(params, model_state)

    def _create_state(self, params, model_state) -> "TrainState":
        """Build + place the initial training state.  Default: create
        (optimizer init included) then replicate over the mesh — pure
        DP.  Parameter-sharded models (TP) override so the optimizer
        state is built directly from SHARDED params and never
        materializes full-size on any device.  ZeRO-1
        (``zero_sharding``) replicates params but builds the optimizer
        state sharded over 'data'."""
        if self.config.fsdp_sharding:
            from theanompi_tpu.parallel.fsdp import (fsdp_specs,
                                                     init_fsdp_state)

            self._check_fsdp_supported()
            # param_specs doubles as the checkpoint-resume placement
            # contract (adopt_restored_state re-places params AND the
            # optimizer's param-like buffers per these specs)
            self.param_specs = fsdp_specs(params, self.mesh)
            return init_fsdp_state(params, self.tx, model_state,
                                   self.mesh, self.param_specs)
        if self.config.zero_sharding:
            from theanompi_tpu.parallel.zero import init_zero_opt_state

            self._check_zero_supported()
            opt_state, _ = init_zero_opt_state(
                self.tx, params, self.mesh,
                exchange_buckets=self.config.exchange_buckets)
            params_r, ms_r, step_r = replicate(
                (params, model_state, jnp.zeros((), jnp.int32)), self.mesh)
            return TrainState(step=step_r, params=params_r,
                              opt_state=opt_state, model_state=ms_r,
                              exchange_residual=self._init_residual(params))
        state = replicate(TrainState.create(params, self.tx, model_state),
                          self.mesh)
        return state.replace(exchange_residual=self._init_residual(params))

    def _init_residual(self, params) -> PyTree | None:
        """Error-feedback residual for the bf16 gradient exchange
        (``ModelConfig.exchange_error_feedback``): zeros with a leading
        data-shard axis, placed sharded ``P('data')`` so each shard
        owns exactly its own quantization error
        (parallel/bsp.py ``TrainState.exchange_residual``).  ``None``
        (the default) leaves the state's pytree unchanged."""
        cfg = self.config
        if not cfg.exchange_error_feedback:
            return None
        if cfg.exchange_dtype != "bf16":
            raise ValueError("exchange_error_feedback compensates bf16 "
                             "quantization; set exchange_dtype='bf16'")
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from theanompi_tpu.parallel.mesh import AXIS_DATA

        part, axes = self._batch_axes()
        if axes != (AXIS_DATA,):
            raise ValueError(
                "exchange_error_feedback keeps one residual per DATA "
                f"shard; this model reduces over {axes} — per-shard "
                "error state is only defined for the pure-data mesh")
        n = self.mesh.shape[AXIS_DATA]
        if cfg.zero_sharding:
            from theanompi_tpu.parallel.zero import (
                init_zero_exchange_residual,
            )

            res = init_zero_exchange_residual(
                params, self.mesh,
                exchange_buckets=cfg.exchange_buckets)
        else:
            from theanompi_tpu.parallel.bsp import init_exchange_residual

            res = init_exchange_residual(params, n)
        sh = NamedSharding(self.mesh, P(AXIS_DATA))
        return jax.tree.map(lambda x: jax.device_put(x, sh), res)

    def _check_psum_grads_only(self, feature: str, how: str,
                               allow_bf16_wire: bool = False) -> None:
        """Shared guard for the sharding features that ARE the gradient
        exchange (zero/fsdp): exchange_what/strategy knobs don't apply.
        ``allow_bf16_wire=True`` (ZeRO) accepts the ``exchange_dtype``
        compression knob — its reduce_scatter has a quantization seam —
        while still rejecting the legacy strategy spelling."""
        cfg = self.config
        if cfg.exchange_what != "grads":
            raise ValueError(f"{feature} IS the gradient exchange; "
                             "exchange_what='params' does not apply")
        from theanompi_tpu.parallel.exchanger import resolve_strategy

        if resolve_strategy(cfg.exchange_strategy) != "psum":
            raise ValueError(
                f"{feature}'s {how}; the bf16-compressed strategy "
                f"{cfg.exchange_strategy!r} does not apply")
        if not allow_bf16_wire and (cfg.exchange_dtype != "f32"
                                    or cfg.exchange_error_feedback):
            raise ValueError(
                f"{feature}'s {how}; exchange_dtype="
                f"{cfg.exchange_dtype!r}/exchange_error_feedback do not "
                "apply")

    def _check_zero_supported(self) -> None:
        from theanompi_tpu.parallel.mesh import AXIS_DATA

        cfg = self.config
        part, axes = self._batch_axes()
        if AXIS_DATA not in axes:
            raise ValueError("zero_sharding shards the optimizer over "
                             f"the '{AXIS_DATA}' axis, which is not "
                             f"among this model's reduce axes {axes}")
        if cfg.optimizer == "lars":
            raise ValueError("zero_sharding needs an ELEMENTWISE "
                             "optimizer; lars computes layerwise trust "
                             "ratios which a flat shard cannot see")
        self._check_psum_grads_only(
            "zero_sharding",
            "reduce_scatter owns the wire dtype (use exchange_dtype)",
            allow_bf16_wire=True)

    def _reject_zero_sharding(self, model_kind: str) -> None:
        """Compile-time guard mirroring _reject_grad_accum for models
        with their own state/step builders."""
        if self.config.zero_sharding:
            raise ValueError(f"zero_sharding is not implemented for "
                             f"the {model_kind}")
        if self.config.fsdp_sharding:
            raise ValueError(f"fsdp_sharding is not implemented for "
                             f"the {model_kind}")
        if self.config.exchange_error_feedback:
            # the residual is TrainState plumbing these custom stacks
            # don't thread; silently ignoring new state would be worse
            # than refusing
            raise ValueError(f"exchange_error_feedback is not "
                             f"implemented for the {model_kind}")
        if self.config.exchange_buckets != 1:
            # custom step builders don't route through the exchanger's
            # backward tags; a silently-ignored knob would fake the win
            raise ValueError(f"exchange_buckets is not implemented for "
                             f"the {model_kind}")

    def _check_fsdp_supported(self) -> None:
        from theanompi_tpu.parallel.mesh import AXIS_DATA

        cfg = self.config
        if cfg.zero_sharding:
            raise ValueError("fsdp_sharding already shards params AND "
                             "optimizer state; combining it with "
                             "zero_sharding is meaningless")
        part, axes = self._batch_axes()
        if axes != (AXIS_DATA,):
            raise ValueError(
                f"fsdp_sharding is the pure-DP parameter-sharding path "
                f"(GSPMD over '{AXIS_DATA}'); this model reduces over "
                f"{axes} — use the family's own sharded step instead")
        self._check_psum_grads_only(
            "fsdp_sharding",
            "collectives are compiler-inserted at full precision")

    def adopt_restored_state(self, state: "TrainState") -> "TrainState":
        """Hook for checkpoint resume: re-establish this model's device
        placement on a restored (host-side) state.  Replicated models:
        as-is (the shard_map step's in_specs place state on entry).
        Parameter-sharded models (``param_specs`` set): params AND the
        optimizer's param-like buffers are re-placed per their specs —
        essential for the TP path, whose plain-jit step infers
        shardings from the committed arrays."""
        if self.param_specs is None:
            return state
        import optax
        from jax.sharding import NamedSharding

        def put(leaf, spec):
            return jax.device_put(jnp.asarray(leaf),
                                  NamedSharding(self.mesh, spec))

        return state.replace(
            params=jax.tree.map(put, state.params, self.param_specs),
            opt_state=optax.tree_map_params(
                self.tx, put, state.opt_state, self.param_specs),
        )

    def _init_scaffold(self, config, mesh, verbose, shard_rank, shard_size,
                       data) -> None:
        """The contract scaffolding shared by every model — including
        ones (WGAN) whose network/optimizer state diverges from the
        single-module TrainState path: mesh/shard bookkeeping, dataset,
        worker-scaled LR, rng, and the train-loop fields that
        ``begin_epoch``/``train_iter``/``_flush_metrics`` rely on."""
        self.config = config or self.default_config()
        self.verbose = verbose
        self.mesh = mesh if mesh is not None else data_mesh()
        self.n_workers = data_axis_size(self.mesh)
        # async-rule data sharding: this model instance sees shard
        # shard_rank of shard_size (BSP leaves these 0/1 — the mesh
        # shards the global batch instead)
        self.shard_rank = shard_rank
        self.shard_size = shard_size
        # multi-host: this controller feeds only its host's slice of
        # every global batch (data/base.py host_train_batches)
        self.multiprocess = is_multiprocess(self.mesh)
        self.host_rank = host_rank() if self.multiprocess else 0
        self.host_count = host_count() if self.multiprocess else 1
        if self.multiprocess and shard_size > 1:
            raise ValueError(
                "per-worker data sharding (shard_size>1, async rules) and a "
                "multi-host mesh cannot be combined in one model instance")
        self.batch_size = self.config.batch_size
        self.global_batch = self.batch_size * self.n_workers
        self.n_epochs = self.config.n_epochs
        self.current_epoch = 0
        self.current_info: dict = {}

        # ``data`` lets N worker models in one process (async rules)
        # share one Dataset instead of loading N copies
        self.data: Dataset = data if data is not None else self.build_data()

        base_lr = self.config.learning_rate
        if self.config.lr_scale_with_workers:
            base_lr = scale_lr(base_lr, self.n_workers,
                               self.config.lr_scale_with_workers)
        self._base_lr = base_lr

        self._rng = self._epoch_rng(0)
        self.train_step = None
        self.train_step_multi = None
        self.train_step_accum = None
        self.eval_step = None
        self._train_prefetcher: DevicePrefetcher | None = None
        self._train_iter: Iterator | None = None
        self._ingest_source = None  # RemoteBatchSource when --ingest
        self._pending: list[tuple[int, dict]] = []

    # -- hooks for subclasses ------------------------------------------------

    @classmethod
    def default_config(cls) -> ModelConfig:
        return ModelConfig()

    def build_module(self) -> nn.Module:
        raise NotImplementedError

    def build_data(self) -> Dataset:
        raise NotImplementedError

    def _input_dtype(self):
        return jnp.float32

    def _compute_dtype(self):
        """MXU compute dtype from config (params stay fp32 masters)."""
        return (jnp.bfloat16 if self.config.compute_dtype == "bfloat16"
                else jnp.float32)

    def _bn_axis(self) -> str | None:
        """Named axis for cross-replica BN stats (ModelConfig.sync_bn);
        None keeps per-shard stats.  BN-using build_module()s pass this
        to their module so one config knob covers the family."""
        if not self.config.sync_bn:
            return None
        from theanompi_tpu.parallel.mesh import AXIS_DATA

        return AXIS_DATA

    # -- optimizer / loss ----------------------------------------------------

    def _build_optimizer(self, lr: float) -> optax.GradientTransformation:
        return build_optimizer(lr, **self._optimizer_kwargs())

    def _optimizer_kwargs(self) -> dict:
        cfg = self.config
        return {"optimizer": cfg.optimizer, "momentum": cfg.momentum,
                "nesterov": cfg.nesterov, "weight_decay": cfg.weight_decay,
                "beta1": cfg.adam_beta1, "beta2": cfg.adam_beta2,
                "eps": cfg.adam_eps, "rmsprop_decay": cfg.rmsprop_decay,
                "lars_trust_coefficient": cfg.lars_trust_coefficient}

    def optimizer_hyperparams(self) -> dict:
        """The plain-value description of this model's optimizer — what
        a remote ASGD service needs to rebuild it (parallel/service.py;
        the keys are ``build_optimizer``'s kwargs)."""
        return {"learning_rate": self._base_lr, **self._optimizer_kwargs()}

    def loss_fn(self, params, model_state, batch, rng):
        """Default: softmax CE + top-1 error.  Override for GANs etc.

        Honors the dataset's ``device_transform`` (ops/augment.py):
        raw uint8 batches are cropped/flipped/normalized on device as
        part of this same jitted step."""
        x, y = batch
        transform = getattr(self.data, "device_transform", None)
        if transform is not None:
            rng, aug_rng = jax.random.split(rng)
            x = transform(x, aug_rng, train=True)
        variables = {"params": params, **model_state}
        mutable = [k for k in model_state if k == "batch_stats"]
        if mutable:
            logits, updates = self.module.apply(
                variables, x, train=True, mutable=mutable,
                rngs={"dropout": rng},
            )
            new_ms = {**model_state, **updates}
        else:
            logits = self.module.apply(variables, x, train=True,
                                       rngs={"dropout": rng})
            new_ms = model_state
        smooth = self.config.label_smoothing  # train-time only; eval
        if isinstance(logits, (tuple, list)):  # aux heads (GoogLeNet)
            main, *aux = logits                 # reports plain CE
            loss = softmax_cross_entropy(main, y, smooth)
            for a_logits, a_w in aux:
                loss = loss + a_w * softmax_cross_entropy(a_logits, y,
                                                          smooth)
            logits = main
        else:
            loss = softmax_cross_entropy(logits, y, smooth)
        metrics = {"loss": loss, "error": error_rate(logits, y)}
        if self.config.track_top5:
            metrics["top5_error"] = topk_error(logits, y, 5)
        return loss, (new_ms, metrics)

    def eval_fn(self, params, model_state, batch):
        x, y = batch
        transform = getattr(self.data, "device_transform", None)
        if transform is not None:
            x = transform(x, None, train=False)  # center crop, no mirror
        variables = {"params": params, **model_state}
        logits = self.module.apply(variables, x, train=False)
        if isinstance(logits, (tuple, list)):
            logits = logits[0]
        metrics = {"loss": softmax_cross_entropy(logits, y),
                   "error": error_rate(logits, y)}
        if self.config.track_top5:
            metrics["top5_error"] = topk_error(logits, y, 5)
        return metrics

    # -- reference contract --------------------------------------------------

    @property
    def params(self) -> PyTree:
        return self.state.params

    def _batch_axes(self) -> tuple:
        """(partition, reduce_axes) derived from ``batch_partition`` —
        every mesh axis the batch is sharded over is also a gradient/
        metric reduce axis, so a subclass setting the attribute gets a
        consistent step with no extra plumbing."""
        from jax.sharding import PartitionSpec as P

        from theanompi_tpu.parallel.mesh import AXIS_DATA

        part = (self.batch_partition if self.batch_partition is not None
                else P(AXIS_DATA))
        axes = []
        for entry in part:
            if entry is None:
                continue
            for a in (entry,) if isinstance(entry, str) else entry:
                axes.append(a)
        return part, tuple(axes)

    #: models whose network contains BatchNorm set this True so the
    #: small-shard warning below can fire (only they are exposed to
    #: the noisy-per-shard-stats failure)
    uses_batchnorm: bool = False

    def compile_iter_fns(self, sync_type: str = "avg") -> None:
        """Build the jitted SPMD steps (the reference's Theano-function
        compile; ``sync_type`` 'avg' vs 'cdd' maps to exchange avg/sum)."""
        part, axes = self._batch_axes()
        if (self.uses_batchnorm and not self.config.sync_bn
                and self.batch_size < 16):
            import warnings

            warnings.warn(
                f"{type(self).__name__}: per-shard batch "
                f"{self.batch_size} with sync_bn=False — BatchNorm "
                "running statistics from so few images are too noisy "
                "to serve eval (observed as chance-level val error at "
                "converged train loss, round-4 jpeg e2e).  Set "
                "ModelConfig.sync_bn=True (cross-replica stats) or "
                "raise batch_size.", stacklevel=2)
        if (self.config.steps_per_call > 1
                and self.config.grad_accum_steps > 1):
            raise ValueError(
                "steps_per_call and grad_accum_steps are both stacked-"
                "batch cadences; combining them by nesting is not "
                "supported — set one of them to 1")
        if self.config.fsdp_sharding:
            from theanompi_tpu.parallel.fsdp import make_bsp_fsdp_step

            self._check_fsdp_supported()
            if self.config.sync_bn:
                raise ValueError(
                    "sync_bn needs a shard_map step with a named 'data' "
                    "axis; the FSDP step is GSPMD-jitted with no named "
                    "axes — use per-shard BN (sync_bn=False) with FSDP")
            # param_specs was derived at state build; passing it keeps
            # the step's shardings and the resume placement identical
            fsdp_kw = dict(avg=(sync_type != "cdd"), batch_partition=part,
                           donate_batch=self.config.donate_batch,
                           specs=self.param_specs,
                           exchange_buckets=self.config.exchange_buckets)
            self.train_step = make_bsp_fsdp_step(
                self.loss_fn, self.tx, self.mesh,
                params_template=self.state.params, **fsdp_kw)
            if self.config.steps_per_call > 1:
                self.train_step_multi = make_bsp_fsdp_step(
                    self.loss_fn, self.tx, self.mesh,
                    params_template=self.state.params, multi=True,
                    **fsdp_kw)
            if self.config.grad_accum_steps > 1:
                self.train_step_accum = make_bsp_fsdp_step(
                    self.loss_fn, self.tx, self.mesh,
                    params_template=self.state.params, accum=True,
                    **fsdp_kw)
            # eval reuses the shard_map step: its replicated in_spec
            # makes jit insert one params all-gather per eval batch
            self.eval_step = make_bsp_eval_step(self.eval_fn, self.mesh,
                                                batch_partition=part,
                                                reduce_axes=axes)
            return
        if self.config.zero_sharding:
            from theanompi_tpu.parallel.zero import make_bsp_zero_step

            self._check_zero_supported()
            zero_kw = dict(avg=(sync_type != "cdd"),
                           donate_batch=self.config.donate_batch,
                           batch_partition=part, reduce_axes=axes,
                           exchange_dtype=self.config.exchange_dtype,
                           error_feedback=self.config
                           .exchange_error_feedback,
                           exchange_buckets=self.config.exchange_buckets)
            self.train_step = make_bsp_zero_step(
                self.loss_fn, self.tx, self.mesh,
                params_template=self.state.params,  # shapes only
                **zero_kw)
            if self.config.steps_per_call > 1:
                self.train_step_multi = make_bsp_zero_step(
                    self.loss_fn, self.tx, self.mesh,
                    params_template=self.state.params, multi=True,
                    **zero_kw)
            if self.config.grad_accum_steps > 1:
                self.train_step_accum = make_bsp_zero_step(
                    self.loss_fn, self.tx, self.mesh,
                    params_template=self.state.params, accum=True,
                    **zero_kw)
            self.eval_step = make_bsp_eval_step(self.eval_fn, self.mesh,
                                                batch_partition=part,
                                                reduce_axes=axes)
            return
        exchanger = BSP_Exchanger(
            strategy=self.config.exchange_strategy,
            avg=(sync_type != "cdd"),
            exchange_what=self.config.exchange_what,
            axis=axes if len(axes) > 1 else axes[0],
            exchange_dtype=(None if self.config.exchange_dtype == "f32"
                            else self.config.exchange_dtype),
            error_feedback=self.config.exchange_error_feedback,
            exchange_buckets=self.config.exchange_buckets,
        )
        self.train_step = make_bsp_train_step(self.loss_fn, self.tx,
                                              self.mesh, exchanger,
                                              batch_partition=part,
                                              reduce_axes=axes)
        if self.config.steps_per_call > 1:
            from theanompi_tpu.parallel.bsp import make_bsp_multi_step

            self.train_step_multi = make_bsp_multi_step(
                self.loss_fn, self.tx, self.mesh, exchanger,
                donate_batch=self.config.donate_batch,
                batch_partition=part, reduce_axes=axes)
        if self.config.grad_accum_steps > 1:
            from theanompi_tpu.parallel.bsp import make_bsp_accum_step

            self.train_step_accum = make_bsp_accum_step(
                self.loss_fn, self.tx, self.mesh, exchanger,
                donate_batch=self.config.donate_batch,
                batch_partition=part, reduce_axes=axes)
        self.eval_step = make_bsp_eval_step(self.eval_fn, self.mesh,
                                            batch_partition=part,
                                            reduce_axes=axes)

    def _reject_grad_accum(self, model_kind: str) -> None:
        """Compile-time guard for models whose custom step builders
        do not implement accumulation (call from compile_iter_fns
        overrides, mirroring their steps_per_call guards)."""
        if self.config.grad_accum_steps > 1:
            raise ValueError(f"grad_accum_steps>1 is not implemented "
                             f"for the {model_kind}")

    def compile_grad_fn(self):
        """Jitted gradient-only step for parameter-server rules (ASGD):
        returns ``fn(state, batch, rng) -> (grads, new_model_state,
        metrics)`` with no optimizer update — the server applies it."""

        from theanompi_tpu.parallel.bsp import grad_and_metrics

        def gstep(state: TrainState, batch, rng):
            return grad_and_metrics(self.loss_fn, state.params,
                                    state.model_state, batch, rng)

        return jax.jit(gstep)

    def begin_epoch(self, epoch: int) -> int:
        """Stage the epoch's prefetched train iterator; returns n_iters
        (rounded down to a multiple of ``steps_per_call``)."""
        self.cleanup_iter()
        self.current_epoch = epoch
        # re-derive the step rng as a pure function of (seed, epoch):
        # dropout/augment draws become epoch-deterministic, so a resume
        # at an epoch boundary replays EXACTLY the continuous run's
        # draws (not merely statistically equivalent ones)
        self._rng = self._epoch_rng(epoch)
        # distributed ingest (theanompi_tpu/ingest): with
        # THEANOMPI_TPU_INGEST set (launcher --ingest), the epoch's
        # host batches come from the remote reader fleet instead of
        # this process's loader thread — byte-identical stream, same
        # DevicePrefetcher downstream, rules untouched.  Multi-host
        # SPMD programs keep the local per-host slicing path (each
        # host feeds only its slice of every global batch).
        ingest = None
        if not self.multiprocess:
            from theanompi_tpu.ingest.client import ingest_addresses

            ingest = ingest_addresses()
        if ingest:
            from theanompi_tpu.ingest.client import RemoteBatchSource

            self._ingest_source = RemoteBatchSource(
                ingest, data=self.data, epoch=epoch,
                global_batch=self.global_batch,
                rank=self.shard_rank, size=self.shard_size)
            host_iter = self._ingest_source
            n_iters = self._ingest_source.n_batches
        elif self.multiprocess:
            host_iter = self.data.host_train_batches(
                epoch, self.global_batch, self.host_rank, self.host_count)
            n_iters = self.data.n_train_batches_for(epoch, self.global_batch)
        else:
            host_iter = self.data.train_batches(
                epoch, self.global_batch, self.shard_rank, self.shard_size)
            n_iters = self.data.n_train_batches_for(
                epoch, self.global_batch, self.shard_rank, self.shard_size)
        spec = self.batch_partition
        # both cadences stage a stacked batch; compile_iter_fns rejects
        # setting both, so at most one of k/a exceeds 1
        stack = max(self.config.steps_per_call,
                    self.config.grad_accum_steps)
        if stack > 1:
            host_iter = _stack_host_batches(host_iter, stack)
            n_iters -= n_iters % stack
            if n_iters == 0:
                raise ValueError(
                    f"the epoch has fewer iterations than the stacked "
                    f"cadence ({stack} = max(steps_per_call, "
                    f"grad_accum_steps)) — every epoch would train "
                    f"NOTHING; shrink the stack or grow the dataset/"
                    f"batch ratio")
            spec = self.stacked_batch_spec()
        # per staged batch this PROCESS assembles: multi-host iterators
        # yield only this host's slice of each global batch
        host_rows = self.global_batch // (self.host_count
                                          if self.multiprocess else 1)
        self._train_prefetcher = DevicePrefetcher(
            host_iter, self.mesh, spec=spec,
            images_per_batch=host_rows * stack,
            source="remote" if ingest else "local")
        self._train_iter = iter(self._train_prefetcher)
        return n_iters

    def stacked_batch_spec(self):
        """PartitionSpec of a stacked batch (leading steps/microbatch
        axis unsharded, per-step axes per ``batch_partition``) — the
        single source bench.py and ``begin_epoch`` stage with, for BOTH
        stacked cadences (``train_step_multi`` and
        ``train_step_accum``)."""
        from jax.sharding import PartitionSpec as P

        from theanompi_tpu.parallel.mesh import AXIS_DATA

        per_step = (self.batch_partition if self.batch_partition
                    is not None else P(AXIS_DATA))
        return P(None, *per_step)

    def _epoch_rng(self, epoch: int):
        """The step-rng stream for an epoch — THE single derivation
        (init uses epoch 0, so pre-training draws match epoch 0's
        stream)."""
        return jax.random.fold_in(jax.random.key(self.config.seed + 1),
                                  epoch)

    def _next_rng(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def train_iter(self, count: int, recorder: Recorder) -> int:
        """One training dispatch; returns the number of iterations it
        covered (``steps_per_call`` for the scanned multi-step,
        ``grad_accum_steps`` for accumulation, else 1) so epoch drivers
        can advance their counters."""
        if self.train_step is None:
            raise RuntimeError("call compile_iter_fns() first")
        k = self.config.steps_per_call
        a = self.config.grad_accum_steps
        recorder.start()
        batch = next(self._train_iter)
        recorder.end("wait")  # time blocked on the loader = reference 'wait'
        recorder.start()
        # the annotation labels this iteration in jax.profiler traces
        # (utils/profiling.py); free when no trace is active
        with jax.profiler.StepTraceAnnotation("train", step_num=count):
            if k > 1:
                self.state, metrics = self.train_step_multi(
                    self.state, batch, self._next_rng())
            elif a > 1:
                if self.train_step_accum is None:
                    raise ValueError(
                        f"{type(self).__name__}'s compile_iter_fns does "
                        "not build an accumulation step; grad_accum_steps"
                        ">1 is unsupported for this model")
                self.state, metrics = self.train_step_accum(
                    self.state, batch, self._next_rng())
            else:
                self.state, metrics = self.train_step(self.state, batch,
                                                      self._next_rng())
        recorder.end("calc")  # async dispatch; device time lands on flush
        self._pending.append((count, metrics))
        # flush window: print_freq when printing, else a fixed window so
        # quiet runs (print_freq<=0) still batch device syncs
        window = recorder.print_freq if recorder.print_freq > 0 else 50
        consumed = max(k, a)
        if len(self._pending) * consumed >= window:
            self._flush_metrics(recorder)
            recorder.print_train_info(count)
        return consumed

    def _flush_metrics(self, recorder: Recorder) -> None:
        """Convert pending device metrics (blocks until the device has
        caught up — charged to 'calc').  Multi-step entries carry
        ``(k,)``-stacked metric leaves; each sub-step is recorded."""
        if not self._pending:
            return
        recorder.start()
        # a scalar entry covers grad_accum_steps microbatches' images
        # (metrics came back averaged over them); stacked entries carry
        # one sub-step per leaf row
        per_scalar = self.global_batch * self.config.grad_accum_steps
        for _, m in self._pending:
            loss = np.asarray(m["loss"])
            err = np.asarray(m["error"])
            if loss.ndim == 0:
                recorder.train_metrics(float(loss), float(err),
                                       per_scalar)
            else:
                for l, e in zip(loss, err):
                    recorder.train_metrics(float(l), float(e),
                                           self.global_batch)
        recorder.end("calc", block_on=self._pending[-1][1])
        self._pending.clear()
        self.current_info = {
            "epoch": self.current_epoch,
            "loss": recorder.train_losses[-1] if recorder.train_losses else None,
        }

    def val_iter(self, count: int, recorder: Recorder,
                 batch=None) -> dict:
        """One async eval dispatch, timed like the train path (the
        returned metrics are device scalars; the caller fetches them in
        bulk so the device pipeline never serializes per batch)."""
        recorder.start()
        metrics = self.eval_step(self.state, batch)
        recorder.end("calc")
        return metrics

    #: max un-synced validation dispatches: bounds how many in-flight
    #: batches' device buffers the runtime must pin (a full ImageNet val
    #: epoch left unfenced would queue gigabytes of inputs)
    VAL_SYNC_WINDOW = 8

    def val_epoch(self, recorder: Recorder) -> dict[str, float]:
        """Full validation pass; returns averaged metrics.  Dispatches
        eval steps asynchronously and syncs once per ``VAL_SYNC_WINDOW``
        batches — the device pipeline stays busy without per-batch
        serialization or unbounded buffer retention."""
        pending: list[dict] = []
        if self.multiprocess:
            host_iter = self.data.host_val_batches(
                self.global_batch, self.host_rank, self.host_count)
        else:
            host_iter = self.data.val_batches(self.global_batch)
        from theanompi_tpu import monitor

        with DevicePrefetcher(host_iter, self.mesh,
                              spec=self.batch_partition) as pf:
            for n, batch in enumerate(pf):
                pending.append(self.val_iter(n, recorder, batch))
                # per-batch heartbeat: a long val epoch is progress,
                # not a stall — only a WEDGED one should trip the
                # watchdog
                monitor.progress(phase="validate", step=n)
                if (n + 1) % self.VAL_SYNC_WINDOW == 0:
                    recorder.start()
                    recorder.end("calc", block_on=pending[-1])
        if not pending:
            return {}
        recorder.start()
        sums: dict[str, float] = {}
        for m in pending:
            for k, v in m.items():
                sums[k] = sums.get(k, 0.0) + float(v)
        recorder.end("calc", block_on=pending[-1])
        return {k: v / len(pending) for k, v in sums.items()}

    def adjust_hyperp(self, epoch: int) -> float:
        """Per-epoch LR schedule (the reference's step/poly decay, plus
        cosine and the large-batch linear warmup ramp)."""
        cfg = self.config
        if cfg.warmup_epochs and epoch < cfg.warmup_epochs:
            lr = self._base_lr * (epoch + 1) / cfg.warmup_epochs
        elif cfg.lr_schedule == "constant":
            lr = self._base_lr
        elif cfg.lr_schedule == "step":
            k = sum(1 for e in cfg.lr_decay_epochs if epoch >= e)
            lr = self._base_lr * (cfg.lr_decay_factor ** k)
        elif cfg.lr_schedule in ("poly", "cosine"):
            # decay spans the post-warmup epochs
            span = max(cfg.n_epochs - cfg.warmup_epochs, 1)
            frac = min((epoch - cfg.warmup_epochs) / span, 1.0)
            if cfg.lr_schedule == "poly":
                lr = self._base_lr * (1.0 - frac) ** cfg.lr_poly_power
            else:
                lr = self._base_lr * 0.5 * (1.0 + math.cos(math.pi * frac))
        else:
            raise ValueError(f"unknown lr_schedule {cfg.lr_schedule!r}")
        self.state = self.state.replace(
            opt_state=set_learning_rate(self.state.opt_state, lr)
        )
        return lr

    # -- persistence (npz param snapshots; full-state resume is Orbax in
    #    the rules layer) ----------------------------------------------------

    def save(self, path: str | None = None) -> str:
        path = path or os.path.join(self.config.snapshot_dir,
                                    f"{self.name}_params.npz")
        save_params_npz(path, self.state.params)
        return path

    #: per-leaf PartitionSpecs for parameter-sharded models (TP/PP/MoE
    #: set this); None = fully replicated params (the DP default)
    param_specs = None

    def _place_params(self, params: PyTree) -> PyTree:
        """Put a host-side param tree back on the mesh the way this
        model shards it (per ``param_specs``, else replicated)."""
        if self.param_specs is None:
            return replicate(jax.tree.map(jnp.asarray, params), self.mesh)
        from jax.sharding import NamedSharding

        return jax.tree.map(
            lambda x, spec: jax.device_put(
                jnp.asarray(x), NamedSharding(self.mesh, spec)),
            params, self.param_specs)

    def load(self, path: str) -> None:
        """Contract ``load`` — PRESERVES the model's param sharding
        (a replicated load of a pipe/expert/model-sharded stack would
        materialize it full-size on every device).  The template is
        shape/dtype-only: no cross-device gather of sharded weights."""
        template = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            self.state.params)
        params = load_params_npz(path, template)
        self.state = self.state.replace(params=self._place_params(params))

    def cleanup_iter(self) -> None:
        if self._train_prefetcher is not None:
            self._train_prefetcher.close()
            self._train_prefetcher = None
            self._train_iter = None
        if self._ingest_source is not None:
            # the prefetcher abandons its host iterator; the remote
            # source's fetcher threads + connections need an explicit
            # close (thread-leak fence, tests/conftest.py)
            self._ingest_source.close()
            self._ingest_source = None

    def cleanup(self) -> None:
        self.cleanup_iter()
