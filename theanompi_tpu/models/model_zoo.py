"""Extra model-zoo variants — parity counterpart of the reference's
``theanompi/models/lasagne_model_zoo/`` (SURVEY.md §2.8 — mount empty,
no file:line), which carried Lasagne-based VGG and ResNet variants
alongside the first-class models.

Here the variants are thin reconfigurations of the first-class flax
networks (the TPU-native analogue of "another model-zoo frontend over
the same layers"): VGG19 (configuration E) and deeper bottleneck
ResNets (101/152).  Each keeps the full model contract, so every rule
and launcher drives them like any zoo member.
"""

from __future__ import annotations

from theanompi_tpu.models.resnet50 import ResNet50
from theanompi_tpu.models.vgg16 import VGG16

# configuration E: (n_convs, features) per block — 16 convs + 3 FC
VGG19_BLOCKS = ((2, 64), (2, 128), (4, 256), (4, 512), (4, 512))


class VGG19(VGG16):
    name = "vgg19"
    blocks = VGG19_BLOCKS
    train_flops_per_sample = 117.6e9  # 2xMAC: 19.6 GMAC fwd @224 x2 x ~3


class ResNet101(ResNet50):
    name = "resnet101"
    stage_sizes = (3, 4, 23, 3)
    train_flops_per_sample = 46.8e9   # 2xMAC: 7.8 GMAC fwd @224 x2 x ~3


class ResNet152(ResNet101):
    name = "resnet152"
    stage_sizes = (3, 8, 36, 3)
    train_flops_per_sample = 69.0e9   # 2xMAC: 11.5 GMAC fwd @224 x2 x ~3


class ResNet50_LargeBatch(ResNet50):
    """The modern large-batch TPU recipe over the same network: LARS +
    linear warmup + cosine decay (Goyal-style ramp, You-style layerwise
    trust ratios), per-chip batch 128 (measured optimum — the round-3
    on-chip ladder ran b/chip {128,256} x k {1,4,8} and 256 lost at
    every k; see default_config below), bf16 compute, space-to-depth
    stem.  The reference era scaled its SGD LR linearly with workers
    (SURVEY.md §2.7 scale_lr); this is the recipe that replaced it when
    global batches outgrew plain momentum."""

    name = "resnet50_large"

    @classmethod
    def default_config(cls):
        from theanompi_tpu.models.base import ModelConfig

        return ModelConfig(
            # per-chip batch 128, measured: the round-3 on-chip ladder
            # (artifacts/tpu_queue_r03.jsonl, BASELINE.md table) ran
            # b/chip in {128,256} x k in {1,4,8} and b=256 LOST at
            # every k (-2.45% to -5.08% img/s/chip) — N<=256 lane-bound
            # conv GEMMs don't gain from doubling M while the 2x
            # activations pressure HBM.  The published LARS recipes'
            # 8k-32k GLOBAL batch comes from the shard count (128/chip
            # x 64+ chips), not from a big per-chip batch, so the
            # large-batch geometry is preserved where it matters.
            batch_size=128,
            # per-shard master LR; sqrt scaling with the data-shard
            # count keeps the LARS LR in its working range at every
            # mesh size (0.7 on 1 chip -> ~5.6 at 64 shards / 8k
            # global batch, the regime the published LARS recipes
            # tune for)
            learning_rate=0.7,
            lr_scale_with_workers="sqrt",
            n_epochs=90,
            optimizer="lars",
            momentum=0.9,
            weight_decay=1e-4,
            lr_schedule="cosine",
            warmup_epochs=5,
            label_smoothing=0.1,
            compute_dtype="bfloat16",
            resnet_stem="s2d",
            track_top5=True,
            print_freq=20,
        )
