"""Extra model-zoo variants — parity counterpart of the reference's
``theanompi/models/lasagne_model_zoo/`` (SURVEY.md §2.8 — mount empty,
no file:line), which carried Lasagne-based VGG and ResNet variants
alongside the first-class models.

Here the variants are thin reconfigurations of the first-class flax
networks (the TPU-native analogue of "another model-zoo frontend over
the same layers"): VGG19 (configuration E) and deeper bottleneck
ResNets (101/152).  Each keeps the full model contract, so every rule
and launcher drives them like any zoo member.
"""

from __future__ import annotations

from theanompi_tpu.models.resnet50 import ResNet50
from theanompi_tpu.models.vgg16 import VGG16

# configuration E: (n_convs, features) per block — 16 convs + 3 FC
VGG19_BLOCKS = ((2, 64), (2, 128), (4, 256), (4, 512), (4, 512))


class VGG19(VGG16):
    name = "vgg19"
    blocks = VGG19_BLOCKS


class ResNet101(ResNet50):
    name = "resnet101"
    stage_sizes = (3, 4, 23, 3)


class ResNet152(ResNet101):
    name = "resnet152"
    stage_sizes = (3, 8, 36, 3)
