"""ResNet-50 — the north-star recipe (bundled recipe #4: 8-worker BSP
ImageNet; BASELINE.json configs[3], ≥2500 img/s on v5e-16).

Parity counterpart of the reference's ``theanompi/models/resnet50.py``
(SURVEY.md §2.8 — mount empty, no file:line): bottleneck ResNet-50
with batch norm, SGD+momentum, step LR decay.  TPU-native choices:
NHWC layout, bf16 compute on the MXU with fp32 master params
(``compute_dtype='bfloat16'``), BN statistics pmean-ed across the data
axis by the BSP step (parallel/bsp.py), and the whole fwd+bwd+psum+
update fused into one jitted SPMD program.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from theanompi_tpu.data.imagenet import ImageNet_data
from theanompi_tpu.models import layers as L
from theanompi_tpu.models.base import ModelConfig, TpuModel
from theanompi_tpu.ops.maxpool import maxpool_stem


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck with projection shortcut on
    stride/width change.  The final BN's scale is init to zero
    (standard residual-friendly init; keeps early training stable at
    large global batch).

    Every BN carries its epilogue (relu; the exit BN also the shortcut
    add) through ``layers.BatchNormAct`` so ``bn_act_impl='pallas'``
    runs each one as a single fused HBM stream — the loop-fusion slice
    of the MFU account.  Instance names pin flax's old auto-numbering
    (``BatchNorm_{i}`` in creation order), so the param tree is
    identical to the pre-seam module and independent of the impl knob.
    """

    features: int            # bottleneck width; output is 4x this
    strides: tuple[int, int] = (1, 1)
    dtype: jnp.dtype = jnp.float32
    #: named mesh axis to pmean BN stats over (cross-replica BN);
    #: None = per-shard stats (the reference's per-worker semantics)
    bn_axis: str | None = None
    #: BN+act epilogue impl (ModelConfig.bn_act_impl): 'xla' | 'pallas'
    bn_act_impl: str = "xla"

    @nn.compact
    def __call__(self, x, train: bool):
        bn_i = iter(range(4))
        norm = lambda act=None, scale_init=nn.initializers.ones: (  # noqa: E731
            L.BatchNormAct(
                use_running_average=not train, momentum=0.9, epsilon=1e-5,
                dtype=self.dtype, scale_init=scale_init,
                axis_name=self.bn_axis, act=act, impl=self.bn_act_impl,
                name=f"BatchNorm_{next(bn_i)}"))
        out_features = self.features * 4

        residual = x
        if residual.shape[-1] != out_features or self.strides != (1, 1):
            residual = L.Conv(out_features, (1, 1), strides=self.strides,
                              use_bias=False, dtype=self.dtype,
                              name="proj_conv")(residual)
            residual = norm()(residual)

        y = L.Conv(self.features, (1, 1), use_bias=False, dtype=self.dtype)(x)
        y = norm(act="relu")(y)
        y = L.Conv(self.features, (3, 3), strides=self.strides,
                   use_bias=False, dtype=self.dtype)(y)
        y = norm(act="relu")(y)
        y = L.Conv(out_features, (1, 1), use_bias=False, dtype=self.dtype)(y)
        # exit epilogue: relu(bn(y) + shortcut) in one fused stream
        return norm(act="relu", scale_init=nn.initializers.zeros)(
            y, residual=residual)


def space_to_depth(x, block: int = 2):
    """(N, H, W, C) -> (N, H/b, W/b, b*b*C) — pixel-block channels in
    (row-offset, col-offset, channel) order, matching
    ``s2d_stem_kernel_from_conv7``."""
    n, h, w, c = x.shape
    x = x.reshape(n, h // block, block, w // block, block, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(
        n, h // block, w // block, block * block * c)


def s2d_stem_kernel_from_conv7(w7):
    """Exact re-parameterization of a 7x7/stride-2 stem kernel as the
    4x4/stride-1 kernel over the 2x2 space-to-depth input: zero-pad
    the taps 7->8 at the leading edge (tap index p = original + 1, so
    p = 2q + a with block tap q and within-block offset a), then fold
    the offsets into the input-channel dim.  Used by the equivalence
    test; training from scratch just initializes the 4x4 kernel."""
    kh, kw, c, o = w7.shape
    assert (kh, kw) == (7, 7)
    w8 = jnp.zeros((8, 8, c, o), w7.dtype).at[1:, 1:].set(w7)
    w8 = w8.reshape(4, 2, 4, 2, c, o)           # (q, a, p, b, c, o)
    return w8.transpose(0, 2, 1, 3, 4, 5).reshape(4, 4, 4 * c, o)


class ResNet(nn.Module):
    """Generic bottleneck ResNet (50 = (3,4,6,3)).

    ``stem='s2d'`` replaces the 7x7/stride-2 stem conv with the exact
    4x4/stride-1 conv over a 2x2 space-to-depth input (12 channels
    instead of 3): the C=3 conv is the one shape in the network the
    MXU cannot pack lanes for, and this is the standard TPU fix for
    it.  Identical function class (see s2d_stem_kernel_from_conv7 +
    tests); opt-in until on-chip profiling decides the default.
    """

    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    width: int = 64
    n_classes: int = 1000
    dtype: jnp.dtype = jnp.float32
    stem: str = "conv7"          # 'conv7' | 's2d'
    #: cross-replica BN axis (ModelConfig.sync_bn); None = per-shard
    bn_axis: str | None = None
    #: stem max-pool impl (ModelConfig.pool_impl): 'xla' or 'pallas'
    #: (argmax-saving kernel, ops/maxpool_pallas.py)
    pool_impl: str = "xla"
    #: BN+activation epilogue impl (ModelConfig.bn_act_impl): 'xla'
    #: (unfused reference path) or 'pallas' (ops/fused_bn.py)
    bn_act_impl: str = "xla"

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        if self.stem == "s2d":
            if x.shape[1] % 2 or x.shape[2] % 2:
                raise ValueError("stem='s2d' needs even spatial dims, "
                                 f"got {x.shape}")
            x = space_to_depth(x, 2)
            # block rows i-2..i+1 of the s2d image -> pad (2, 1)
            x = L.Conv(self.width, (4, 4), strides=(1, 1),
                       padding=[(2, 1), (2, 1)], use_bias=False,
                       dtype=self.dtype, name="stem_conv")(x)
        elif self.stem == "conv7":
            x = L.Conv(self.width, (7, 7), strides=(2, 2),
                       padding=[(3, 3), (3, 3)], use_bias=False,
                       dtype=self.dtype, name="stem_conv")(x)
        else:
            raise ValueError(f"unknown stem {self.stem!r}")
        x = L.BatchNormAct(use_running_average=not train, momentum=0.9,
                           epsilon=1e-5, dtype=self.dtype, name="stem_bn",
                           axis_name=self.bn_axis,
                           impl=self.bn_act_impl)(x)
        # relu AFTER the pool: max-pooling commutes with relu (max of
        # relu == relu of max, -inf pool padding never wins, and the
        # backward argmax selection is identical), so this is
        # bit-identical to the textbook relu-then-pool stem while
        # running the relu on the 4x smaller pooled tensor.  The r3
        # on-chip xplane account charged 0.62 ms/step — 1.3% of the
        # step — to the pre-pool relu on [b,112,112,64] as a separate
        # HBM-bound loop fusion (artifacts/fusion_deepdive.json
        # 'fwd/ResNet/max'); post-pool it fuses into the maxpool
        # output fusion's quarter-size stream.
        x = maxpool_stem(x, impl=self.pool_impl)
        x = nn.relu(x)
        for stage, n_blocks in enumerate(self.stage_sizes):
            for block in range(n_blocks):
                strides = (2, 2) if stage > 0 and block == 0 else (1, 1)
                x = BottleneckBlock(self.width * (2 ** stage), strides,
                                    self.dtype, self.bn_axis,
                                    self.bn_act_impl)(x, train)
        x = L.global_avg_pool(x)
        x = L.Dense(self.n_classes, kernel_init=L.xavier_init())(x)
        return x.astype(jnp.float32)


class ResNet50(TpuModel):
    name = "resnet50"
    uses_batchnorm = True        # enables the small-shard BN warning
    stage_sizes = (3, 4, 6, 3)   # zoo variants (101/152) override this
    #: 2xMAC FLOPs — ~4.1 GMAC fwd @224 = 8.2 GF (tools/conv_ladder.py
    #: enumerates it), x ~3 for fwd+bwd.  Round-2 used the MAC count
    #: (12.3e9) here while the chip's nominal 197 TF/s and the measured
    #: matmul rates are true FLOPs, understating every MFU figure 2x.
    train_flops_per_sample = 24.6e9

    @classmethod
    def default_config(cls) -> ModelConfig:
        # The reference-era 90-epoch step recipe (SURVEY.md §5.6), with
        # linear LR scaling over workers for the 8-worker BSP config.
        return ModelConfig(
            batch_size=128,
            n_epochs=90,
            learning_rate=0.05,     # per 128-batch; scaled by n_workers
            momentum=0.9,
            weight_decay=1e-4,
            lr_schedule="step",
            lr_decay_epochs=(30, 60, 80),
            lr_decay_factor=0.1,
            lr_scale_with_workers="linear",
            compute_dtype="bfloat16",
            track_top5=True,
            print_freq=20,
        )

    def build_module(self) -> nn.Module:
        return ResNet(stage_sizes=self.stage_sizes,
                      n_classes=self.data.n_classes,
                      dtype=self._compute_dtype(),
                      stem=self.config.resnet_stem,
                      bn_axis=self._bn_axis(),
                      pool_impl=self.config.pool_impl,
                      bn_act_impl=self.config.bn_act_impl)

    def build_data(self):
        return ImageNet_data(data_dir=self.config.data_dir,
                             seed=self.config.seed,
                             augment_on_device=self.config.augment_on_device)


# reference-style alias (upstream files exposed Model-suffixed names too)
ResNet50_model = ResNet50
