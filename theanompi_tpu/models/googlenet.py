"""GoogLeNet (Inception v1) — bundled recipe #3 (VGG16/GoogLeNet
ImageNet BSP; BASELINE.json configs[2]).

Parity counterpart of the reference's ``theanompi/models/googlenet.py``
(SURVEY.md §2.8 — mount empty, no file:line): the 22-layer inception
network — 9 inception modules with 1x1/3x3/5x5 branches and pool
projection, LRN around the stem, two auxiliary softmax heads (weight
0.3) on inception 4a/4d during training, global average pooling and a
single FC head, SGD+momentum with polynomial LR decay (the GoogLeNet
paper's schedule, which the reference followed).

The aux-head training loss is the weighted sum handled generically by
``TpuModel.loss_fn`` — during training the module returns
``(main_logits, (aux1, 0.3), (aux2, 0.3))``; at eval it returns the
main logits only, so the aux towers fold away in the eval program.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from theanompi_tpu.data.imagenet import ImageNet_data
from theanompi_tpu.models import layers as L
from theanompi_tpu.models.base import ModelConfig, TpuModel


class ConvRelu(nn.Module):
    features: int
    kernel: tuple[int, int] = (1, 1)
    strides: tuple[int, int] = (1, 1)
    padding: str = "SAME"
    dtype: jnp.dtype = jnp.float32
    #: bias+relu epilogue (ModelConfig.bn_act_impl): 'pallas' fuses
    #: them via layers.BiasAct; moves the bias param out of the conv
    #: scope (see layers.BiasAct)
    act_impl: str = "xla"
    #: BN variant (ModelConfig.batch_norm): conv → BatchNorm → relu,
    #: conv bias dropped.  ``bn_axis`` comes from ``_bn_axis()`` so
    #: ``sync_bn`` reaches every conv in the network (ADVICE r4)
    batch_norm: bool = False
    bn_axis: str | None = None
    train: bool = False          # BN needs the phase; set by callers

    @nn.compact
    def __call__(self, x):
        if self.batch_norm:
            x = L.Conv(self.features, self.kernel, strides=self.strides,
                       padding=self.padding, use_bias=False,
                       kernel_init=L.xavier_init(), dtype=self.dtype)(x)
            return L.BatchNorm(use_running_average=not self.train,
                               dtype=self.dtype, axis_name=self.bn_axis,
                               act="relu", impl=self.act_impl)(x)
        if self.act_impl == "xla":
            x = L.Conv(self.features, self.kernel, strides=self.strides,
                       padding=self.padding, kernel_init=L.xavier_init(),
                       bias_init=L.constant_init(0.2), dtype=self.dtype)(x)
            return nn.relu(x)
        x = L.Conv(self.features, self.kernel, strides=self.strides,
                   padding=self.padding, use_bias=False,
                   kernel_init=L.xavier_init(), dtype=self.dtype)(x)
        return L.BiasAct(self.features, bias_init=L.constant_init(0.2),
                         act="relu", impl=self.act_impl)(x)


class Inception(nn.Module):
    """One inception module: 1x1 | 1x1→3x3 | 1x1→5x5 | pool→1x1,
    concatenated on channels."""

    b1: int          # 1x1 branch width
    b3r: int         # 3x3 reduce
    b3: int          # 3x3 branch width
    b5r: int         # 5x5 reduce
    b5: int          # 5x5 branch width
    bp: int          # pool-projection width
    dtype: jnp.dtype = jnp.float32
    act_impl: str = "xla"
    batch_norm: bool = False
    bn_axis: str | None = None
    train: bool = False

    @nn.compact
    def __call__(self, x):
        def conv(features, kernel):
            return ConvRelu(features, kernel, dtype=self.dtype,
                            act_impl=self.act_impl,
                            batch_norm=self.batch_norm,
                            bn_axis=self.bn_axis, train=self.train)

        p1 = conv(self.b1, (1, 1))(x)
        p3 = conv(self.b3r, (1, 1))(x)
        p3 = conv(self.b3, (3, 3))(p3)
        p5 = conv(self.b5r, (1, 1))(x)
        p5 = conv(self.b5, (5, 5))(p5)
        pp = nn.max_pool(x, (3, 3), (1, 1), padding="SAME")
        pp = conv(self.bp, (1, 1))(pp)
        return jnp.concatenate([p1, p3, p5, pp], axis=-1)


class AuxHead(nn.Module):
    """Auxiliary classifier: 5x5/3 avg pool → 1x1 conv → FC → softmax
    head (the regularizing side towers of the original network)."""

    n_classes: int
    dtype: jnp.dtype = jnp.float32
    act_impl: str = "xla"
    batch_norm: bool = False
    bn_axis: str | None = None

    @nn.compact
    def __call__(self, x, train: bool):
        x = nn.avg_pool(x, (5, 5), (3, 3), padding="VALID")
        x = ConvRelu(128, (1, 1), dtype=self.dtype,
                     act_impl=self.act_impl,
                     batch_norm=self.batch_norm,
                     bn_axis=self.bn_axis, train=train)(x)
        x = x.reshape((x.shape[0], -1))
        x = L.Dense(1024, kernel_init=L.gaussian_init(0.01),
                    bias_init=L.constant_init(0.1), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = L.Dropout(0.7)(x, train)
        x = L.Dense(self.n_classes, kernel_init=L.gaussian_init(0.01),
                    dtype=self.dtype)(x)
        return x.astype(jnp.float32)


class GoogLeNetCNN(nn.Module):
    n_classes: int = 1000
    aux_weight: float = 0.3
    dtype: jnp.dtype = jnp.float32
    #: channel-width multiplier (1.0 = the paper's widths).  Tests
    #: shrink the zoo with this instead of paying full-width CPU
    #: compiles — the aux-head/LRN/inception structure is what the
    #: contract tests care about, not the 1x widths.
    width_mult: float = 1.0
    #: conv bias+relu epilogue (ModelConfig.bn_act_impl)
    act_impl: str = "xla"
    #: BN variant (ModelConfig.batch_norm) + the sync_bn axis the
    #: builder threads from ``_bn_axis()`` (ADVICE r4)
    batch_norm: bool = False
    bn_axis: str | None = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        def w(n: int) -> int:
            return max(8, round(n * self.width_mult))

        def inception(b1, b3r, b3, b5r, b5, bp):
            return Inception(w(b1), w(b3r), w(b3), w(b5r), w(b5), w(bp),
                             self.dtype, self.act_impl,
                             self.batch_norm, self.bn_axis, train)

        def conv(features, kernel, **kw):
            return ConvRelu(features, kernel, dtype=self.dtype,
                            act_impl=self.act_impl,
                            batch_norm=self.batch_norm,
                            bn_axis=self.bn_axis, train=train, **kw)

        x = x.astype(self.dtype)
        # stem
        x = conv(w(64), (7, 7), strides=(2, 2))(x)
        x = L.max_pool(x, 3, 2, padding="SAME")
        x = L.LRN(n=5, k=2.0, alpha=1e-4, beta=0.75)(x)
        x = conv(w(64), (1, 1))(x)
        x = conv(w(192), (3, 3))(x)
        x = L.LRN(n=5, k=2.0, alpha=1e-4, beta=0.75)(x)
        x = L.max_pool(x, 3, 2, padding="SAME")
        # inception 3a/3b
        x = inception(64, 96, 128, 16, 32, 32)(x)
        x = inception(128, 128, 192, 32, 96, 64)(x)
        x = L.max_pool(x, 3, 2, padding="SAME")
        # inception 4a..4e with aux heads off 4a and 4d
        x = inception(192, 96, 208, 16, 48, 64)(x)
        aux1 = (AuxHead(self.n_classes, self.dtype, self.act_impl,
                         self.batch_norm, self.bn_axis,
                         name="aux1")(x, train)
                if train else None)
        x = inception(160, 112, 224, 24, 64, 64)(x)
        x = inception(128, 128, 256, 24, 64, 64)(x)
        x = inception(112, 144, 288, 32, 64, 64)(x)
        aux2 = (AuxHead(self.n_classes, self.dtype, self.act_impl,
                         self.batch_norm, self.bn_axis,
                         name="aux2")(x, train)
                if train else None)
        x = inception(256, 160, 320, 32, 128, 128)(x)
        x = L.max_pool(x, 3, 2, padding="SAME")
        # inception 5a/5b
        x = inception(256, 160, 320, 32, 128, 128)(x)
        x = inception(384, 192, 384, 48, 128, 128)(x)
        # head
        x = L.global_avg_pool(x)
        x = L.Dropout(0.4)(x, train)
        x = L.Dense(self.n_classes, kernel_init=L.xavier_init(),
                    dtype=self.dtype)(x)
        main = x.astype(jnp.float32)
        if train:
            return (main, (aux1, self.aux_weight), (aux2, self.aux_weight))
        return main


class GoogLeNet(TpuModel):
    name = "googlenet"
    #: 2xMAC FLOPs: ~1.5 GMAC fwd @224 x2, x ~3 for fwd+bwd
    train_flops_per_sample = 9.0e9
    #: channel-width multiplier threaded into build_module — tests
    #: subclass with a fraction to exercise the REAL builder (incl.
    #: the batch_norm/bn_axis threading) without full-width compiles
    width_mult: float = 1.0

    @property
    def uses_batchnorm(self) -> bool:  # small-shard stats warning
        return self.config.batch_norm

    @classmethod
    def default_config(cls) -> ModelConfig:
        return ModelConfig(
            batch_size=64,
            n_epochs=70,
            learning_rate=0.01,
            momentum=0.9,
            weight_decay=2e-4,
            lr_schedule="poly",
            lr_poly_power=0.5,
            compute_dtype="bfloat16",
            track_top5=True,
            print_freq=40,
        )

    def build_module(self) -> nn.Module:
        dtype = self._compute_dtype()
        return GoogLeNetCNN(n_classes=self.data.n_classes, dtype=dtype,
                            act_impl=self.config.bn_act_impl,
                            width_mult=self.width_mult,
                            batch_norm=self.config.batch_norm,
                            bn_axis=self._bn_axis())

    def build_data(self):
        return ImageNet_data(data_dir=self.config.data_dir, crop=224,
                             seed=self.config.seed,
                             augment_on_device=self.config.augment_on_device)


# reference-style alias
GoogLeNet_model = GoogLeNet
