"""VGG16 — bundled recipe #3 (VGG16/GoogLeNet ImageNet BSP;
BASELINE.json configs[2]).

Parity counterpart of the reference's ``theanompi/models/vgg16.py``
and its Lasagne-zoo variant (SURVEY.md §2.8 — mount empty, no
file:line): the 13-conv/3-FC configuration-D network — 3x3 convs in
blocks of 2,2,3,3,3 with 2x2 max pools, two 4096-wide dropout FC
layers, softmax over 1000 classes, SGD+momentum.

TPU notes: VGG is almost pure conv FLOPs — ideal MXU food in bf16.
The 25088→4096 fc6 matmul dominates the parameter count; it stays a
single dense op (XLA tiles it).  The reference trained VGG at a small
per-GPU batch for memory; v5e HBM fits 64 at bf16 comfortably.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from theanompi_tpu.data.imagenet import ImageNet_data
from theanompi_tpu.models import layers as L
from theanompi_tpu.models.base import ModelConfig, TpuModel

# configuration D: (n_convs, features) per block
VGG16_BLOCKS = ((2, 64), (2, 128), (3, 256), (3, 512), (3, 512))


class VGGCNN(nn.Module):
    blocks: tuple = VGG16_BLOCKS
    n_classes: int = 1000
    dtype: jnp.dtype = jnp.float32
    #: conv bias+relu epilogue (ModelConfig.bn_act_impl): 'pallas'
    #: fuses them into one stream via layers.BiasAct — NOTE the bias
    #: param moves from Conv_*/bias to BiasAct_*/bias, so the param
    #: tree depends on this knob (see layers.BiasAct)
    act_impl: str = "xla"
    #: vgg16_bn-style variant (ModelConfig.batch_norm): conv →
    #: BatchNorm → relu, conv bias dropped.  ``bn_axis`` is the
    #: cross-replica stats axis the builder threads from
    #: ``TpuModel._bn_axis()`` so ``sync_bn`` is honored here too
    #: (ADVICE r4 wiring obligation, layers.BatchNorm)
    batch_norm: bool = False
    bn_axis: str | None = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        for n_convs, features in self.blocks:
            for _ in range(n_convs):
                if self.batch_norm:
                    x = L.Conv(features, (3, 3), use_bias=False,
                               kernel_init=L.he_init(),
                               dtype=self.dtype)(x)
                    x = L.BatchNorm(use_running_average=not train,
                                    dtype=self.dtype,
                                    axis_name=self.bn_axis,
                                    act="relu", impl=self.act_impl)(x)
                elif self.act_impl == "xla":
                    x = L.Conv(features, (3, 3),
                               kernel_init=L.he_init(),
                               bias_init=L.constant_init(0.0),
                               dtype=self.dtype)(x)
                    x = nn.relu(x)
                else:
                    x = L.Conv(features, (3, 3), use_bias=False,
                               kernel_init=L.he_init(),
                               dtype=self.dtype)(x)
                    x = L.BiasAct(features,
                                  bias_init=L.constant_init(0.0),
                                  act="relu", impl=self.act_impl)(x)
            x = L.max_pool(x, 2, 2)
        x = x.reshape((x.shape[0], -1))
        x = L.Dense(4096, kernel_init=L.gaussian_init(0.005),
                    bias_init=L.constant_init(0.1), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = L.Dropout(0.5)(x, train)
        x = L.Dense(4096, kernel_init=L.gaussian_init(0.005),
                    bias_init=L.constant_init(0.1), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = L.Dropout(0.5)(x, train)
        x = L.Dense(self.n_classes, kernel_init=L.gaussian_init(0.01),
                    dtype=self.dtype)(x)
        return x.astype(jnp.float32)


class VGG16(TpuModel):
    name = "vgg16"
    #: 2xMAC FLOPs: ~15.5 GMAC fwd @224 x2, x ~3 for fwd+bwd
    train_flops_per_sample = 93.0e9
    blocks = VGG16_BLOCKS   # zoo variants (VGG19) override this

    @property
    def uses_batchnorm(self) -> bool:  # small-shard stats warning
        return self.config.batch_norm

    @classmethod
    def default_config(cls) -> ModelConfig:
        return ModelConfig(
            batch_size=64,
            n_epochs=70,
            learning_rate=0.01,
            momentum=0.9,
            weight_decay=5e-4,
            lr_schedule="step",
            lr_decay_epochs=(25, 50, 65),
            lr_decay_factor=0.1,
            compute_dtype="bfloat16",
            track_top5=True,
            print_freq=40,
        )

    def build_module(self) -> nn.Module:
        return VGGCNN(blocks=self.blocks, n_classes=self.data.n_classes,
                      dtype=self._compute_dtype(),
                      act_impl=self.config.bn_act_impl,
                      batch_norm=self.config.batch_norm,
                      bn_axis=self._bn_axis())

    def build_data(self):
        return ImageNet_data(data_dir=self.config.data_dir, crop=224,
                             seed=self.config.seed,
                             augment_on_device=self.config.augment_on_device)


# reference-style alias
VGG16_model = VGG16
