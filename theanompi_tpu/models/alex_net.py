"""AlexNet — the paper's main benchmark model (bundled recipe #2:
AlexNet-128 ImageNet, 2-worker BSP allreduce; BASELINE.json
configs[1]).

Parity counterpart of the reference's ``theanompi/models/alex_net.py``
(SURVEY.md §2.8 — mount empty, no file:line): the one-column AlexNet
variant the reference trained at batch 128 — grouped conv2/4/5 (the
original's dual-GPU split kept as channel grouping), cross-channel
LRN after conv1/conv2, overlapping 3x2 max pools, two dropout FC
layers, softmax over 1000 classes, SGD+momentum with step LR decay.

TPU-native choices: the reference routed grouped convolution to
cuDNN's ``groups``; here it is XLA's ``feature_group_count``, which
tiles onto the MXU like any other conv.  LRN dispatches through
theanompi_tpu.ops.lrn (Pallas kernel on TPU, composed XLA elsewhere).
Compute dtype is configurable; bf16 puts the conv/matmul FLOPs on the
MXU at full rate.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from theanompi_tpu.data.imagenet import ImageNet_data
from theanompi_tpu.models import layers as L
from theanompi_tpu.models.base import ModelConfig, TpuModel


class AlexNetCNN(nn.Module):
    """One-column AlexNet with channel grouping (NHWC)."""

    n_classes: int = 1000
    dtype: jnp.dtype = jnp.float32
    #: BN variant (ModelConfig.batch_norm): each conv's bias+relu
    #: becomes BatchNorm+relu (BN supersedes the LRN-era local
    #: normalization but the LRN layers are kept for parity — they
    #: are parameterless).  ``bn_axis`` threads ``_bn_axis()`` so
    #: ``sync_bn`` is honored (ADVICE r4 wiring obligation)
    batch_norm: bool = False
    bn_axis: str | None = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        def epilogue(x):
            if not self.batch_norm:
                return nn.relu(x)
            return L.BatchNorm(use_running_average=not train,
                               dtype=self.dtype,
                               axis_name=self.bn_axis, act="relu")(x)

        use_bias = not self.batch_norm
        x = x.astype(self.dtype)
        # conv1: 96 @ 11x11 /4  → LRN → pool
        x = L.Conv(96, (11, 11), strides=(4, 4), padding="VALID",
                   kernel_init=L.gaussian_init(0.01), use_bias=use_bias,
                   bias_init=L.constant_init(0.0), dtype=self.dtype)(x)
        x = epilogue(x)
        x = L.LRN(n=5, k=2.0, alpha=1e-4, beta=0.75)(x)
        x = L.max_pool(x, 3, 2)
        # conv2: 256 @ 5x5, 2 groups → LRN → pool
        x = L.Conv(256, (5, 5), groups=2,
                   kernel_init=L.gaussian_init(0.01), use_bias=use_bias,
                   bias_init=L.constant_init(0.1), dtype=self.dtype)(x)
        x = epilogue(x)
        x = L.LRN(n=5, k=2.0, alpha=1e-4, beta=0.75)(x)
        x = L.max_pool(x, 3, 2)
        # conv3/4/5
        x = L.Conv(384, (3, 3),
                   kernel_init=L.gaussian_init(0.01), use_bias=use_bias,
                   bias_init=L.constant_init(0.0), dtype=self.dtype)(x)
        x = epilogue(x)
        x = L.Conv(384, (3, 3), groups=2,
                   kernel_init=L.gaussian_init(0.01), use_bias=use_bias,
                   bias_init=L.constant_init(0.1), dtype=self.dtype)(x)
        x = epilogue(x)
        x = L.Conv(256, (3, 3), groups=2,
                   kernel_init=L.gaussian_init(0.01), use_bias=use_bias,
                   bias_init=L.constant_init(0.1), dtype=self.dtype)(x)
        x = epilogue(x)
        x = L.max_pool(x, 3, 2)
        # fc6/fc7 with dropout, fc8 softmax head
        x = x.reshape((x.shape[0], -1))
        x = L.Dense(4096, kernel_init=L.gaussian_init(0.005),
                    bias_init=L.constant_init(0.1), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = L.Dropout(0.5)(x, train)
        x = L.Dense(4096, kernel_init=L.gaussian_init(0.005),
                    bias_init=L.constant_init(0.1), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = L.Dropout(0.5)(x, train)
        x = L.Dense(self.n_classes, kernel_init=L.gaussian_init(0.01),
                    dtype=self.dtype)(x)
        return x.astype(jnp.float32)


class AlexNet(TpuModel):
    name = "alexnet"
    #: 2xMAC FLOPs: ~0.7 GMAC fwd @227 (one-column) x2, x ~3 fwd+bwd
    train_flops_per_sample = 4.2e9

    @property
    def uses_batchnorm(self) -> bool:  # small-shard stats warning
        return self.config.batch_norm

    @classmethod
    def default_config(cls) -> ModelConfig:
        # The reference's batch-128 recipe (SURVEY.md §2.8/§5.6): SGD
        # momentum 0.9, wd 5e-4, LR 0.01 stepped down through training.
        return ModelConfig(
            batch_size=128,
            n_epochs=70,
            learning_rate=0.01,
            momentum=0.9,
            weight_decay=5e-4,
            lr_schedule="step",
            lr_decay_epochs=(20, 40, 60),
            lr_decay_factor=0.1,
            compute_dtype="bfloat16",
            track_top5=True,
            print_freq=40,
        )

    def build_module(self) -> nn.Module:
        dtype = self._compute_dtype()
        return AlexNetCNN(n_classes=self.data.n_classes, dtype=dtype,
                          batch_norm=self.config.batch_norm,
                          bn_axis=self._bn_axis())

    def build_data(self):
        # AlexNet trains on 227x227 crops (valid-padded 11x11/4 stem).
        return ImageNet_data(data_dir=self.config.data_dir, crop=227,
                             seed=self.config.seed,
                             augment_on_device=self.config.augment_on_device)


# reference-style alias
AlexNet_model = AlexNet
