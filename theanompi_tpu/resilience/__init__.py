"""theanompi_tpu.resilience — fault injection, retry/backoff,
supervised recovery, and checkpoint integrity.

The monitor subsystem (PR 1) *detects* stalls, stragglers, and crashes;
this subsystem *acts* on them (docs/RESILIENCE.md is the operator's
reference).  Four modules, one discipline:

* **faults** (``faults.py``) — a deterministic, config/env-driven
  fault-injection plane: kill worker rank R at step N, drop/delay the
  Kth ServiceClient RPC, truncate a just-written checkpoint, raise in
  a server exchange hook.  Activated by ``THEANOMPI_TPU_FAULTS`` (a
  JSON fault plan, inline or a file path) or ``faults.install(...)``;
  a strict zero-cost no-op when disabled — every instrumented site
  pays ONE ``is None`` check and allocates nothing (tested:
  ``tests/test_resilience.py::test_faults_disabled_is_noop``, the same
  discipline as the monitor's zero-write guarantee).
* **retry** (``retry.py``) — a reusable retry/backoff policy
  (exponential + jitter, deadline, retryable-exception classifier)
  adopted by ``ServiceClient.call`` (reconnect-with-backoff through a
  parameter-service restart), ``Checkpointer.restore`` (transient
  read I/O; the write *fence* deliberately stays retry-free — orbax
  clears its stored async-write error after raising it once, so a
  retried fence would mask data loss), and the bench probe loop.
* **supervisor** (``supervisor.py``) — bounded restart-from-center
  supervision for the async rules' worker threads, consuming the
  monitor's StragglerDetector signal; aborts when the worker quorum is
  lost.  GOSGD workers are not restartable (no center to restart
  from) and fall back to the hub's existing ``deactivate`` path.
* **recovery** (``recovery.py``) — checkpoint integrity (a manifest +
  per-file sha256 digest written alongside every completed Orbax save)
  and verified restore: a corrupt latest checkpoint falls back to the
  previous kept epoch instead of killing the resume.

Enablement contract: fault injection is OFF unless a plan is
installed; retry/recovery are *always-on behaviors of their host
components* (a reconnect only happens on a transport error, a manifest
only costs I/O at checkpoint-fence time) and add nothing to the BSP
hot path.  Supervision is OFF unless a rule is given
``max_restarts > 0`` (the default preserves the reference's fail-fast
a-dead-worker-kills-the-job semantics, SURVEY.md §5.3).
"""

from __future__ import annotations

from theanompi_tpu.resilience import faults, recovery, retry, supervisor
from theanompi_tpu.resilience.faults import ENV_VAR, FaultInjected, FaultPlan
from theanompi_tpu.resilience.retry import RetryPolicy
from theanompi_tpu.resilience.supervisor import WorkerSupervisor

__all__ = [
    "ENV_VAR", "FaultInjected", "FaultPlan", "RetryPolicy",
    "WorkerSupervisor", "faults", "recovery", "retry", "supervisor",
]
