"""Retry/backoff policy — exponential + jitter, deadline, classifier.

One policy object serves the three adopters named in docs/RESILIENCE.md:

* ``ServiceClient.call`` — reconnect-with-backoff so async workers
  survive a parameter-service restart (the client drives its own
  attempt loop with :meth:`delay`/:meth:`is_retryable`, because a
  reconnect + session rejoin happens *between* attempts);
* ``Checkpointer.restore`` — transient read-I/O retry on the resume
  path (:meth:`call`; the write fence stays retry-free — see
  utils/checkpoint.py on why a retried fence would mask data loss);
* ``bench.py``'s backend probe loop — :meth:`delay` replaces its
  hand-rolled flat 30 s sleeps.

The policy is deliberately dependency-free and side-effect-free except
for ``time.sleep`` in :meth:`call`; monitor counters
(``retry/attempts_total{site=...}``) are no-op gated like every other
monitor write.
"""

from __future__ import annotations

import random
import time
from typing import Any, Callable, Iterable

from theanompi_tpu import monitor

#: transport-shaped failures that reconnect/backoff can actually fix.
#: OSError covers the socket family (ConnectionError subclasses it);
#: EOFError is multiprocessing.connection's peer-went-away signal.
CONNECTION_ERRORS: tuple[type[BaseException], ...] = (OSError, EOFError)


class RetryPolicy:
    """Exponential backoff with jitter, an attempt cap, an optional
    wall-clock deadline, and a retryable-exception classifier.

    ``delay(attempt)`` for attempt=0,1,2,... is
    ``min(max_delay, base_delay * multiplier**attempt)`` scaled into
    ``[d*(1-jitter), d]`` uniformly — full determinism at ``jitter=0``.
    """

    def __init__(self, max_attempts: int = 5, base_delay: float = 0.05,
                 max_delay: float = 2.0, multiplier: float = 2.0,
                 jitter: float = 0.5, deadline_s: float | None = None,
                 retryable: Iterable[type[BaseException]] = CONNECTION_ERRORS,
                 classify: Callable[[BaseException], bool] | None = None,
                 name: str = "retry"):
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
        if not 0.0 <= jitter <= 1.0:
            raise ValueError(f"jitter must be in [0, 1], got {jitter}")
        self.max_attempts = max_attempts
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.multiplier = multiplier
        self.jitter = jitter
        self.deadline_s = deadline_s
        self.retryable = tuple(retryable)
        self.classify = classify
        self.name = name

    def is_retryable(self, exc: BaseException) -> bool:
        if self.classify is not None:
            return bool(self.classify(exc))
        return isinstance(exc, self.retryable)

    def delay(self, attempt: int) -> float:
        d = min(self.max_delay,
                self.base_delay * self.multiplier ** max(0, attempt))
        if self.jitter:
            d *= 1.0 - self.jitter * random.random()
        return d

    def call(self, fn: Callable[..., Any], *args,
             site: str | None = None,
             on_retry: Callable[[int, BaseException], None] | None = None,
             **kwargs) -> Any:
        """Run ``fn`` with retries; re-raises the last error when the
        attempt cap, the deadline, or the classifier says stop."""
        t0 = time.monotonic()
        for attempt in range(self.max_attempts):
            try:
                return fn(*args, **kwargs)
            except BaseException as e:
                if (attempt + 1 >= self.max_attempts
                        or not self.is_retryable(e)):
                    raise
                d = self.delay(attempt)
                if (self.deadline_s is not None
                        and time.monotonic() - t0 + d > self.deadline_s):
                    raise
                monitor.inc("retry/attempts_total",
                            site=site or self.name)
                if on_retry is not None:
                    on_retry(attempt + 1, e)
                time.sleep(d)
        raise AssertionError("unreachable")  # pragma: no cover
