"""Crash auto-resume: checkpoint integrity + verified restore.

A checkpoint that *exists* is not a checkpoint that *restores*: a
worker killed mid-write, a full disk, or plain bit rot leaves a step
directory that Orbax only rejects at resume time — which, before this
module, killed the resume and with it the whole recovery story.

Mechanism (wired through ``utils/checkpoint.Checkpointer``):

* **manifest** — after every *completed* Orbax write (queued at fence
  time, so the async write has landed; digested on the Checkpointer's
  background worker, off the training thread),
  ``manifest_{epoch}.json`` is written beside the step directory
  carrying per-file sizes + sha256 digests.  Manifests are pruned in
  step with ``max_to_keep``.
* **verify** — :func:`verify_checkpoint` recomputes sizes/digests
  against the manifest; a checkpoint with no manifest (pre-resilience
  or foreign) is *unverifiable*, not invalid — the restore itself is
  then the arbiter.
* **fallback** — :func:`restore_latest_verified` walks kept epochs
  newest-first, skipping any that fail verification (or whose actual
  restore raises), and returns the first that loads — so a truncated
  latest checkpoint costs one epoch of progress, not the run.  Used by
  every rule's resume path (``rules/base.py Rule._restore_latest``).
* **crash marker** — :func:`record_crash` is the postmortem hook: when
  a rule session dies with monitoring enabled, a small
  ``resilience_crash_*.json`` lands in the monitor run dir naming the
  rule, the error, and the newest manifest-bearing checkpoint — the
  machine-readable resume hint for the launcher's ``--max-restarts``
  auto-resume loop (or an operator).
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import time
from typing import Any

from theanompi_tpu import monitor

PyTree = Any

_CHUNK = 1 << 20


def manifest_path(directory: str, epoch: int) -> str:
    return os.path.join(directory, f"manifest_{int(epoch)}.json")


def _digest(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_CHUNK)
            if not chunk:
                return h.hexdigest()
            h.update(chunk)


def _walk_files(step_dir: str) -> dict[str, str]:
    out = {}
    for root, _dirs, files in os.walk(step_dir):
        for name in files:
            full = os.path.join(root, name)
            out[os.path.relpath(full, step_dir)] = full
    return out


def write_manifest(directory: str, epoch: int, step_dir: str) -> str:
    """Digest every file under ``step_dir`` into
    ``manifest_{epoch}.json`` (atomic rename — a crash mid-manifest
    must not leave a half-written manifest that fails every verify)."""
    files = {
        rel: {"size": os.path.getsize(full), "sha256": _digest(full)}
        for rel, full in sorted(_walk_files(step_dir).items())
    }
    path = manifest_path(directory, epoch)
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump({"epoch": int(epoch), "written": time.time(),
                   "n_files": len(files), "files": files}, f)
    os.replace(tmp, path)
    return path


def find_step_dir(directory: str, epoch: int) -> str | None:
    """The Orbax step directory for ``epoch`` — plain ``str(epoch)``
    by default, with a scan fallback for zero-padded step formats."""
    cand = os.path.join(directory, str(int(epoch)))
    if os.path.isdir(cand):
        return cand
    try:
        names = os.listdir(directory)
    except OSError:
        return None
    for name in names:
        if name.isdigit() and int(name) == int(epoch):
            path = os.path.join(directory, name)
            if os.path.isdir(path):
                return path
    return None


def verify_checkpoint(directory: str, epoch: int,
                      step_dir: str | None = None
                      ) -> tuple[bool | None, str]:
    """(ok, detail): True = verified, False = corrupt (with the first
    mismatch in ``detail``), None = no manifest to verify against."""
    mpath = manifest_path(directory, epoch)
    if not os.path.exists(mpath):
        return None, "no manifest"
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        return False, f"unreadable manifest: {e}"
    if step_dir is None:
        step_dir = find_step_dir(directory, epoch)
    if step_dir is None or not os.path.isdir(step_dir):
        return False, f"step dir missing for epoch {epoch}"
    on_disk = _walk_files(step_dir)
    for rel, want in manifest.get("files", {}).items():
        full = on_disk.get(rel)
        if full is None:
            return False, f"missing file: {rel}"
        size = os.path.getsize(full)
        if size != want["size"]:
            return False, (f"size mismatch {rel}: "
                           f"{size} != {want['size']}")
        if _digest(full) != want["sha256"]:
            return False, f"digest mismatch: {rel}"
    return True, f"{manifest.get('n_files', 0)} files verified"


def prune_manifests(directory: str, kept_epochs: set[int]) -> None:
    """Drop manifests of epochs Orbax's ``max_to_keep`` pruned."""
    import glob
    import re

    for path in glob.glob(os.path.join(directory, "manifest_*.json")):
        m = re.search(r"manifest_(\d+)\.json$", path)
        if m and int(m.group(1)) not in kept_epochs:
            try:
                os.unlink(path)
            except OSError:
                pass


def restore_latest_verified(ckpt, like: PyTree | None = None
                            ) -> tuple[int | None, PyTree | None]:
    """(epoch, payload) of the newest checkpoint that verifies AND
    restores; (None, None) when nothing is restorable.  ``ckpt`` is a
    ``utils.checkpoint.Checkpointer`` (duck-typed: ``kept_epochs``,
    ``directory``, ``restore``)."""
    epochs = sorted(ckpt.kept_epochs(), reverse=True)
    for i, epoch in enumerate(epochs):
        ok, detail = verify_checkpoint(ckpt.directory, epoch)
        if ok is False:
            monitor.inc("resilience/checkpoint_corrupt_total")
            # PROVEN corrupt (digest/size mismatch): quarantine the
            # step dir so the resumed run's save of this epoch really
            # writes (orbax silently skips saves to an existing step)
            # and nothing re-blesses the corrupt files.  Restore-raise
            # failures below are NOT quarantined — without a digest
            # proof the failure could be transient, and discarding a
            # good checkpoint is worse than a skipped re-save.
            quarantined = None
            qfn = getattr(ckpt, "quarantine_epoch", None)
            if qfn is not None:
                try:
                    quarantined = qfn(epoch)
                except OSError:
                    pass
            print(f"[resilience] checkpoint epoch {epoch} in "
                  f"{ckpt.directory} is CORRUPT ({detail}); "
                  f"{'quarantined to ' + quarantined if quarantined else 'left in place'}"
                  "; trying the previous kept epoch",
                  file=sys.stderr, flush=True)
            continue
        try:
            payload = ckpt.restore(epoch, like=like)
        except Exception as e:
            # unverifiable (no manifest) + unloadable, or a corruption
            # the manifest missed — same fallback
            monitor.inc("resilience/checkpoint_corrupt_total")
            print(f"[resilience] checkpoint epoch {epoch} in "
                  f"{ckpt.directory} failed to restore "
                  f"({type(e).__name__}: {e}); trying the previous "
                  "kept epoch", file=sys.stderr, flush=True)
            continue
        if i > 0:
            monitor.inc("resilience/checkpoint_fallbacks_total")
            print(f"[resilience] resumed from FALLBACK epoch {epoch} "
                  f"(skipped {i} corrupt/unloadable)", file=sys.stderr,
                  flush=True)
        return epoch, payload
    return None, None


def latest_manifest_epoch(directory: str) -> int | None:
    """Newest epoch with a manifest on disk — the cheap (digest-free)
    resume hint used by :func:`record_crash`; full verification
    happens at actual resume."""
    import glob
    import re

    best = None
    for path in glob.glob(os.path.join(directory, "manifest_*.json")):
        m = re.search(r"manifest_(\d+)\.json$", path)
        if m:
            e = int(m.group(1))
            best = e if best is None else max(best, e)
    return best


def record_crash(rule_name: str, exc: BaseException,
                 model=None) -> str | None:
    """The rule-session postmortem hook (rules/base.py): drop a
    machine-readable crash marker with a resume hint into the monitor
    run dir.  Never raises; no-op when monitoring is disabled."""
    run_dir = monitor.monitor_dir()
    if not monitor.enabled() or run_dir is None:
        return None
    try:
        marker = {
            "rule": rule_name,
            "error": f"{type(exc).__name__}: {exc}",
            "time": time.time(),
        }
        if model is not None:
            ckpt_dir = os.path.join(model.config.snapshot_dir, model.name)
            marker["checkpoint_dir"] = os.path.abspath(ckpt_dir)
            marker["latest_manifest_epoch"] = (
                latest_manifest_epoch(ckpt_dir)
                if os.path.isdir(ckpt_dir) else None)
        path = os.path.join(run_dir, f"resilience_crash_{os.getpid()}.json")
        with open(path, "w") as f:
            json.dump(marker, f)
        return path
    except Exception:
        return None  # a crash marker must never mask the crash
