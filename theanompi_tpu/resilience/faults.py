"""Deterministic fault injection.

A *fault plan* is a JSON list of fault specs; each spec names a
``site`` (where in the code the fault fires), coordinate matchers
(which event at that site), and an ``action``:

    [{"site": "worker_step", "worker": 1, "step": 3, "action": "raise"},
     {"site": "service_call", "op": "easgd_exchange", "nth": 3,
      "action": "drop"},
     {"site": "service_call", "op": "asgd_push_pull", "action": "delay",
      "delay_s": 0.2, "times": 2},
     {"site": "checkpoint", "epoch": 1, "action": "truncate"},
     {"site": "exchange", "kind": "easgd", "action": "raise"}]

Spec fields:

``site``
    required — matched literally against the call site's name.  The
    wired sites are ``worker_step`` (async-rule worker loops; coords
    ``rule``, ``worker``, ``step``), ``service_call``
    (``ServiceClient.call``; coord ``op``), ``checkpoint``
    (``Checkpointer`` manifest sync; coord ``epoch``),
    ``exchange`` (the in-process parameter stores; coord ``kind``),
    and the serving pair (docs/SERVING.md): ``serve_step`` (one
    replica batch execution; coords ``replica``, ``step`` — ``raise``
    fails the batch and exercises restart-from-export, ``delay``
    slows a replica so admission control trips) and ``serve_rpc``
    (the inference server's per-request handler; coord ``op``).
    Distributed ingest (docs/DESIGN.md "Distributed ingest") adds
    ``ingest_batch`` (reader-side batch assembly; coords ``reader``,
    ``epoch``, ``index`` — ``delay`` makes a reader a straggler;
    ``raise`` surfaces a typed server error that FAILS the trainer's
    stream fast — the client only retries typed ``Overloaded`` and
    only fails over on transport errors, so reader-death drills use a
    real kill, e.g. ``IngestProcessGroup.kill_reader`` or the bench
    ``--smoke`` leg) and ``ingest_pull`` (trainer-side fetch; coords
    ``index``, ``rank`` — ``raise`` injects a trainer-side stream
    failure).  Disaggregated serving (docs/SERVING.md "Disaggregated
    serving") adds ``router_route`` (the front-door router's
    per-request handler; coord ``op`` — ``raise`` fails a client
    stream at the router before any backend is touched) and
    ``page_migrate`` (the KV-page migration legs; coords ``side`` =
    ``export``/``adopt`` and, on the adopt side, ``replica`` —
    ``raise`` on ``export`` sheds the prefill, on ``adopt`` it fails
    the decode leg and exercises router failover).
``action``
    ``raise`` (default) raises :class:`FaultInjected` at the site;
    ``delay`` sleeps ``delay_s`` seconds (default 0.1) then lets the
    call proceed; any other string (``drop``, ``truncate``) is
    returned to the call site, which implements the effect —
    ``ServiceClient`` turns ``drop`` into a synthesized transport
    error (exercising the reconnect path), the checkpointer turns
    ``truncate`` into a half-truncated file in the just-written epoch
    dir.
``nth``
    1-based: fire on the nth *matching* event (default 1 — the first).
``times``
    how many consecutive matching events fire from ``nth`` on
    (default 1); ``-1`` = every matching event forever.

Any other key is a coordinate matcher: the spec matches only events
whose ``fire(site, key=value, ...)`` call carries an equal value
(compared as strings, so ``"worker": 1`` and ``"worker": "1"`` are the
same).  A coordinate the call site doesn't pass never matches.

Activation: ``THEANOMPI_TPU_FAULTS`` (inline JSON or a path to a JSON
file) is read once at import, so every process of a run — launcher,
workers, a tmserver — picks the plan up from its environment; the
launcher's ``--fault-plan`` flag re-reads it after setting the env
var.  Tests use :func:`install` / :func:`clear` directly.

No-op discipline (the contract every hot loop relies on): with no
plan installed, :func:`fire` returns after ONE ``is None`` check and
allocates nothing.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any

from theanompi_tpu import monitor

ENV_VAR = "THEANOMPI_TPU_FAULTS"

#: spec keys that are control fields, not coordinate matchers
_CONTROL_KEYS = frozenset({"site", "action", "nth", "times", "delay_s"})


class FaultInjected(RuntimeError):
    """Raised by a ``raise``-action fault.  A plain RuntimeError
    subclass so the supervised-recovery path treats it exactly like a
    real worker crash — the point of injecting it."""


class _Spec:
    """One compiled fault spec with its private match counter."""

    def __init__(self, raw: dict):
        if not isinstance(raw, dict) or "site" not in raw:
            raise ValueError(f"fault spec needs a 'site' key: {raw!r}")
        self.site = str(raw["site"])
        self.action = str(raw.get("action", "raise"))
        self.nth = int(raw.get("nth", 1))
        self.times = int(raw.get("times", 1))
        self.delay_s = float(raw.get("delay_s", 0.1))
        self.coords = {k: str(v) for k, v in raw.items()
                       if k not in _CONTROL_KEYS}
        if self.nth < 1:
            raise ValueError(f"fault spec nth must be >= 1: {raw!r}")
        self._matched = 0

    def matches(self, site: str, coords: dict[str, Any]) -> bool:
        if site != self.site:
            return False
        for k, want in self.coords.items():
            if k not in coords or str(coords[k]) != want:
                return False
        return True

    def should_fire(self) -> bool:
        """Count a matching event; True while inside [nth, nth+times)."""
        self._matched += 1
        if self._matched < self.nth:
            return False
        return self.times < 0 or self._matched < self.nth + self.times


class FaultPlan:
    """A compiled, thread-safe fault plan (see module docstring)."""

    def __init__(self, specs: list[dict]):
        self._specs = [_Spec(s) for s in specs]
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self._specs)

    def fire(self, site: str, **coords) -> str | None:
        """Match + perform the first firing spec; None when nothing
        fires.  ``raise`` raises here; ``delay`` sleeps here; other
        actions are returned for the call site to implement."""
        with self._lock:
            action = None
            for spec in self._specs:
                if spec.matches(site, coords) and spec.should_fire():
                    action = spec.action
                    break
        if action is None:
            return None
        monitor.inc("resilience/faults_injected_total",
                    site=site, action=action)
        print(f"[resilience] FAULT {action} at {site} "
              f"{coords}", file=sys.stderr, flush=True)
        if action == "raise":
            raise FaultInjected(f"injected fault at {site} {coords}")
        if action == "delay":
            time.sleep(spec.delay_s)
        return action


#: the active plan — None is the strict no-op state
_plan: FaultPlan | None = None


def enabled() -> bool:
    return _plan is not None


def fire(site: str, **coords) -> str | None:
    """The instrumented-site entry point.  With no plan installed this
    is ONE attribute read + ``is None`` check — the zero-cost path."""
    plan = _plan
    if plan is None:
        return None
    return plan.fire(site, **coords)


def load(text_or_path: str) -> FaultPlan:
    """Parse a plan from inline JSON or a path to a JSON file."""
    text = text_or_path.strip()
    if not text.startswith(("[", "{")):
        with open(text_or_path) as f:
            text = f.read()
    specs = json.loads(text)
    if isinstance(specs, dict):
        specs = [specs]
    return FaultPlan(specs)


def install(plan_or_specs: FaultPlan | list[dict] | str) -> FaultPlan:
    """Activate a plan (replacing any previous one); returns it."""
    global _plan
    if isinstance(plan_or_specs, FaultPlan):
        plan = plan_or_specs
    elif isinstance(plan_or_specs, str):
        plan = load(plan_or_specs)
    else:
        plan = FaultPlan(plan_or_specs)
    _plan = plan
    return plan


def clear() -> None:
    """Deactivate fault injection (back to the strict no-op state)."""
    global _plan
    _plan = None


def install_from_env() -> FaultPlan | None:
    """(Re)read ``THEANOMPI_TPU_FAULTS``; None + cleared when unset.
    Called once at import and again by the launcher after it exports
    ``--fault-plan`` (the package may already be imported by then)."""
    raw = os.environ.get(ENV_VAR)
    if not raw:
        clear()
        return None
    plan = install(raw)
    print(f"[resilience] fault plan active: {len(plan)} spec(s) "
          f"from ${ENV_VAR}", file=sys.stderr, flush=True)
    return plan


install_from_env()
