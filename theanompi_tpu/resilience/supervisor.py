"""Worker supervision for the async rules.

The reference (and this rebuild's default) is fail-fast: any worker
exception aborts the whole session (SURVEY.md §5.3).  For long
multi-worker runs that is the wrong trade — one transient fault (a
dropped connection, an injected kill, an OOM-killed data thread)
should not discard hours of every other worker's progress.  The
TensorFlow paper (arXiv:1605.08695) treats component restart as a
first-class requirement; this module is that layer for the async
rules' worker *threads*.

:class:`WorkerSupervisor` wraps each worker target: when a worker
raises a recoverable error (any ``Exception``; ``BaseException``
escapees like KeyboardInterrupt stay fatal) and restart budget
remains, the rule-provided ``restart_from`` callback re-seeds the
worker's model from the center parameters and the worker function is
re-run.  A worker that exhausts its budget — or is not restartable at
all (GOSGD has no center; it passes ``restart_from=None``) — is
*lost*: the rule's ``on_lost`` hook runs (GOSGD's existing
``hub.deactivate`` path, so peers stop gossiping at the corpse), and
the session continues **unless the surviving-worker quorum drops
below ``min_workers``**, in which case the whole session aborts with
the worker's original error — the fail-fast contract, restored at the
quorum boundary.

Straggler handoff (docs/OBSERVABILITY.md): the rules feed
``monitor.observe_step``'s straggler flag into
:meth:`note_straggler`; the supervisor counts edge transitions
(``resilience/straggler_handoffs_total``) and exposes the live set —
a Python thread cannot be preempted, so a *stalled-but-alive* worker
is surfaced and counted rather than forcibly restarted (the stall
watchdog names it; the operator or the launcher-level auto-resume
acts on it).
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Callable, Sequence

from theanompi_tpu import monitor
from theanompi_tpu.analysis.lockgraph import make_lock
from theanompi_tpu.resilience.retry import RetryPolicy


class WorkerSupervisor:
    """Bounded restart-with-quorum supervision (module docstring)."""

    def __init__(self, n_workers: int, max_restarts: int = 1,
                 min_workers: int = 1,
                 restart_from: Callable[[int], None] | None = None,
                 on_lost: Callable[[int], None] | None = None,
                 backoff: RetryPolicy | None = None,
                 name: str = "rule"):
        if min_workers < 1:
            raise ValueError(f"min_workers must be >= 1, got {min_workers}")
        self.n_workers = n_workers
        self.max_restarts = max_restarts
        self.min_workers = min_workers
        self.restart_from = restart_from
        self.on_lost = on_lost
        self.name = name
        # short pause before re-running a restarted worker: the fault
        # that killed it (a service mid-restart, say) is often still
        # clearing; full retry semantics are overkill here
        self.backoff = backoff or RetryPolicy(
            max_attempts=max(2, max_restarts + 1), base_delay=0.1,
            max_delay=2.0, name=f"{name}-restart")
        self._lock = make_lock("WorkerSupervisor._lock")
        self._restarts: dict[int, int] = {}   # guarded_by: self._lock
        self._lost: set[int] = set()          # guarded_by: self._lock
        self._stragglers: set[int] = set()    # guarded_by: self._lock

    # -- introspection (rules put these in their result dict) ----------

    def restart_counts(self) -> dict[int, int]:
        with self._lock:
            return dict(self._restarts)

    def lost_workers(self) -> list[int]:
        with self._lock:
            return sorted(self._lost)

    def is_lost(self, rank: int) -> bool:
        with self._lock:
            return rank in self._lost

    def stragglers(self) -> list[int]:
        with self._lock:
            return sorted(self._stragglers)

    # -- detector handoff ---------------------------------------------

    def note_straggler(self, rank: int, flagged: bool) -> None:
        """Consume the StragglerDetector signal (the return value of
        ``monitor.observe_step``).  Edge-triggered bookkeeping only —
        see the module docstring on why a live thread is not
        restarted."""
        with self._lock:
            was = rank in self._stragglers
            if flagged == was:
                return
            if flagged:
                self._stragglers.add(rank)
            else:
                self._stragglers.discard(rank)
        if flagged:
            monitor.inc("resilience/straggler_handoffs_total",
                        worker=rank)

    # -- the run loop --------------------------------------------------

    def run(self, workers: Sequence[Callable], extra: Sequence[Callable] = ()
            ) -> None:
        """Run ``workers`` under supervision plus ``extra`` unsupervised
        targets (e.g. EASGD's orchestrator); every target receives the
        shared abort Event.  Joins everything; re-raises the first
        fatal error."""
        abort = threading.Event()
        errors: list[BaseException] = []

        def supervised(rank: int, fn: Callable):
            def loop():
                while not abort.is_set():
                    try:
                        fn(abort)
                        return
                    except BaseException as e:
                        # TM101 regression: the restart ordinal is
                        # returned from under _handle_failure's lock —
                        # the old bare self._restarts.get() here raced
                        # other workers' failure bookkeeping
                        attempt = self._handle_failure(
                            rank, e, errors, abort)
                        if not attempt:
                            return
                        try:
                            if self.restart_from is not None:
                                self.restart_from(rank)
                        except BaseException as e2:
                            # center unreachable etc. — restarting is
                            # hopeless; fail the session
                            with self._lock:
                                errors.append(e2)
                            abort.set()
                            return
                        time.sleep(self.backoff.delay(attempt - 1))
            return threading.Thread(target=loop, daemon=True,
                                    name=f"{self.name}-worker{rank}")

        def plain(i: int, fn: Callable):
            def run_once():
                try:
                    fn(abort)
                except BaseException as e:
                    with self._lock:
                        errors.append(e)
                    abort.set()
            return threading.Thread(target=run_once, daemon=True,
                                    name=f"{self.name}-extra{i}")

        threads = [supervised(r, fn) for r, fn in enumerate(workers)]
        threads += [plain(i, fn) for i, fn in enumerate(extra)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]

    def _handle_failure(self, rank: int, e: BaseException,
                        errors: list[BaseException],
                        abort: threading.Event) -> int:
        """Decide restart (returns the 1-based restart ordinal) vs
        stop-this-thread (returns 0); flips the session abort when the
        error is fatal or quorum is lost."""
        recoverable = isinstance(e, Exception)
        with self._lock:
            if abort.is_set():
                return 0
            n = self._restarts.get(rank, 0)
            if (recoverable and self.restart_from is not None
                    and n < self.max_restarts):
                self._restarts[rank] = n + 1
                print(f"[resilience] {self.name} worker {rank} died "
                      f"({type(e).__name__}: {e}); restarting from "
                      f"center ({n + 1}/{self.max_restarts})",
                      file=sys.stderr, flush=True)
                monitor.inc("resilience/worker_restarts_total",
                            worker=rank)
                return n + 1
            self._lost.add(rank)
            alive = self.n_workers - len(self._lost)
            monitor.inc("resilience/workers_lost_total", worker=rank)
            if not recoverable or alive < self.min_workers:
                print(f"[resilience] {self.name} worker {rank} lost "
                      f"({type(e).__name__}: {e}); "
                      f"{'fatal error' if not recoverable else 'quorum lost'}"
                      f" ({alive} alive < {self.min_workers} required) — "
                      "aborting session", file=sys.stderr, flush=True)
                errors.append(e)
                abort.set()
                return 0
        # outside the lock: the hook may do service I/O.  ``alive`` was
        # computed under the lock — the old f-string re-read self._lost
        # bare here (TM101)
        if self.on_lost is not None:
            try:
                self.on_lost(rank)
            except Exception as hook_err:
                print(f"[resilience] on_lost({rank}) hook failed: "
                      f"{hook_err}", file=sys.stderr, flush=True)
        print(f"[resilience] {self.name} worker {rank} lost "
              f"({type(e).__name__}: {e}); continuing with "
              f"{alive} worker(s)", file=sys.stderr, flush=True)
        return 0
