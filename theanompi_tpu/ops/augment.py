"""Device-side augmentation: crop + mirror + normalize inside the step.

The reference did crop/flip on the host in its parallel loader
(SURVEY.md §2.9/§3.4) because the GPU was busy and host cores were
plentiful.  On this environment the economics invert: one host core
cannot augment 2500+ img/s (measured: the fused native C++ kernel tops
out ~1600 img/s), while the TPU's VPU does the same work in noise
compared to the conv FLOPs.  So the TPU-native pipeline ships RAW
uint8 store images (e.g. 256x256) to the device — 4x fewer H2D bytes
than normalized fp32 crops — and the jitted train step crops, mirrors
and normalizes on device.

The transform is built once per dataset (``make_device_augment``) and
applied by ``TpuModel.loss_fn``/``eval_fn`` when the dataset exposes
it as ``device_transform``; randomness comes from the step rng, so the
whole path stays one compiled SPMD program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def make_device_augment(crop: int, mean=None, std=None,
                        divisor: float = 255.0, flip: bool = True,
                        pad: int = 0):
    """Build ``transform(x, rng, train) -> float32 (N, crop, crop, C)``.

    Train: per-image random crop window + mirror-half (rng required).
    Eval: deterministic center crop, no mirror (rng may be None).
    Both normalize ``(x/divisor - mean)/std`` in fp32 (the model casts
    to its compute dtype at the stem).
    """
    mean_a = None if mean is None else jnp.asarray(mean, jnp.float32)
    std_a = None if std is None else jnp.asarray(std, jnp.float32)

    def normalize(win):
        win = win.astype(jnp.float32) / divisor
        if mean_a is not None:
            win = win - mean_a
        if std_a is not None:
            win = win / std_a
        return win

    def transform(x, rng, train: bool):
        n, h, w, c = x.shape
        if pad:
            x = jnp.pad(x, ((0, 0), (pad, pad), (pad, pad), (0, 0)),
                        mode="reflect")
            h, w = h + 2 * pad, w + 2 * pad
        if h < crop or w < crop:
            raise ValueError(f"images {h}x{w} smaller than crop {crop}")
        if train:
            ky, kx, kf = jax.random.split(rng, 3)
            ys = jax.random.randint(ky, (n,), 0, h - crop + 1)
            xs = jax.random.randint(kx, (n,), 0, w - crop + 1)
        else:
            ys = jnp.full((n,), (h - crop) // 2, jnp.int32)
            xs = jnp.full((n,), (w - crop) // 2, jnp.int32)

        def slice_one(img, y0, x0):
            return jax.lax.dynamic_slice(img, (y0, x0, 0), (crop, crop, c))

        out = jax.vmap(slice_one)(x, ys, xs)
        if train and flip:
            flips = jax.random.bernoulli(kf, 0.5, (n,))
            out = jnp.where(flips[:, None, None, None], out[:, :, ::-1, :],
                            out)
        return normalize(out)

    return transform
