"""Pallas TPU kernel for cross-channel LRN (forward + custom VJP).

This is the TPU default for ``ops.lrn`` (it microbenchmarked ~1.2-1.5x
faster fwd+bwd than the XLA-composed form on the v5e chip — see
tools/bench_lrn.py).  It tiles the flattened (N*H*W, C) view into VMEM
blocks, computes the windowed squared-sum on the VPU in one pass, and
backs it with an analytic VJP so the backward pass reuses the same
kernel shape instead of differentiating through the shift-and-add
chain (W^T is the adjoint window — equal to W for odd n):

    y  = x * s^{-beta},            s = k + a * W(x^2)
    dx = g * s^{-beta} - 2*a*beta * x * W^T(g * x * s^{-beta-1})

Falls back to interpret mode off-TPU so the numerics are unit-testable
on the CPU mesh.  Select explicitly with ``ops.lrn(..., impl=...)`` or
the ``THEANOMPI_TPU_LRN_IMPL`` env var.
"""

from __future__ import annotations

import functools

import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from theanompi_tpu.ops.lrn import window_sum as _window_sum

# rows of the flattened (pixels, channels) view per VMEM block; with
# C<=512 fp32 this stays well under the ~16MB VMEM budget
TILE_M = 1024


def _fwd_kernel(x_ref, y_ref, *, n, k, a, beta):
    x = x_ref[:]
    s = k + a * _window_sum(x * x, n)
    y_ref[:] = x * s ** (-beta)


def _bwd_kernel(x_ref, g_ref, dx_ref, *, n, k, a, beta):
    x = x_ref[:]
    g = g_ref[:]
    s = k + a * _window_sum(x * x, n)
    s_mb1 = s ** (-beta - 1.0)
    dx_ref[:] = g * s_mb1 * s - 2.0 * a * beta * x * _window_sum(
        g * x * s_mb1, n, adjoint=True)


def _blocked_call(kernel, n_in: int, m: int, c: int, dtype,
                  interpret: bool):
    tile = min(TILE_M, m)
    grid = (pl.cdiv(m, tile),)
    spec = pl.BlockSpec((tile, c), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[spec] * n_in,
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((m, c), dtype),
        interpret=interpret,
    )


def _auto_interpret() -> bool:
    return jax.default_backend() != "tpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def lrn_pallas(x: jax.Array, n: int = 5, k: float = 2.0,
               alpha: float = 1e-4, beta: float = 0.75,
               alpha_scaled_by_n: bool = True) -> jax.Array:
    """Cross-channel LRN for NHWC input — Pallas TPU kernel."""
    y, _ = _lrn_fwd(x, n, k, alpha, beta, alpha_scaled_by_n)
    return y


def _lrn_fwd(x, n, k, alpha, beta, alpha_scaled_by_n):
    if x.ndim != 4:
        raise ValueError(f"lrn expects NHWC, got shape {x.shape}")
    a = alpha / n if alpha_scaled_by_n else alpha
    b, h, w, c = x.shape
    m = b * h * w
    flat = x.reshape(m, c)
    kern = functools.partial(_fwd_kernel, n=n, k=k, a=a, beta=beta)
    y = _blocked_call(kern, 1, m, c, x.dtype, _auto_interpret())(flat)
    return y.reshape(x.shape), x


def _lrn_bwd(n, k, alpha, beta, alpha_scaled_by_n, x, g):
    a = alpha / n if alpha_scaled_by_n else alpha
    b, h, w, c = x.shape
    m = b * h * w
    kern = functools.partial(_bwd_kernel, n=n, k=k, a=a, beta=beta)
    dx = _blocked_call(kern, 2, m, c, x.dtype, _auto_interpret())(
        x.reshape(m, c), g.reshape(m, c))
    return (dx.reshape(x.shape),)


lrn_pallas.defvjp(_lrn_fwd, _lrn_bwd)
