"""Pallas TPU kernel for the fused scale-bias(-residual)-ReLU epilogue
(forward + custom VJP), with a plain-XLA fallback.

Why this exists: the round-4/5 op-level account of the real v5e step
(artifacts/mfu_account.json, artifacts/fusion_deepdive.json) charges
**5.81 ms/step — 12.4% of device time at ~1% of the FLOPs — to 269
"loop fusion" events**, dominated by the BatchNorm normalize/affine
passes and the residual add+relu epilogues of the bottleneck blocks,
all running at 678–992 GB/s of pure HBM streaming.  XLA fuses each of
them locally but still materializes the BN output before the residual
add and the add before the relu in several block shapes.  This kernel
collapses the whole epilogue into ONE pass over the activation:

    y = act(x * scale + bias [+ residual])

where ``scale``/``bias`` are the folded BN affine
(``gamma*rsqrt(var+eps)`` and ``beta - mean*scale``: the batch-stat
reductions stay XLA — they are genuine reductions, not streaming
waste) or a plain conv-bias (``scale=1``).  The backward recomputes
the relu mask from the saved input instead of storing it and emits
``dx``/``dresidual`` plus the folded-parameter cotangents in the same
single stream, so fwd+bwd touch x, residual and g once each.

Like ops/lrn_pallas.py this tiles the flattened ``(N*H*W, C)`` view
into VMEM row-blocks and runs in interpret mode off-TPU, so the
numerics are unit-tested on the CPU mesh (tests/test_fused_bn.py pins
forward AND gradient against the unfused XLA reference).  Opt-in via
``ModelConfig.bn_act_impl='pallas'`` — 'xla' stays the default until
the queued A/B pair (tools/xla_sweep.py, artifacts/) confirms the
account's prediction on chip.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: per-operand VMEM block budget; with 4 streamed operands (x, g, dx,
#: res) in the widest backward this keeps the working set ~2 MB
_TILE_BYTES = 1 << 19


def _auto_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _tile_rows(m: int, c: int, itemsize: int) -> int:
    rows = _TILE_BYTES // max(c * itemsize, 1)
    rows = max(8, (rows // 8) * 8)
    return min(rows, m)


def _row_mask(shape, m_rows: int, tile: int):
    """True for rows that exist in the un-padded (m, c) view — the last
    grid block may be padded and OOB reads are NOT guaranteed zero, so
    every reduction masks by absolute row index."""
    rows = pl.program_id(0) * tile + jax.lax.broadcasted_iota(
        jnp.int32, shape, 0)
    return rows < m_rows


# -- kernels over the flattened (rows, C) view ----------------------------

def _fwd_kernel(x_ref, s_ref, b_ref, y_ref, *, relu):
    z = x_ref[:].astype(jnp.float32) * s_ref[0] + b_ref[0]
    if relu:
        z = jnp.maximum(z, 0.0)
    y_ref[:] = z.astype(y_ref.dtype)


def _fwd_res_kernel(x_ref, s_ref, b_ref, r_ref, y_ref, *, relu):
    z = (x_ref[:].astype(jnp.float32) * s_ref[0] + b_ref[0]
         + r_ref[:].astype(jnp.float32))
    if relu:
        z = jnp.maximum(z, 0.0)
    y_ref[:] = z.astype(y_ref.dtype)


def _bwd_kernel(x_ref, s_ref, b_ref, g_ref, dx_ref, ds_ref, db_ref,
                *, relu, m_rows, tile):
    x = x_ref[:].astype(jnp.float32)
    s = s_ref[0]
    g = g_ref[:].astype(jnp.float32)
    if relu:
        g = jnp.where(x * s + b_ref[0] > 0, g, 0.0)
    g = jnp.where(_row_mask(x.shape, m_rows, tile), g, 0.0)
    dx_ref[:] = (g * s).astype(dx_ref.dtype)
    ds_ref[0] = jnp.sum(g * x, axis=0)
    db_ref[0] = jnp.sum(g, axis=0)


def _bwd_res_kernel(x_ref, s_ref, b_ref, r_ref, g_ref,
                    dx_ref, dr_ref, ds_ref, db_ref,
                    *, relu, m_rows, tile):
    x = x_ref[:].astype(jnp.float32)
    s = s_ref[0]
    g = g_ref[:].astype(jnp.float32)
    if relu:
        z = x * s + b_ref[0] + r_ref[:].astype(jnp.float32)
        g = jnp.where(z > 0, g, 0.0)
    g = jnp.where(_row_mask(x.shape, m_rows, tile), g, 0.0)
    dx_ref[:] = (g * s).astype(dx_ref.dtype)
    dr_ref[:] = g.astype(dr_ref.dtype)
    ds_ref[0] = jnp.sum(g * x, axis=0)
    db_ref[0] = jnp.sum(g, axis=0)


def _specs(m: int, c: int, itemsize: int):
    """(grid, row-block spec, broadcast (1,C) spec, partial-sum spec,
    tile) shared by the forward and backward pallas_calls."""
    tile = _tile_rows(m, c, itemsize)
    grid = (pl.cdiv(m, tile),)
    row = pl.BlockSpec((tile, c), lambda i: (i, 0),
                       memory_space=pltpu.VMEM)
    vec = pl.BlockSpec((1, c), lambda i: (0, 0), memory_space=pltpu.VMEM)
    part = pl.BlockSpec((1, c), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    return grid, row, vec, part, tile


# -- custom_vjp wrappers (2-D view; reshape happens in scale_bias_act) ----

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fused(x, scale, bias, relu, out_dtype):
    y, _ = _fused_fwd(x, scale, bias, relu, out_dtype)
    return y


def _fused_fwd(x, scale, bias, relu, out_dtype):
    m, c = x.shape
    grid, row, vec, _part, _tile = _specs(m, c, x.dtype.itemsize)
    out_row = pl.BlockSpec(row.block_shape, lambda i: (i, 0),
                           memory_space=pltpu.VMEM)
    y = pl.pallas_call(
        functools.partial(_fwd_kernel, relu=relu),
        grid=grid,
        in_specs=[row, vec, vec],
        out_specs=out_row,
        out_shape=jax.ShapeDtypeStruct((m, c), out_dtype),
        interpret=_auto_interpret(),
    )(x, scale.reshape(1, c), bias.reshape(1, c))
    return y, (x, scale, bias)


def _fused_bwd(relu, out_dtype, saved, g):
    x, scale, bias = saved
    m, c = x.shape
    grid, row, vec, part, tile = _specs(m, c, x.dtype.itemsize)
    n_blocks = grid[0]
    dx, ds_p, db_p = pl.pallas_call(
        functools.partial(_bwd_kernel, relu=relu, m_rows=m, tile=tile),
        grid=grid,
        in_specs=[row, vec, vec, row],
        out_specs=[row, part, part],
        out_shape=[
            jax.ShapeDtypeStruct((m, c), x.dtype),
            jax.ShapeDtypeStruct((n_blocks, c), jnp.float32),
            jax.ShapeDtypeStruct((n_blocks, c), jnp.float32),
        ],
        interpret=_auto_interpret(),
    )(x, scale.reshape(1, c), bias.reshape(1, c), g)
    return (dx, ds_p.sum(0).astype(scale.dtype),
            db_p.sum(0).astype(bias.dtype))


_fused.defvjp(_fused_fwd, _fused_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _fused_res(x, scale, bias, res, relu, out_dtype):
    y, _ = _fused_res_fwd(x, scale, bias, res, relu, out_dtype)
    return y


def _fused_res_fwd(x, scale, bias, res, relu, out_dtype):
    m, c = x.shape
    grid, row, vec, _part, _tile = _specs(m, c, x.dtype.itemsize)
    y = pl.pallas_call(
        functools.partial(_fwd_res_kernel, relu=relu),
        grid=grid,
        in_specs=[row, vec, vec, row],
        out_specs=pl.BlockSpec(row.block_shape, lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, c), out_dtype),
        interpret=_auto_interpret(),
    )(x, scale.reshape(1, c), bias.reshape(1, c), res)
    return y, (x, scale, bias, res)


def _fused_res_bwd(relu, out_dtype, saved, g):
    x, scale, bias, res = saved
    m, c = x.shape
    grid, row, vec, part, tile = _specs(m, c, x.dtype.itemsize)
    n_blocks = grid[0]
    dx, dr, ds_p, db_p = pl.pallas_call(
        functools.partial(_bwd_res_kernel, relu=relu, m_rows=m,
                          tile=tile),
        grid=grid,
        in_specs=[row, vec, vec, row, row],
        out_specs=[row, row, part, part],
        out_shape=[
            jax.ShapeDtypeStruct((m, c), x.dtype),
            jax.ShapeDtypeStruct((m, c), res.dtype),
            jax.ShapeDtypeStruct((n_blocks, c), jnp.float32),
            jax.ShapeDtypeStruct((n_blocks, c), jnp.float32),
        ],
        interpret=_auto_interpret(),
    )(x, scale.reshape(1, c), bias.reshape(1, c), res, g)
    return (dx, ds_p.sum(0).astype(scale.dtype),
            db_p.sum(0).astype(bias.dtype), dr)


_fused_res.defvjp(_fused_res_fwd, _fused_res_bwd)


# -- public API -----------------------------------------------------------

def scale_bias_act(x: jax.Array, scale: jax.Array, bias: jax.Array,
                   residual: jax.Array | None = None,
                   act: str | None = "relu", impl: str = "xla",
                   out_dtype=None) -> jax.Array:
    """``act(x * scale + bias [+ residual])`` over channel-last input.

    ``scale``/``bias`` are per-channel vectors (the folded BN affine or
    a conv bias with ``scale=ones``); ``residual`` must match ``x``'s
    shape.  ``impl='pallas'`` runs the fused single-stream kernel
    (interpret mode off-TPU); ``impl='xla'`` is the plain jnp fallback
    the kernel is oracle-tested against.  Math is f32 either way; the
    result is cast to ``out_dtype`` (default: ``x.dtype``).
    """
    if act not in (None, "relu"):
        raise ValueError(f"unknown act {act!r} (want None|'relu')")
    c = x.shape[-1]
    if scale.shape != (c,) or bias.shape != (c,):
        raise ValueError(
            f"scale/bias must be ({c},) channel vectors, got "
            f"{scale.shape}/{bias.shape} for x {x.shape}")
    if residual is not None and residual.shape != x.shape:
        raise ValueError(f"residual {residual.shape} != x {x.shape}")
    out_dtype = jnp.dtype(out_dtype if out_dtype is not None else x.dtype)
    if x.size == 0 and impl == "pallas":
        # zero-size activations (e.g. a VALID pool collapsing a tiny
        # test shape) have no rows to tile; the jnp path is exact
        impl = "xla"
    if impl == "xla":
        z = (x.astype(jnp.float32) * scale.astype(jnp.float32)
             + bias.astype(jnp.float32))
        if residual is not None:
            z = z + residual.astype(jnp.float32)
        if act == "relu":
            z = jnp.maximum(z, 0.0)
        return z.astype(out_dtype)
    if impl != "pallas":
        raise ValueError(f"unknown impl {impl!r} (want 'xla'|'pallas')")
    shape = x.shape
    m = 1
    for d in shape[:-1]:
        m *= d
    x2 = x.reshape(m, c)
    if residual is None:
        y = _fused(x2, scale, bias, act == "relu", out_dtype)
    else:
        y = _fused_res(x2, scale, bias, residual.reshape(m, c),
                       act == "relu", out_dtype)
    return y.reshape(shape)
