"""Pallas TPU kernel for the ResNet stem max-pool (3x3/stride-2/pad-1),
forward + argmax-based custom VJP.

Why this exists: the round-4/5 op-level account of the real v5e step
(artifacts/mfu_account.json, artifacts/fusion_deepdive.json) shows the
ONE maxpool backward as XLA ``select-and-scatter`` costing
0.761 ms/step at 608 GB/s = 74% of HBM peak — the only slice of the
near-zero-FLOP time with real bandwidth headroom.  select-and-scatter
re-reads the full input x (205 MB at b=128 bf16) to rediscover each
window's argmax.  This kernel stores the argmax at forward time
(int8, 1/8th of x) and computes the backward as a pure GATHER:

    dx[i,j] = sum over the <=4 windows covering (i,j) of
              g[w] * [idx[w] == tap of (i,j) in w]

so the backward streams g + idx + writes dx ≈ 282 MB instead of
~460 MB — a ~0.34 ms bound vs the measured 0.76.  The gather is
expressed scatter-free by decomposing input pixels into (row, col)
parity classes: for stride 2 each class receives from a fixed subset
of the 9 taps at a fixed output offset, so each class is a sum of
``where(idx_slice == tap, g_slice, 0)`` terms and the four class
planes interleave back with stack+reshape.

Tie semantics: FIRST maximum in row-major window order (strict ``>``
during the tap scan), matching jnp.argmax; XLA's select-and-scatter
also routes ties to one element, so gradient mass is conserved either
way — tests pin equality on tie-free inputs and conservation always.

Like ops/lrn_pallas.py this runs in interpret mode off-TPU, so the
numerics are unit-tested on the CPU mesh; the on-chip win is measured
by tools/bench_maxpool.py (queued).  Opt-in via
``ModelConfig.pool_impl='pallas'`` — 'xla' stays the default until the
chip confirms the account's prediction.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

def _auto_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _fwd_kernel(x_ref, y_ref, idx_ref, *, oh, ow):
    x = x_ref[0]                       # (H, W, C)
    # -inf padding exactly like XLA's reduce_window init, so a window
    # of true -inf inputs still yields -inf (a finite sentinel would
    # mask an upstream overflow).  bidx initializes to tap 4 — the
    # window CENTER, which is in-bounds for every window under pad-1 —
    # so when nothing beats -inf (all-(-inf) window) the backward
    # still routes that window's cotangent to a real pixel and
    # gradient mass stays conserved.  Finite ties are unaffected: the
    # first tap to exceed -inf claims the window, so first-max
    # row-major order still holds.
    neg = jnp.asarray(-jnp.inf, x.dtype)
    xp = jnp.pad(x, ((1, 1), (1, 1), (0, 0)), constant_values=neg)
    best = jnp.full((oh, ow, x.shape[-1]), neg, x.dtype)
    bidx = jnp.full((oh, ow, x.shape[-1]), 4, jnp.int32)
    for t in range(9):
        dy, dx = divmod(t, 3)
        v = jax.lax.slice(xp, (dy, dx, 0),
                          (dy + 2 * oh - 1, dx + 2 * ow - 1,
                           xp.shape[-1]), (2, 2, 1))
        # strict >: first max wins ties.  NaN must PROPAGATE like
        # reduce_window's max (NaN > x is false, so a bare scan would
        # silently drop NaNs): the first NaN tap claims the window and
        # sticks (isnan(best) blocks later takes).
        take = ((v > best) | jnp.isnan(v)) & ~jnp.isnan(best)
        best = jnp.where(take, v, best)
        bidx = jnp.where(take, t, bidx)
    y_ref[0] = best
    idx_ref[0] = bidx.astype(jnp.int8)


def _fwd_value_kernel(x_ref, y_ref, *, oh, ow):
    """idx-free forward for the PRIMAL path: under plain inference/eval
    (no grad), the two-output kernel would still write the int8 argmax
    plane (~x/8 bytes of HBM) that XLA cannot dead-code-eliminate out
    of an opaque pallas_call (round-5 review)."""
    x = x_ref[0]
    neg = jnp.asarray(-jnp.inf, x.dtype)
    xp = jnp.pad(x, ((1, 1), (1, 1), (0, 0)), constant_values=neg)
    best = jnp.full((oh, ow, x.shape[-1]), neg, x.dtype)
    for t in range(9):
        dy, dx = divmod(t, 3)
        v = jax.lax.slice(xp, (dy, dx, 0),
                          (dy + 2 * oh - 1, dx + 2 * ow - 1,
                           xp.shape[-1]), (2, 2, 1))
        best = jnp.where((v > best) | jnp.isnan(v), v, best)
    y_ref[0] = best


def _bwd_kernel(g_ref, idx_ref, dx_ref, *, oh, ow):
    g = g_ref[0]                       # (OH, OW, C)
    idx = idx_ref[0].astype(jnp.int32)
    c = g.shape[-1]
    # pad by one output cell on each side; padded idx = -1 never matches
    gp = jnp.pad(g, ((1, 1), (1, 1), (0, 0)))
    ip = jnp.pad(idx, ((1, 1), (1, 1), (0, 0)), constant_values=-1)

    def class_plane(pi, pj):
        acc = jnp.zeros((oh, ow, c), g.dtype)
        for dy in range(3):
            if (pi + 1 - dy) % 2:
                continue
            o = (pi + 1 - dy) // 2     # output row offset, 0 or 1
            for dx in range(3):
                if (pj + 1 - dx) % 2:
                    continue
                p = (pj + 1 - dx) // 2
                gs = jax.lax.slice(gp, (o + 1, p + 1, 0),
                                   (o + 1 + oh, p + 1 + ow, c))
                is_ = jax.lax.slice(ip, (o + 1, p + 1, 0),
                                    (o + 1 + oh, p + 1 + ow, c))
                acc = acc + jnp.where(is_ == dy * 3 + dx, gs, 0)
        return acc

    ee, eo = class_plane(0, 0), class_plane(0, 1)
    oe, oo = class_plane(1, 0), class_plane(1, 1)
    # interleave columns within each row class, then rows
    top = jnp.stack([ee, eo], axis=2).reshape(oh, 2 * ow, c)
    bot = jnp.stack([oe, oo], axis=2).reshape(oh, 2 * ow, c)
    dx_ref[0] = jnp.stack([top, bot], axis=1).reshape(2 * oh, 2 * ow, c)


def _check(x):
    if x.ndim != 4:
        raise ValueError(f"maxpool3x3s2 expects NHWC, got {x.shape}")
    b, h, w, c = x.shape
    if h % 2 or w % 2:
        raise ValueError(
            "maxpool3x3s2 (stride 2, pad 1) needs even H and W so the "
            f"parity-interleaved backward tiles exactly; got {x.shape} "
            "— use ops.maxpool default impl='xla' for odd sizes")
    return b, h, w, c


@jax.custom_vjp
def maxpool3x3s2(x: jax.Array) -> jax.Array:
    """3x3/stride-2/pad-1 max pool over NHWC via the Pallas kernel —
    the ResNet stem pool geometry (models/resnet50.py).

    The primal body (inference/eval, no grad) runs the idx-free
    kernel; under AD the custom_vjp fwd rule below replaces it with
    the argmax-saving variant."""
    b, h, w, c = _check(x)
    oh, ow = h // 2, w // 2
    return pl.pallas_call(
        functools.partial(_fwd_value_kernel, oh=oh, ow=ow),
        grid=(b,),
        in_specs=[pl.BlockSpec((1, h, w, c), lambda i: (i, 0, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((1, oh, ow, c), lambda i: (i, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, oh, ow, c), x.dtype),
        interpret=_auto_interpret(),
    )(x)


def _mp_fwd(x):
    b, h, w, c = _check(x)
    oh, ow = h // 2, w // 2
    kern = functools.partial(_fwd_kernel, oh=oh, ow=ow)
    y, idx = pl.pallas_call(
        kern,
        grid=(b,),
        in_specs=[pl.BlockSpec((1, h, w, c), lambda i: (i, 0, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=[
            pl.BlockSpec((1, oh, ow, c), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, oh, ow, c), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, oh, ow, c), x.dtype),
            jax.ShapeDtypeStruct((b, oh, ow, c), jnp.int8),
        ],
        interpret=_auto_interpret(),
    )(x)
    return y, idx


def _mp_bwd(idx, g):
    b, oh, ow, c = idx.shape
    h, w = 2 * oh, 2 * ow
    kern = functools.partial(_bwd_kernel, oh=oh, ow=ow)
    spec_o = pl.BlockSpec((1, oh, ow, c), lambda i: (i, 0, 0, 0),
                          memory_space=pltpu.VMEM)
    dx = pl.pallas_call(
        kern,
        grid=(b,),
        in_specs=[spec_o, spec_o],
        out_specs=pl.BlockSpec((1, h, w, c), lambda i: (i, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, h, w, c), g.dtype),
        interpret=_auto_interpret(),
    )(g, idx)
    return (dx,)


maxpool3x3s2.defvjp(_mp_fwd, _mp_bwd)
