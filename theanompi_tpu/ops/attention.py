"""Fused softmax attention as a Pallas TPU kernel.

The transformer family's hot op (beyond-parity surface — the reference
predates attention; its analogue is routing conv/LRN to cuDNN,
SURVEY.md §2.12).  The kernel computes one Q block's full attention in
VMEM — scores, causal/position mask, row softmax, and the PV matmul —
in a single pass per (batch*head, q-block) grid cell, so the (Tq, Tk)
score matrix never round-trips HBM the way the composed XLA form's
does.  Softmax statistics are computed in fp32 regardless of the
compute dtype.

Scope notes:

* K/V for one (batch, head) must fit VMEM alongside one fp32 score
  block (checked; oversize shapes fall back to the XLA path) — local
  shard lengths up to a few thousand, which is the regime this
  framework runs attention at: GLOBAL long context is the ring/
  Ulysses layer's job (parallel/sequence.py), and what each device
  sees locally is exactly this kernel's shape.
* Backward is ALSO fused (flash-style): the fwd emits the per-row
  logsumexp, and the bwd kernel recomputes p from (q, k, lse) block
  by block, accumulating dk/dv in fp32 VMEM scratch — the (Tq, Tk)
  matrix never exists outside VMEM in either direction.  Ragged
  q-blocks or oversize shapes fall back to the composed-XLA VJP.
* ``impl='auto'``: Pallas on TPU, XLA elsewhere; force with
  ``THEANOMPI_TPU_ATTN_IMPL=pallas|xla`` (interpret mode makes the
  Pallas path unit-testable on the CPU mesh, tests/test_ops.py).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# large-negative mask value: finite so softmax/online-softmax
# accumulators never produce inf-inf=nan; exp(-1e30 - m) underflows to
# exactly 0 once any real score is seen, wiping masked contributions.
# The single source — parallel/sequence.py imports it.
_MASK_NEG = -1e30
#: per-(batch*head) VMEM budget for K + V + one fp32 score block.
#: Env-tunable (THEANOMPI_TPU_ATTN_VMEM_MB / _ATTN_QBLOCK) so on-chip
#: block-size sweeps need no code edits; defaults are the round-2
#: interpret-validated values.
_VMEM_BUDGET_BYTES = int(float(os.environ.get(
    "THEANOMPI_TPU_ATTN_VMEM_MB", "12")) * 1024 * 1024)
if _VMEM_BUDGET_BYTES <= 0:
    raise ValueError("THEANOMPI_TPU_ATTN_VMEM_MB must be positive — 0 "
                     "would silently route every shape to the XLA path")
_Q_BLOCK = int(os.environ.get("THEANOMPI_TPU_ATTN_QBLOCK", "256"))
if _Q_BLOCK < 8 or _Q_BLOCK % 8:
    raise ValueError(f"THEANOMPI_TPU_ATTN_QBLOCK must be a positive "
                     f"multiple of 8 (sublane tiling), got {_Q_BLOCK}")


def block_scores(q, k, scale):
    """q (B,Tq,H,D) x k (B,Tk,H,D) -> (B,H,Tq,Tk); fp32 accumulation.
    Shared with parallel/sequence.py's ring/oracle forms."""
    return jnp.einsum("bqhd,bkhd->bhqk", q, k,
                      preferred_element_type=jnp.float32) * scale


def causal_mask(q_pos, k_pos):
    return q_pos[:, None] >= k_pos[None, :]          # (Tq, Tk)


def _kernel(q_ref, k_ref, v_ref, qpos_ref, kpos_ref, o_ref, lse_ref, *,
            scale, causal):
    q = q_ref[0]                                      # (TQ, D)
    k = k_ref[0]                                      # (TK, D)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale   # (TQ, TK)
    if causal:
        mask = qpos_ref[:] >= kpos_ref[:]             # (TQ,1)>=(1,TK)
        s = jnp.where(mask, s, _MASK_NEG)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(
        p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    o_ref[0] = (o / l).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l)                       # (TQ, 1) fp32


def _pallas_attention(q, k, v, q_pos, k_pos, scale, causal,
                      interpret: bool):
    b, tq, h, d = q.shape
    tk = k.shape[1]
    bh = b * h

    def fold(x):                                      # (B,T,H,D)->(BH,T,D)
        return x.transpose(0, 2, 1, 3).reshape(bh, x.shape[1], d)

    qf, kf, vf = fold(q), fold(k), fold(v)
    qp = q_pos.astype(jnp.int32).reshape(tq, 1)
    kp = k_pos.astype(jnp.int32).reshape(1, tk)

    tq_blk = min(_Q_BLOCK, tq)
    grid = (bh, pl.cdiv(tq, tq_blk))
    kern = functools.partial(_kernel, scale=scale, causal=causal)
    out, lse = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, tq_blk, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tk, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tk, d), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tq_blk, 1), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tk), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, tq_blk, d), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tq_blk, 1), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, tq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, qp, kp)
    return (out.reshape(b, h, tq, d).transpose(0, 2, 1, 3),
            lse.reshape(bh, tq, 1))


def _xla_attention(q, k, v, q_pos, k_pos, scale, causal):
    """The composed-XLA fallback (same primitives as the oracle)."""
    s = block_scores(q, k, scale)
    if causal:
        s = jnp.where(causal_mask(q_pos, k_pos)[None, None], s, _MASK_NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


def _fits_vmem(tq, tk, d, dtype) -> bool:
    itemsize = jnp.dtype(dtype).itemsize
    tq_blk = min(_Q_BLOCK, tq)
    need = (2 * tk * d * itemsize          # K + V
            + tq_blk * d * itemsize        # Q block
            + 2 * tq_blk * tk * 4)         # fp32 scores + exp
    return need <= _VMEM_BUDGET_BYTES


def _fits_vmem_bwd(tq, tk, d, dtype) -> bool:
    """The fused bwd holds whole Q/G/dq plus K/V/dk/dv per (b*h),
    fp32 copies of K/V (kmat/vmat), fp32 dk/dv scratch, and per-block
    fp32 casts of q/g."""
    itemsize = jnp.dtype(dtype).itemsize
    tq_blk = min(_Q_BLOCK, tq)
    need = (3 * tq * d * itemsize          # Q, G, dq
            + 4 * tk * d * itemsize        # K, V, dk, dv
            + 2 * tk * d * 4               # kmat/vmat fp32 copies
            + 2 * tk * d * 4               # fp32 dk/dv scratch
            + 2 * tq_blk * d * 4           # q/g block fp32 casts
            + 3 * tq_blk * tk * 4)         # s/p + dp/ds blocks
    return need <= _VMEM_BUDGET_BYTES


def _resolve_impl(impl: str | None, q, k) -> str:
    impl = impl or os.environ.get("THEANOMPI_TPU_ATTN_IMPL", "auto")
    if impl not in ("auto", "pallas", "xla"):
        raise ValueError(f"unknown attention impl {impl!r}")
    if impl == "auto":
        b, tq, h, d = q.shape
        if not _fits_vmem(tq, k.shape[1], d, q.dtype):
            return "xla"
        # ragged q-tails rely on Pallas out-of-range block padding that
        # is only exercised in interpret mode (ADVICE r2) — on real
        # silicon route them to XLA like the backward already does;
        # impl='pallas' still forces the kernel (how tests cover it)
        if tq % min(_Q_BLOCK, tq) != 0:
            return "xla"
        return "pallas" if jax.default_backend() == "tpu" else "xla"
    return impl


def _bwd_kernel(q_ref, k_ref, v_ref, qpos_ref, kpos_ref, g_ref, lse_ref,
                dq_ref, dk_ref, dv_ref, dk_s, dv_s, *, scale, causal,
                tq_blk):
    """Flash-style backward for one (batch*head): loop q-blocks,
    recompute p from (q, k, lse) — no stored score matrix anywhere —
    accumulating dk/dv in fp32 VMEM scratch."""
    kmat = k_ref[0].astype(jnp.float32)               # (TK, D)
    vmat = v_ref[0].astype(jnp.float32)
    dk_s[...] = jnp.zeros_like(dk_s)
    dv_s[...] = jnp.zeros_like(dv_s)
    n_blocks = q_ref.shape[1] // tq_blk

    def body(i, _):
        sl = pl.ds(i * tq_blk, tq_blk)
        q = q_ref[0, sl].astype(jnp.float32)          # (TQB, D)
        g = g_ref[0, sl].astype(jnp.float32)
        lse = lse_ref[0, sl]                          # (TQB, 1)
        s = jax.lax.dot_general(
            q, kmat, (((1,), (1,)), ((), ()))) * scale
        if causal:
            mask = qpos_ref[sl] >= kpos_ref[:]        # (TQB,1)>=(1,TK)
            s = jnp.where(mask, s, _MASK_NEG)
        p = jnp.exp(s - lse)
        # re-normalize: a no-op (sum==1) for ordinary rows, but a
        # FULLY-masked row saturates lse to _MASK_NEG in fp32 and
        # exp(s-lse)=1 everywhere — the divide restores the uniform
        # 1/Tk distribution the forward actually produced there
        p = p / jnp.sum(p, axis=-1, keepdims=True)
        dv_s[...] += jax.lax.dot_general(
            p, g, (((0,), (0,)), ((), ())))           # p^T g (TK, D)
        dp = jax.lax.dot_general(
            g, vmat, (((1,), (1,)), ((), ())))        # g v^T (TQB, TK)
        ds = p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True))
        dq_ref[0, sl] = (jax.lax.dot_general(
            ds, kmat, (((1,), (0,)), ((), ()))) * scale
        ).astype(dq_ref.dtype)
        dk_s[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ()))) * scale  # ds^T q (TK, D)
        return 0

    jax.lax.fori_loop(0, n_blocks, body, 0)
    dk_ref[0] = dk_s[...].astype(dk_ref.dtype)
    dv_ref[0] = dv_s[...].astype(dv_ref.dtype)


def _pallas_attention_bwd(q, k, v, q_pos, k_pos, lse, g, scale, causal,
                          interpret):
    b, tq, h, d = q.shape
    tk = k.shape[1]
    bh = b * h

    def fold(x):
        return x.transpose(0, 2, 1, 3).reshape(bh, x.shape[1], d)

    qf, kf, vf, gf = fold(q), fold(k), fold(v), fold(g)
    qp = q_pos.astype(jnp.int32).reshape(tq, 1)
    kp = k_pos.astype(jnp.int32).reshape(1, tk)
    tq_blk = min(_Q_BLOCK, tq)

    whole = lambda i: (i, 0, 0)  # noqa: E731
    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_kernel, scale=scale, causal=causal,
                          tq_blk=tq_blk),
        grid=(bh,),
        in_specs=[
            pl.BlockSpec((1, tq, d), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tk, d), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tk, d), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((tq, 1), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tk), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tq, d), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tq, 1), whole, memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, tq, d), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tk, d), whole, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tk, d), whole, memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, tk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, tk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((tk, d), jnp.float32),
            pltpu.VMEM((tk, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, qp, kp, gf, lse)

    def unfold(x, t):
        return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)

    return unfold(dq, tq), unfold(dk, tk), unfold(dv, tk)


def _xla_bwd(q, k, v, q_pos, k_pos, scale, causal, g):
    """Composed-XLA VJP (recompute p from inputs): dv = p^T g;
    ds = p * (dp - rowsum(dp*p)), dp = g v^T; dq = ds k * scale;
    dk = ds^T q * scale.  Fallback when the Pallas bwd's VMEM/blocking
    premises don't hold."""
    s = block_scores(q, k, scale)
    if causal:
        s = jnp.where(causal_mask(q_pos, k_pos)[None, None], s, _MASK_NEG)
    p = jax.nn.softmax(s, axis=-1)                       # fp32
    g32 = g.astype(jnp.float32)
    dv = jnp.einsum("bhqk,bqhd->bkhd", p, g32).astype(v.dtype)
    dp = jnp.einsum("bqhd,bkhd->bhqk", g32, v.astype(jnp.float32))
    ds = p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True))
    dq = (jnp.einsum("bhqk,bkhd->bqhd", ds, k.astype(jnp.float32))
          * scale).astype(q.dtype)
    dk = (jnp.einsum("bhqk,bqhd->bkhd", ds, q.astype(jnp.float32))
          * scale).astype(k.dtype)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _fused(q, k, v, q_pos, k_pos, scale, causal, interpret):
    out, _ = _pallas_attention(q, k, v, q_pos, k_pos, scale, causal,
                               interpret)
    return out


def _fused_fwd(q, k, v, q_pos, k_pos, scale, causal, interpret):
    out, lse = _pallas_attention(q, k, v, q_pos, k_pos, scale, causal,
                                 interpret)
    return out, (q, k, v, q_pos, k_pos, lse)


def _fused_bwd(scale, causal, interpret, res, g):
    q, k, v, q_pos, k_pos, lse = res
    tq = q.shape[1]
    # the fused bwd loops exact q-blocks; ragged tails or oversize
    # VMEM needs take the composed-XLA path instead
    if tq % min(_Q_BLOCK, tq) == 0 and _fits_vmem_bwd(
            tq, k.shape[1], q.shape[-1], q.dtype):
        dq, dk, dv = _pallas_attention_bwd(q, k, v, q_pos, k_pos, lse,
                                           g, scale, causal, interpret)
    else:
        dq, dk, dv = _xla_bwd(q, k, v, q_pos, k_pos, scale, causal, g)
    return dq, dk, dv, None, None


_fused.defvjp(_fused_fwd, _fused_bwd)


def fused_attention(q, k, v, q_pos=None, k_pos=None,
                    causal: bool = False, scale: float | None = None,
                    impl: str | None = None):
    """Softmax attention, fused on TPU.

    q: (B, Tq, H, D); k/v: (B, Tk, H, D); optional global positions
    (Tq,)/(Tk,) for the causal mask (default: local aranges).  Returns
    (B, Tq, H, D) in q.dtype.
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    if q_pos is None:
        q_pos = jnp.arange(q.shape[1])
    if k_pos is None:
        k_pos = jnp.arange(k.shape[1])
    resolved = _resolve_impl(impl, q, k)
    if resolved == "xla":
        return _xla_attention(q, k, v, q_pos, k_pos, scale, causal)
    interpret = jax.default_backend() != "tpu"
    return _fused(q, k, v, q_pos, k_pos, scale, causal, interpret)
