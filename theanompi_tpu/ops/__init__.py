from theanompi_tpu.ops.lrn import lrn

__all__ = ["lrn"]
