from theanompi_tpu.ops.fused_bn import scale_bias_act
from theanompi_tpu.ops.lrn import lrn
from theanompi_tpu.ops.maxpool import maxpool_stem

__all__ = ["lrn", "maxpool_stem", "scale_bias_act"]
