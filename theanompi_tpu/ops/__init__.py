from theanompi_tpu.ops.lrn import lrn
from theanompi_tpu.ops.maxpool import maxpool_stem

__all__ = ["lrn", "maxpool_stem"]
