"""Max-pool front-end: XLA ``reduce_window`` or the Pallas argmax
kernel (ops/maxpool_pallas.py).

Only the ResNet stem geometry (3x3, stride 2, pad 1, NHWC with even
H/W) has a Pallas path — that is the one pool in the flagship model,
and its backward (XLA select-and-scatter) is the account's only
near-zero-FLOP slice with measured bandwidth headroom (0.761 ms/step
at 74% of HBM peak; artifacts/fusion_deepdive.json).  Anything else
routes to XLA.

Default 'xla': unlike ops.lrn, the Pallas win here is PREDICTED from
the account's byte counts (~282 vs ~460 MB for the bwd), not yet
measured on silicon — tools/bench_maxpool.py is queued
(artifacts/queue_r05_exps.json); flip the default only when the chip
agrees.  Env override: ``THEANOMPI_TPU_POOL_IMPL``.
"""

from __future__ import annotations

import os

import jax
from flax import linen as nn


def maxpool_stem(x: jax.Array, impl: str | None = None) -> jax.Array:
    """3x3/stride-2/pad-1 max pool (the ResNet stem pool).

    ``impl``: 'xla' (default; reduce_window + select-and-scatter bwd)
    or 'pallas' (argmax-saving kernel, gather backward).  The
    ``THEANOMPI_TPU_POOL_IMPL`` env var takes precedence over the
    argument so an operator can A/B the kernel on chip without
    editing recipes (the model path always passes its config value,
    which would otherwise shadow the env).
    """
    impl = os.environ.get("THEANOMPI_TPU_POOL_IMPL") or impl or "xla"
    if impl == "pallas":
        from theanompi_tpu.ops.maxpool_pallas import maxpool3x3s2

        return maxpool3x3s2(x)
    if impl != "xla":
        raise ValueError(
            f"unknown pool impl {impl!r} (want 'xla'|'pallas')")
    return nn.max_pool(x, (3, 3), (2, 2), padding=[(1, 1), (1, 1)])
