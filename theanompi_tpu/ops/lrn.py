"""Local Response Normalization (AlexNet-era, across channels).

The reference got LRN from cuDNN via Theano's dnn ops (layer library
``theanompi/models/layers2.py``, SURVEY.md §2.8 — mount empty, no
file:line).  On TPU there is no library kernel to call; two impls:
a composed-XLA form (shift-and-add over the channel axis, fused by
the compiler) and a Pallas VMEM-tiled kernel with an analytic VJP
(ops/lrn_pallas.py), which microbenchmarks ~1.2-1.5x faster fwd+bwd
on the v5e chip and is the TPU default.

y = x / (k + alpha/n * sum_{j in window(n)} x_j^2)^beta
(matching cuDNN/Caffe LRN, where alpha is divided by the window size;
set ``alpha_scaled_by_n=False`` for the raw AlexNet-paper variant that
uses alpha directly).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp


def window_sum(v: jax.Array, n: int, adjoint: bool = False) -> jax.Array:
    """Windowed sum over the last (channel) axis, same-padded — static
    shift-and-add (n is tiny, 3-5, so this beats reduce_window and is
    trivially differentiable).  The single source of truth for the
    window convention, shared by the XLA and Pallas impls: centered
    low for even n (lo=(n-1)//2); ``adjoint=True`` swaps the padding
    (the transpose the Pallas VJP needs; identical for odd n)."""
    lo = (n - 1) // 2
    hi = n - 1 - lo
    if adjoint:
        lo, hi = hi, lo
    c = v.shape[-1]
    pad = [(0, 0)] * (v.ndim - 1) + [(lo, hi)]
    padded = jnp.pad(v, pad)
    win = padded[..., 0:c]
    for d in range(1, n):
        win = win + padded[..., d:d + c]
    return win


_PALLAS_OK: bool | None = None  # lazily probed once per process


def _pallas_available() -> bool:
    """One-time probe: compile+run the Pallas kernel on a tiny input.

    'auto' was validated on v5e only; other TPU generations could hit a
    Mosaic lowering regression that would otherwise surface mid-train.
    A failed probe falls back to the composed-XLA impl (which lowers
    everywhere) and warns once.  Explicit ``impl='pallas'`` skips the
    probe so real errors stay loud.
    """
    global _PALLAS_OK
    if _PALLAS_OK is None:
        try:
            from theanompi_tpu.ops.lrn_pallas import lrn_pallas

            x = jnp.ones((1, 8, 8, 16), jnp.float32)
            jax.block_until_ready(lrn_pallas(x, 5, 2.0, 1e-4, 0.75, True))
            _PALLAS_OK = True
        except Exception as e:  # lowering/compile failure on this backend
            import warnings

            warnings.warn(
                f"Pallas LRN unavailable on this backend ({e!r}); "
                "falling back to the composed-XLA impl. Set "
                "THEANOMPI_TPU_LRN_IMPL=pallas to force (and see the error).")
            _PALLAS_OK = False
    return _PALLAS_OK


def lrn(
    x: jax.Array,
    n: int = 5,
    k: float = 2.0,
    alpha: float = 1e-4,
    beta: float = 0.75,
    *,
    alpha_scaled_by_n: bool = True,
    impl: str | None = None,
) -> jax.Array:
    """Cross-channel LRN for NHWC input.

    ``impl``: 'auto' (default), 'xla' (composed ops, fused by the
    compiler) or 'pallas' (VMEM-tiled kernel with analytic VJP,
    ops/lrn_pallas.py); default from the ``THEANOMPI_TPU_LRN_IMPL``
    env var.  'auto' picks pallas on TPU — measured on the v5e chip
    (tools/bench_lrn.py, batch 64): fwd+bwd 4.35→2.94 ms at
    (55,55,96) and 2.41→1.96 ms at (27,27,256) vs the composed form —
    and xla elsewhere (interpret-mode pallas is test-only).
    """
    if x.ndim != 4:
        raise ValueError(f"lrn expects NHWC, got shape {x.shape}")
    impl = impl or os.environ.get("THEANOMPI_TPU_LRN_IMPL", "auto")
    if impl == "auto":
        impl = ("pallas" if jax.default_backend() == "tpu"
                and _pallas_available() else "xla")
    if impl == "pallas":
        from theanompi_tpu.ops.lrn_pallas import lrn_pallas

        return lrn_pallas(x, n, k, alpha, beta, alpha_scaled_by_n)
    if impl != "xla":
        raise ValueError(f"unknown lrn impl {impl!r} (want 'xla'|'pallas')")
    a = alpha / n if alpha_scaled_by_n else alpha
    return x * (k + a * window_sum(x * x, n)) ** (-beta)
