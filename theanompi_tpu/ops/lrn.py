"""Local Response Normalization (AlexNet-era, across channels).

The reference got LRN from cuDNN via Theano's dnn ops (layer library
``theanompi/models/layers2.py``, SURVEY.md §2.8 — mount empty, no
file:line).  On TPU there is no library kernel to call; this composes
XLA ops — ``reduce_window`` over the channel axis — which XLA fuses
into the surrounding elementwise work.  Benchmarked as a tiny fraction
of AlexNet step time, so a Pallas kernel is not warranted (SURVEY.md
§2.12 note: Pallas only if profiling demands).

y = x / (k + alpha/n * sum_{j in window(n)} x_j^2)^beta
(matching cuDNN/Caffe LRN, where alpha is divided by the window size;
set ``alpha_scaled_by_n=False`` for the raw AlexNet-paper variant that
uses alpha directly).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def lrn(
    x: jax.Array,
    n: int = 5,
    k: float = 2.0,
    alpha: float = 1e-4,
    beta: float = 0.75,
    *,
    alpha_scaled_by_n: bool = True,
) -> jax.Array:
    """Cross-channel LRN for NHWC input."""
    if x.ndim != 4:
        raise ValueError(f"lrn expects NHWC, got shape {x.shape}")
    sq = x * x
    # windowed sum over channel dim, same-padded.  n is tiny (3-5), so a
    # sum of n shifted slices beats reduce_window (and is trivially
    # differentiable); XLA fuses it into the surrounding elementwise ops.
    lo = (n - 1) // 2
    hi = n - 1 - lo
    c = x.shape[-1]
    padded = jnp.pad(sq, ((0, 0), (0, 0), (0, 0), (lo, hi)))
    win = padded[..., 0:c]
    for d in range(1, n):
        win = win + padded[..., d:d + c]
    a = alpha / n if alpha_scaled_by_n else alpha
    return x * (k + a * win) ** (-beta)
