"""Launchers — ``tmlocal`` (single host) and ``tmlauncher`` (multi-host).

Parity surface of the reference's console entry points (SURVEY.md
§2.1 — mount empty, no file:line): ``tmlauncher <rule> ...`` composed
an ``mpirun`` command with one rank per GPU; ``tmlocal`` was the
single-node variant.

TPU-native inversion (deliberate divergence, SURVEY.md §7.6): there is
no process-per-device.  ``tmlocal`` runs the rule in-process over all
(or the requested) local chips — BSP is one SPMD program, async rules
are worker threads.  ``tmlauncher`` is the multi-host form: run the
SAME command on every host with ``--coordinator host:port --nhosts N
--host-id i``; it calls ``jax.distributed.initialize`` so the hosts
form one global mesh over DCN, then runs the rule across
``jax.devices()`` (one process per HOST, not per chip).

Usage (matches the reference's shape):
    tmlocal BSP -D 8 -m theanompi_tpu.models.cifar10 -c Cifar10_model
    tmlauncher BSP --coordinator host0:1234 --nhosts 2 --host-id 0 \
        -m theanompi_tpu.models.resnet50 -c ResNet50
"""

from __future__ import annotations

import argparse
import sys

from theanompi_tpu.models import MODEL_ZOO

#: SERVE is the inference mode (theanompi_tpu/serving, docs/SERVING.md)
#: — same entry point so one operator surface covers train AND serve
RULES = ("BSP", "EASGD", "ASGD", "GOSGD", "SERVE")


def _build_parser(multihost: bool) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tmlauncher" if multihost else "tmlocal",
        description=__doc__.split("\n")[0],
    )
    p.add_argument("rule", choices=RULES, help="parallel training rule")
    p.add_argument("-m", "--modelfile",
                   default="theanompi_tpu.models.cifar10",
                   help="model module path, or a zoo shortname "
                        f"({', '.join(MODEL_ZOO)})")
    p.add_argument("-c", "--modelclass", default=None,
                   help="model class name (inferred for zoo shortnames)")
    p.add_argument("-D", "--devices", type=int, default=None,
                   help="number of local devices (default: all)")
    p.add_argument("--epochs", type=int, default=None,
                   help="cap the number of epochs (for smoke runs)")
    p.add_argument("--resume", action="store_true")
    p.add_argument("--sync-type", default="avg", choices=("avg", "cdd"))
    p.add_argument("--model-parallel", type=int, default=1,
                   help="BSP: tensor-parallel degree (devices on the "
                        "'model' mesh axis; use with transformer_lm_tp)")
    p.add_argument("--seq-parallel", type=int, default=1,
                   help="BSP: sequence-parallel degree (devices on the "
                        "'seq' axis; ring attention for transformer_lm)")
    p.add_argument("--pipe-parallel", type=int, default=1,
                   help="BSP: pipeline-parallel degree (devices on the "
                        "'pipe' axis; use with transformer_lm_pp)")
    p.add_argument("--expert-parallel", type=int, default=1,
                   help="BSP: expert-parallel degree (devices on the "
                        "'expert' axis; use with transformer_lm_moe)")
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--lr", type=float, default=None)
    p.add_argument("--snapshot-dir", default=None)
    p.add_argument("--set", action="append", default=[], metavar="K=V",
                   dest="config_sets",
                   help="override any ModelConfig field, repeatable "
                        "(e.g. --set optimizer=lars --set "
                        "warmup_epochs=5 --set lr_schedule=cosine); "
                        "values are parsed by the field's declared type")
    p.add_argument("--tau", type=int, default=10, help="EASGD sync period")
    p.add_argument("--alpha", type=float, default=0.5,
                   help="EASGD elastic coefficient")
    p.add_argument("--p-push", type=float, default=0.1,
                   help="GOSGD per-iteration push probability")
    p.add_argument("--merge-momentum", default="scale",
                   choices=("scale", "keep"),
                   help="GOSGD: scale momentum by the receiver's share "
                        "on each merge (default — prevents the measured "
                        "stale-momentum divergence over slow links, see "
                        "docs/SCALING.md) or keep it untouched")
    p.add_argument("--server-addr", default=None,
                   help="host:port of a tmserver parameter service — runs "
                        "the async rule's server over DCN instead of "
                        "in-process (parallel/service.py).  A "
                        "comma-separated list names a SHARD FLEET: the "
                        "center is leaf-range-partitioned across the "
                        "listed shard services (parallel/shards.py; "
                        "EASGD/ASGD only)")
    p.add_argument("--shards", type=int, default=None, metavar="K",
                   help="EASGD/ASGD, single-host: spawn and supervise K "
                        "shard service processes and partition the "
                        "center across them (docs/DESIGN.md 'Sharded "
                        "parameter service').  A crashed shard is "
                        "relaunched (budget --max-restarts, default 1) "
                        "and the workers' per-shard session rejoin "
                        "re-seeds only its leaf range.  Multi-host runs "
                        "point every host at one fleet via a "
                        "comma-separated --server-addr instead")
    p.add_argument("--ingest", default=None, metavar="ADDR[,ADDR...]",
                   help="distributed ingest (theanompi_tpu/ingest, "
                        "docs/DESIGN.md 'Distributed ingest'): pull "
                        "train batches from a standalone reader fleet "
                        "instead of the in-process loader.  ONE "
                        "address names the fleet's coordinator; a "
                        "comma-separated list names the readers "
                        "directly (static fleet, plan derived "
                        "client-side).  The stream is byte-identical "
                        "to the local loader for the same dataset "
                        "seed; exported as THEANOMPI_TPU_INGEST so "
                        "every epoch's loader (and any subprocess) "
                        "picks it up.  Start a fleet with tmingest or "
                        "python -m theanompi_tpu.ingest.fleet")
    p.add_argument("--overlap-exchange", action="store_true",
                   help="EASGD/ASGD: run each worker's parameter "
                        "exchange on a dedicated thread so compute "
                        "overlaps the RPC (bounded staleness 1; "
                        "docs/DESIGN.md 'Overlapped exchange')")
    p.add_argument("--local-aggregation", action="store_true",
                   help="EASGD/ASGD: aggregate this host's worker "
                        "exchanges in-process so N local workers cost "
                        "ONE wire exchange per shard per period — ASGD "
                        "delta-sums the gradient pushes, EASGD "
                        "composes the elastic displacements against "
                        "one center version (docs/DESIGN.md "
                        "'Hierarchical exchange').  Workers fall back "
                        "to direct exchange if the aggregation plane "
                        "goes down; composes with --overlap-exchange "
                        "(the aggregate rides the exchange threads) "
                        "and --shards/--server-addr fleets")
    p.add_argument("--wire-protocol", default=None,
                   choices=("v1", "v2"),
                   help="param-service transport: v2 framed zero-copy "
                        "(default) or v1 pickle (legacy); exported as "
                        "THEANOMPI_TPU_WIRE_PROTOCOL so every client "
                        "this run spawns inherits it")
    p.add_argument("--wire-compression", default=None,
                   choices=("none", "zlib"),
                   help="v2 wire payload compression "
                        "(THEANOMPI_TPU_WIRE_COMPRESSION)")
    p.add_argument("--wire-dtype", default=None, choices=("f32", "bf16"),
                   help="v2 wire dtype: bf16 halves param/grad bytes on "
                        "the wire; f32 accumulation at the service is "
                        "preserved (THEANOMPI_TPU_WIRE_DTYPE)")
    p.add_argument("--n-total-workers", type=int, default=None,
                   help="GOSGD: global worker count when several hosts "
                        "share one --server-addr hub")
    p.add_argument("--rank-offset", type=int, default=0,
                   help="GOSGD: this host's first global worker rank")
    p.add_argument("--session-id", default=None,
                   help="shared id scoping the --server-addr service "
                        "store; hosts of ONE training session must pass "
                        "the same id (default: a fresh uuid per session)")
    p.add_argument("--platform", default=None,
                   help="force a jax platform (e.g. 'cpu' with "
                        "XLA_FLAGS=--xla_force_host_platform_device_count=8 "
                        "for the virtual test mesh)")
    p.add_argument("--result-json", default=None, metavar="PATH",
                   help="write the session result (val metrics + scalar "
                        "rule stats, e.g. GOSGD gossip weights, EASGD "
                        "n_exchanges) as JSON — param trees are omitted")
    # default None: training resolves to 0 (the reference's fail-fast
    # behavior), SERVE to 2 (serving defaults to supervised recovery —
    # serve_main and `python -m ...serving.server` already do; the
    # launcher must not silently disable it)
    p.add_argument("--max-restarts", type=int, default=None, metavar="N",
                   help="resilience (docs/RESILIENCE.md): async rules "
                        "restart a crashed worker thread from the center "
                        "params up to N times (quorum-bounded); under "
                        "tmlocal any rule additionally auto-resumes a "
                        "crashed session from its latest verified "
                        "checkpoint up to N times (requires "
                        "checkpointing, the default).  Session "
                        "auto-resume is single-host only — one host of "
                        "a tmlauncher SPMD program cannot rejoin the "
                        "collectives its peers are mid-flight in. "
                        "0 = the reference's fail-fast behavior.  "
                        "SERVE: per-replica restart-from-export budget "
                        "(docs/SERVING.md)")
    p.add_argument("--fault-plan", default=None, metavar="PATH|JSON",
                   help="activate the deterministic fault-injection "
                        "plane with this plan (a JSON file path or "
                        "inline JSON; docs/RESILIENCE.md); equivalent "
                        "to setting THEANOMPI_TPU_FAULTS — exported so "
                        "subprocesses inherit it")
    p.add_argument("--export-dir", default=None, metavar="DIR",
                   help="SERVE: versioned model-export directory "
                        "(serving/export.py export_model writes it; "
                        "required for the SERVE rule, which watches it "
                        "for new versions to hot-reload)")
    p.add_argument("--port", type=int, default=None,
                   help="SERVE: listen port (default 45900)")
    p.add_argument("--serve-host", default="0.0.0.0",
                   help="SERVE: listen address")
    p.add_argument("--serve-replicas", type=int, default=1,
                   help="SERVE: inference replica count (each with its "
                        "own queue + batcher)")
    p.add_argument("--max-batch", type=int, default=8,
                   help="SERVE: max rows per coalesced batch")
    p.add_argument("--max-delay-ms", type=float, default=5.0,
                   help="SERVE: max wait for a batch to fill before it "
                        "dispatches anyway")
    p.add_argument("--serve-buckets", default=None, metavar="N,N,...",
                   help="SERVE: padded batch sizes (pre-compiled "
                        "shapes; default powers of two up to "
                        "--max-batch)")
    p.add_argument("--max-queue", type=int, default=32,
                   help="SERVE: admission bound — pending requests "
                        "beyond this are rejected with Overloaded "
                        "instead of queued (docs/SERVING.md)")
    p.add_argument("--reload-poll-s", type=float, default=1.0,
                   help="SERVE: export-dir poll interval for hot "
                        "reload (0 disables the watcher)")
    p.add_argument("--decode", action="store_true",
                   help="SERVE: autoregressive decode mode "
                        "(theanompi_tpu/decode, docs/SERVING.md): "
                        "paged KV-cache + continuous batching over a "
                        "TransformerLM export; clients use the "
                        "GENERATE wire op (InferenceClient.generate)")
    p.add_argument("--decode-page-size", type=int, default=16,
                   help="SERVE --decode: tokens per KV-cache page")
    p.add_argument("--decode-pages-per-seq", type=int, default=8,
                   help="SERVE --decode: pages per live sequence — "
                        "page_size x pages_per_seq is the attention "
                        "window; older tokens ring-evict")
    p.add_argument("--decode-max-seqs", type=int, default=8,
                   help="SERVE --decode: max concurrently-decoding "
                        "sequences per replica")
    p.add_argument("--decode-max-pending", type=int, default=32,
                   help="SERVE --decode: admission bound — pending "
                        "prompts beyond this are rejected with "
                        "Overloaded")
    p.add_argument("--decode-prefill-buckets", default=None,
                   metavar="N,N,...",
                   help="SERVE --decode: padded prompt-length buckets "
                        "(default powers of two up to min(512, "
                        "max_len))")
    p.add_argument("--decode-draft-export-dir", default=None,
                   metavar="DIR",
                   help="SERVE --decode: speculative decoding — a "
                        "small decode-capable export proposing tokens "
                        "the target verifies k-at-a-time in one "
                        "bucketed step (docs/SERVING.md 'Speculative "
                        "decode'); dims may differ, vocab must match")
    p.add_argument("--decode-speculate-k", type=int, default=4,
                   help="SERVE --decode: draft tokens per speculative "
                        "round (needs --decode-draft-export-dir)")
    p.add_argument("--decode-no-prefix-cache", action="store_true",
                   help="SERVE --decode: disable the cross-request "
                        "prefix cache (copy-on-write KV page sharing "
                        "is on by default — docs/SERVING.md 'Prefix "
                        "cache')")
    p.add_argument("--decode-prefill-batch", type=int, default=8,
                   help="SERVE --decode: max prompts coalesced into "
                        "ONE batched prefill program call per "
                        "admission round (1 = serial prefill — "
                        "docs/SERVING.md 'Batched prefill')")
    p.add_argument("--decode-prefill-delay-ms", type=float,
                   default=2.0,
                   help="SERVE --decode: how long the oldest pending "
                        "prompt may wait for batch company before its "
                        "prefill launches anyway")
    p.add_argument("--decode-fleet-cache", default=None,
                   metavar="HOST:PORT",
                   help="SERVE --decode: fleet-wide prefix-cache "
                        "authority (a prefill server) consulted on "
                        "local prefix-cache misses — docs/SERVING.md "
                        "'Fleet prefix cache'")
    p.add_argument("--disaggregate", action="store_true",
                   help="SERVE --decode: split the deployment into a "
                        "prefill fleet + decode fleet behind the "
                        "front-door router (theanompi_tpu/frontdoor, "
                        "docs/SERVING.md 'Disaggregated serving'); "
                        "--serve-replicas sizes the decode fleet")
    p.add_argument("--prefill-replicas", type=int, default=1,
                   help="SERVE --disaggregate: initial prefill "
                        "replica count")
    p.add_argument("--autoscale", action="store_true",
                   help="SERVE --disaggregate: grow/shrink both roles "
                        "from load signals (frontdoor/autoscale.py)")
    p.add_argument("--scale-max", type=int, default=4,
                   help="SERVE --disaggregate --autoscale: max "
                        "replicas per role (the fleet budget)")
    p.add_argument("--slo-p99-ms", type=float, default=None,
                   help="SERVE --disaggregate --autoscale: intertoken "
                        "p99 target feeding the decode scale signal")
    p.add_argument("--compilation-cache-dir", default=None, metavar="DIR",
                   help="persistent XLA compilation cache "
                        "(utils/helper_funcs.enable_compilation_cache): "
                        "a repeat run deserializes compiled programs "
                        "instead of paying the measured 39.3 s ResNet-50 "
                        "compile again.  Default: <monitor-dir>/jax_cache "
                        "when --monitor-dir is set, else off; exported "
                        "as THEANOMPI_TPU_COMPILATION_CACHE so "
                        "subprocesses share it")
    p.add_argument("--monitor-dir", default=None, metavar="DIR",
                   help="enable the telemetry subsystem and write its "
                        "artifacts (metrics snapshot JSONL + Prometheus "
                        "dump, per-rank heartbeat, crash postmortem) "
                        "under DIR; equivalent to setting "
                        "THEANOMPI_TPU_MONITOR=DIR "
                        "(docs/OBSERVABILITY.md)")
    p.add_argument("--collector", action="store_true",
                   help="spawn + supervise a telemetry collector for "
                        "this run (monitor/collector.py): every process "
                        "ships span/metric events to ONE merged "
                        "fleet.jsonl under --monitor-dir (required); "
                        "enables distributed tracing "
                        "(THEANOMPI_TPU_TRACE=1, unless already set) "
                        "and exports THEANOMPI_TPU_COLLECTOR so shard/"
                        "reader/serve subprocesses ship too.  Inspect "
                        "with tools/traces.py and tools/tmtop.py "
                        "(docs/OBSERVABILITY.md 'Distributed tracing')")
    if multihost:
        p.add_argument("--coordinator", required=True,
                       help="host:port of host 0 (jax.distributed)")
        p.add_argument("--nhosts", type=int, required=True)
        p.add_argument("--host-id", type=int, required=True)
    return p


def _parse_config_sets(pairs: list[str]) -> dict:
    """``--set k=v`` strings → typed ModelConfig overrides (the typed
    escape hatch so every new config field doesn't need its own flag)."""
    import dataclasses

    from theanompi_tpu.models.base import ModelConfig

    fields = {f.name: f for f in dataclasses.fields(ModelConfig)}
    out: dict = {}
    for pair in pairs:
        key, sep, raw = pair.partition("=")
        if not sep:
            raise SystemExit(f"--set expects K=V, got {pair!r}")
        if key not in fields:
            raise SystemExit(f"--set: unknown ModelConfig field {key!r}; "
                             f"valid: {', '.join(sorted(fields))}")
        default = fields[key].default
        low = raw.lower()
        if low in ("none", "null") and default is None:
            # only nullable fields (declared default None) accept it
            out[key] = None
        elif isinstance(default, bool):
            if low not in ("true", "false", "1", "0"):
                raise SystemExit(f"--set {key}: expected a bool, got {raw!r}")
            out[key] = low in ("true", "1")
        else:
            try:
                if isinstance(default, int):
                    out[key] = int(raw)
                elif isinstance(default, float):
                    out[key] = float(raw)
                elif isinstance(default, tuple):
                    out[key] = tuple(
                        float(x) if "." in x else int(x)
                        for x in raw.split(",") if x != "")
                else:
                    out[key] = raw
            except ValueError:
                raise SystemExit(
                    f"--set {key}: expected a "
                    f"{type(default).__name__}, got {raw!r}") from None
    return out


def _resolve_model(args) -> tuple[str, str]:
    if args.modelfile in MODEL_ZOO:
        mod, cls = MODEL_ZOO[args.modelfile]
        return mod, args.modelclass or cls
    if args.modelclass is None:
        raise SystemExit("--modelclass is required for a custom --modelfile")
    return args.modelfile, args.modelclass


def _run(args, multihost: bool) -> int:
    """Collector seam around the session: the collector must be up
    (and ``THEANOMPI_TPU_COLLECTOR`` exported) BEFORE any monitor
    session activates — the exporter reads the address once at session
    start — and must outlive the session's final flush."""
    collector = None
    if getattr(args, "collector", False):
        if multihost:
            # one collector per RUN, not per host: start it once
            # (python -m theanompi_tpu.monitor.collector) and export
            # THEANOMPI_TPU_COLLECTOR on every host instead
            raise SystemExit(
                "--collector is single-host (tmlocal spawns the "
                "collector process); multi-host runs start one "
                "collector and export THEANOMPI_TPU_COLLECTOR=host:port "
                "on every host")
        if not args.monitor_dir:
            raise SystemExit("--collector requires --monitor-dir (the "
                             "merged fleet.jsonl lands there)")
        import os

        # export before spawning so the collector's own artifacts land
        # under the run dir too
        os.environ["THEANOMPI_TPU_MONITOR"] = args.monitor_dir
        from theanompi_tpu.monitor.collector import CollectorProcess

        collector = CollectorProcess(args.monitor_dir)
        # a collector without tracing still merges fleet metrics, but
        # the flag's point is the one-timeline view — turn tracing on
        # unless the operator pinned it (e.g. =0 to sample metrics only)
        os.environ.setdefault("THEANOMPI_TPU_TRACE", "1")
    try:
        return _run_session(args, multihost)
    finally:
        if collector is not None:
            collector.stop()


def _run_session(args, multihost: bool) -> int:
    if args.monitor_dir:
        # the env var is THE activation channel: the rule session, the
        # recorder, the service clients, and any subprocess this run
        # spawns all read it (theanompi_tpu/monitor)
        import os

        os.environ["THEANOMPI_TPU_MONITOR"] = args.monitor_dir
    for flag, env in (("wire_protocol", "THEANOMPI_TPU_WIRE_PROTOCOL"),
                      ("wire_compression",
                       "THEANOMPI_TPU_WIRE_COMPRESSION"),
                      ("wire_dtype", "THEANOMPI_TPU_WIRE_DTYPE")):
        value = getattr(args, flag, None)
        if value:
            # env is the channel: ServiceClient reads it at connect,
            # and subprocesses this run spawns inherit it
            import os

            os.environ[env] = value
    if args.fault_plan:
        import os

        os.environ["THEANOMPI_TPU_FAULTS"] = args.fault_plan
        # the package may already be imported (env read at import
        # happened before argv parsing) — re-read explicitly
        from theanompi_tpu.resilience import faults

        faults.install_from_env()
    if args.ingest:
        if args.rule == "SERVE":
            raise SystemExit("--ingest feeds TRAINING batches; the "
                             "SERVE rule has no train loader")
        if multihost:
            # a multi-host SPMD program slices each global batch per
            # host locally; silently ignoring the flag would let the
            # user believe the fleet is feeding the run when it is not
            raise SystemExit(
                "--ingest is single-host for now (each host of a "
                "tmlauncher program feeds its own slice); run the "
                "readers co-located with each host instead")
        import os

        from theanompi_tpu.ingest.protocol import ingest_addresses

        try:
            ingest_addresses(args.ingest)  # fail fast on a bad spec
        except ValueError as e:
            raise SystemExit(f"--ingest: {e}") from None
        # env is the channel: models/base.py begin_epoch reads it each
        # epoch, and subprocesses this run spawns inherit it
        os.environ["THEANOMPI_TPU_INGEST"] = args.ingest
    if args.platform:
        import jax

        # must land before the first backend touch; env alone can be
        # overridden by site customizations that pre-register plugins
        jax.config.update("jax_platforms", args.platform)
    cache_dir = args.compilation_cache_dir
    if cache_dir is None and args.monitor_dir:
        # default under the monitor dir: the run's artifacts and its
        # compiled-program cache live (and get cleaned up) together
        import os

        cache_dir = os.path.join(args.monitor_dir, "jax_cache")
    # cache_dir=None still honors an inherited env var (a run_tpu_queue
    # child gets the queue-wide cache without any flag)
    from theanompi_tpu.utils.helper_funcs import enable_compilation_cache

    enable_compilation_cache(cache_dir)
    if args.decode and args.rule != "SERVE":
        # silently ignoring the flag would let the user believe the
        # decode plane is live when it is not
        raise SystemExit("--decode is a SERVE option "
                         "(tmlocal SERVE --decode ...)")
    if args.rule == "SERVE":
        # inference mode (theanompi_tpu/serving): no rule session, no
        # model resolution — the export's metadata names the model
        if multihost:
            raise SystemExit("SERVE is single-host (run one server per "
                             "host behind your load balancer)")
        if not args.export_dir:
            raise SystemExit("SERVE requires --export-dir (see "
                             "serving/export.py export_model)")
        from theanompi_tpu.serving.server import (
            DEFAULT_PORT,
            decode_opts_from_args,
            serve_main,
        )

        buckets = (tuple(int(b) for b in args.serve_buckets.split(","))
                   if args.serve_buckets else None)
        if args.disaggregate:
            if not args.decode:
                # prefill/decode disaggregation only exists on the
                # decode plane — the eval server has no KV pages
                raise SystemExit("--disaggregate requires --decode "
                                 "(tmlocal SERVE --decode "
                                 "--disaggregate ...)")
            from theanompi_tpu.frontdoor import fleet as frontdoor_fleet
            from theanompi_tpu.frontdoor.router import (
                DEFAULT_PORT as ROUTER_PORT,
            )

            pb = (tuple(int(b)
                        for b in args.decode_prefill_buckets.split(","))
                  if args.decode_prefill_buckets else None)
            return frontdoor_fleet.run_foreground(
                export_dir=args.export_dir,
                prefill=args.prefill_replicas,
                decode=args.serve_replicas,
                router_host=args.serve_host,
                router_port=(args.port if args.port is not None
                             else ROUTER_PORT),
                page_size=args.decode_page_size,
                pages_per_seq=args.decode_pages_per_seq,
                max_seqs=args.decode_max_seqs,
                prefill_buckets=pb,
                decode_max_pending=args.decode_max_pending,
                prefix_cache=not args.decode_no_prefix_cache,
                prefill_batch=args.decode_prefill_batch,
                prefill_delay_ms=args.decode_prefill_delay_ms,
                draft_export_dir=args.decode_draft_export_dir,
                speculate_k=args.decode_speculate_k,
                autoscale=args.autoscale, scale_max=args.scale_max,
                slo_p99_ms=args.slo_p99_ms,
                max_restarts=(1 if args.max_restarts is None
                              else args.max_restarts))
        decode_opts = decode_opts_from_args(args)
        return serve_main(
            args.export_dir, host=args.serve_host,
            port=args.port if args.port is not None else DEFAULT_PORT,
            replicas=args.serve_replicas, max_batch=args.max_batch,
            max_delay_ms=args.max_delay_ms, buckets=buckets,
            max_queue=args.max_queue,
            max_restarts=(2 if args.max_restarts is None
                          else args.max_restarts),
            reload_poll_s=args.reload_poll_s,
            decode=args.decode, decode_opts=decode_opts)
    if multihost:
        import jax

        jax.distributed.initialize(
            coordinator_address=args.coordinator,
            num_processes=args.nhosts,
            process_id=args.host_id,
        )

    import theanompi_tpu as tm

    modelfile, modelclass = _resolve_model(args)
    rule_cls = getattr(tm, args.rule)
    rule = rule_cls()

    config = None
    overrides = {k: v for k, v in (("batch_size", args.batch_size),
                                   ("learning_rate", args.lr),
                                   ("snapshot_dir", args.snapshot_dir))
                 if v is not None}
    overrides.update(_parse_config_sets(args.config_sets))
    if overrides:
        from theanompi_tpu.rules import resolve_model_class
        import dataclasses

        cls = resolve_model_class(modelfile, modelclass)
        config = dataclasses.replace(cls.default_config(), **overrides)

    kwargs = dict(devices=args.devices, modelfile=modelfile,
                  modelclass=modelclass, config=config, resume=args.resume,
                  sync_type=args.sync_type, max_epochs=args.epochs)
    if args.rule == "BSP":
        kwargs.update(model_parallel=args.model_parallel,
                      seq_parallel=args.seq_parallel,
                      pipe_parallel=args.pipe_parallel,
                      expert_parallel=args.expert_parallel)
    elif (args.model_parallel > 1 or args.seq_parallel > 1
          or args.pipe_parallel > 1 or args.expert_parallel > 1):
        raise SystemExit("--model-parallel/--seq-parallel/--pipe-parallel/"
                         "--expert-parallel are BSP options (async rules "
                         "are data-parallel per worker)")
    if args.overlap_exchange and args.rule not in ("EASGD", "ASGD"):
        # BSP overlaps via XLA; GOSGD pushes are already fire-and-forget
        # — silently ignoring the flag would let the user believe the
        # exchange is overlapped when it is not
        raise SystemExit("--overlap-exchange applies to EASGD/ASGD only")
    if args.local_aggregation and args.rule not in ("EASGD", "ASGD"):
        # same refusal matrix as --shards: GOSGD ships whole trees to
        # random peers (nothing to delta-sum) and BSP exchanges inside
        # the step program — silently ignoring the flag would let the
        # user believe the wire cost dropped when it did not
        raise SystemExit(
            "--local-aggregation applies to EASGD/ASGD only: GOSGD "
            "gossip pushes whole (params, weight) trees to random "
            "peers and BSP exchanges in-step via XLA collectives "
            "(docs/DESIGN.md 'Hierarchical exchange')")
    shard_group = None
    if args.shards is not None:
        if args.rule not in ("EASGD", "ASGD"):
            raise SystemExit(
                "--shards applies to EASGD/ASGD only: the GOSGD gossip "
                "hub is unsharded (it rendezvouses whole param trees, "
                "not an accumulating center) and BSP has no parameter "
                "service (docs/DESIGN.md 'Sharded parameter service')")
        if multihost:
            raise SystemExit(
                "--shards is single-host (tmlocal spawns the shard "
                "processes); multi-host runs start the fleet once and "
                "point every host at it with a comma-separated "
                "--server-addr")
        if args.server_addr:
            raise SystemExit(
                "pass either --shards K (spawn a local shard fleet) or "
                "a comma-separated --server-addr (an existing fleet), "
                "not both")
        if args.shards < 1:
            raise SystemExit("--shards must be >= 1")
        from theanompi_tpu.parallel.shards import ShardProcessGroup

        shard_group = ShardProcessGroup(
            args.shards,
            max_restarts=(1 if args.max_restarts is None
                          else args.max_restarts))
        args.server_addr = shard_group.server_addr
    if args.rule == "EASGD":
        kwargs.update(tau=args.tau, alpha=args.alpha)
    elif args.rule == "GOSGD":
        kwargs.update(p_push=args.p_push,
                      n_total_workers=args.n_total_workers,
                      rank_offset=args.rank_offset,
                      merge_momentum=args.merge_momentum)
    if args.rule != "BSP":
        if args.server_addr:
            kwargs.update(server_addr=args.server_addr)
            if args.session_id:
                kwargs.update(session_id=args.session_id)
        if args.overlap_exchange:
            kwargs.update(overlap=True)
        if args.local_aggregation:
            kwargs.update(local_aggregation=True)
        if args.max_restarts:
            # worker-thread supervision (resilience.supervisor) — the
            # first line of defense; the session-level auto-resume
            # below catches what it can't
            kwargs.update(max_restarts=args.max_restarts)
    # session-level auto-resume (docs/RESILIENCE.md): a crashed
    # session restarts from its latest VERIFIED checkpoint — corrupt
    # latest falls back to the previous kept epoch (rules' resume
    # paths go through resilience.recovery).  Single-host only: one
    # host of a multi-host SPMD program resuming alone would issue
    # collectives its peers (blocked mid-all-reduce at a different
    # step) can never match — fail fast on every host instead.
    session_restarts = (0 if multihost
                        else (args.max_restarts or 0))
    attempts = 0
    try:
        while True:
            rule.init(**kwargs)
            try:
                result = rule.wait()
                break
            except Exception as e:
                attempts += 1
                if attempts > session_restarts:
                    raise
                import sys as _sys

                if (args.rule == "GOSGD" and args.server_addr
                        and args.session_id):
                    # a pinned-session-id gossip hub survives the crash
                    # WITH its deactivated ranks and stale in-flight
                    # payloads — resuming into it would refuse gossip to
                    # restarted ranks and merge pre-crash params; the
                    # operator must restart every host with a fresh id
                    print("[resilience] NOT auto-resuming GOSGD: the "
                          f"pinned --session-id {args.session_id!r} hub "
                          "keeps deactivated ranks and stale in-flight "
                          "gossip across a resume; restart all hosts "
                          "with a fresh --session-id", file=_sys.stderr,
                          flush=True)
                    raise
                print(f"[resilience] {args.rule} session died "
                      f"({type(e).__name__}: {e}); auto-resume "
                      f"{attempts}/{session_restarts} from the latest "
                      "verified checkpoint", file=_sys.stderr, flush=True)
                from theanompi_tpu import monitor

                monitor.inc("resilience/session_autoresumes_total")
                kwargs.update(resume=True)
                rule = rule_cls()
    finally:
        if shard_group is not None:
            shard_group.stop()
    val = result.get("val", {})
    if val:
        print("final val:", {k: round(float(v), 4) for k, v in val.items()})
    if args.result_json:
        # tmlauncher runs the SAME command on every host: gate like the
        # recorder's JSONL (rules/bsp.py) so N hosts sharing a
        # filesystem don't clobber one path with nondeterministic data
        if multihost:
            import jax

            write = jax.process_index() == 0
        else:
            write = True
        if write:
            import json

            with open(args.result_json, "w") as f:
                json.dump(_jsonable(result), f)
    return 0


def _jsonable(value):
    """Scalar-only view of a rule result: val metrics, counters, gossip
    weights survive; param/center pytrees (device or numpy arrays) are
    dropped — the snapshot dir is the artifact channel for those."""
    import numpy as np

    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    if isinstance(value, dict):
        kept = {k: v for k, v in ((k, _jsonable(v))
                                  for k, v in value.items())
                if v is not None}
        # a param tree filters down to nested empty dicts — drop it
        # entirely rather than emitting structural noise
        return kept or None
    if isinstance(value, (list, tuple)):
        kept = [_jsonable(v) for v in value]
        return kept if all(v is not None for v in kept) else None
    if np.isscalar(value) or (hasattr(value, "shape")
                              and getattr(value, "shape") == ()):
        try:
            return float(value)
        except (TypeError, ValueError):
            return None
    return None


def tmlocal(argv=None) -> int:
    return _run(_build_parser(False).parse_args(argv), multihost=False)


def tmlauncher(argv=None) -> int:
    return _run(_build_parser(True).parse_args(argv), multihost=True)


def main(argv=None) -> int:  # python -m theanompi_tpu.launcher
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "--multihost":
        return tmlauncher(argv[1:])
    return tmlocal(argv)


if __name__ == "__main__":
    sys.exit(main())
