"""Ingest wire protocol — op names + the pure range-assignment math.

Every ingest process (reader, coordinator, trainer client) speaks the
param-service transport (``parallel/service.py serve`` /
``ServiceClient``): HMAC handshake, negotiated wire v2 framing, typed
``err`` replies whose class-name prefix rides the wire (``Overloaded``
here, like ``SessionDisplaced`` there).  This module holds what the
three sides must agree on:

* **ops** — the request vocabulary (constants below);
* **plan math** — :func:`partition_batches` cuts an epoch's batch
  index space ``[0, n_batches)`` into contiguous per-reader ranges, a
  pure function of (n_batches, reader list) so every party derives
  the identical assignment from the same inputs;
* **addresses** — :func:`ingest_addresses` parses the launcher's
  ``--ingest`` / ``THEANOMPI_TPU_INGEST`` value.

Correctness note: range assignment is an I/O-locality and read-ahead
hint, NOT a correctness boundary.  Every reader derives the same epoch
permutation from (seed, epoch) — ``ingest/order.py`` — so ANY reader
serves ANY batch index byte-identically; that is what makes mid-epoch
reassignment after a reader death trivially safe.
"""

from __future__ import annotations

import os
from typing import Sequence

#: probe: who am I talking to?  -> {"kind": "reader"|"coordinator", ...}
OP_INFO = "ingest_info"
#: reader: dataset identity -> dict (compared with the trainer's local
#: ``Dataset.ingest_signature()`` — a mismatch is a hard error)
OP_META = "ingest_meta"
#: reader: (epoch, rank, size, global_batch, index) -> RawArrays(x, y)
OP_BATCH = "ingest_batch"
#: reader: (epoch, rank, size, global_batch, lo, hi) -> "ok"; kicks the
#: background read-ahead of batches [lo, hi) (fadvise + page touch)
OP_ASSIGN = "ingest_assign"
#: coordinator: (epoch, rank, size, global_batch, n_batches) ->
#: {"version": int, "owners": [[lo, hi, addr], ...]}
OP_PLAN = "ingest_plan"
#: coordinator: (addr,) -> {"dead": bool, "version": int} — verify +
#: mark a reader the caller could not reach; bumps the plan version
OP_REPORT_DEAD = "ingest_report_dead"

ENV_VAR = "THEANOMPI_TPU_INGEST"

DEFAULT_COORDINATOR_PORT = 45950
DEFAULT_READER_BASE_PORT = 45951


def partition_batches(n_batches: int, readers: Sequence[str],
                      rotation: int = 0) -> list[tuple[int, int, str]]:
    """Contiguous equal split of ``[0, n_batches)`` over ``readers``:
    range ``i`` goes to reader ``(i + rotation) % len(readers)``.
    Early ranges take the remainder, so sizes differ by at most one.
    Deterministic in (n_batches, readers, rotation) — the coordinator
    and a coordinator-less client derive the same plan.

    ``rotation`` is the trainer's rank: an epoch stream is consumed in
    order, so with T trainers all starting at batch 0, un-rotated
    plans would have every trainer pulling from reader 0's range
    first, then reader 1's — the fleet serving one reader at a time.
    Rotating the reader order per rank spreads the CONCURRENT load
    across the whole fleet while keeping each (trainer, reader) range
    contiguous for read-ahead locality."""
    n, k = int(n_batches), len(readers)
    if n < 0:
        raise ValueError(f"n_batches must be >= 0, got {n}")
    if k < 1:
        raise ValueError("no readers to partition batches over")
    base, rem = divmod(n, k)
    owners: list[tuple[int, int, str]] = []
    lo = 0
    for i in range(k):
        hi = lo + base + (1 if i < rem else 0)
        owners.append((lo, hi, readers[(i + int(rotation)) % k]))
        lo = hi
    return owners


def owner_of(owners: Sequence[Sequence], index: int) -> str:
    """The reader address owning batch ``index`` under ``owners``
    (``partition_batches`` output, or its JSON round-trip)."""
    for lo, hi, addr in owners:
        if lo <= index < hi:
            return addr
    raise IndexError(f"batch {index} is outside every assigned range "
                     f"({[(lo, hi) for lo, hi, _ in owners]})")


def ingest_addresses(value: str | None = None) -> list[str] | None:
    """Parse ``--ingest`` / ``$THEANOMPI_TPU_INGEST``: one coordinator
    address, or a comma-separated static reader fleet.  None when
    unset (the in-process loader path)."""
    raw = value if value is not None else os.environ.get(ENV_VAR)
    if not raw:
        return None
    addrs = [a.strip() for a in raw.split(",") if a.strip()]
    if not addrs:
        raise ValueError(f"no addresses in ingest spec {raw!r}")
    for a in addrs:
        host, _, port = a.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(f"ingest address {a!r} is not host:port")
    return addrs
