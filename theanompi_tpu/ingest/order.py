"""Random-access epoch order over an ImageNet shard tree.

The in-process loader (``data/imagenet.py _file_batches``) streams an
epoch: shard files arrive in the epoch's seeded order, one in-file
permutation is drawn per file from the sequential shuffle stream, and
batches are assembled across file boundaries with carried tails.  A
standalone reader cannot stream — trainers pull *batch index b* from
whichever reader owns it — so this module re-expresses the same epoch
as a random-access pure function:

* the epoch's file order, per-file permutations, and running sample
  offsets are derived once per (epoch, rank, size) from the SAME
  helpers the in-process loader uses (``epoch_file_order`` /
  ``shuffle_rng`` — data/imagenet.py), so both paths compute one
  global permutation from (seed, epoch) with zero coordination;
* batch ``b`` of global size ``B`` is the slice ``[b*B, (b+1)*B)`` of
  the concatenated permuted sample sequence, gathered straight from
  the mmap shard files with one ``np.take`` per contributing shard —
  the r5 single-gather path, byte-identical to the streaming
  assembler's output (pinned by tests/test_ingest.py).

Shard files are opened lazily through ``_load_shard`` (mmap +
``posix_fadvise(WILLNEED)`` + page touch) and cached for the epoch, so
serving a contiguous batch range pages each file in exactly once.
"""

from __future__ import annotations

import bisect
import threading
from typing import Sequence

import numpy as np

from theanompi_tpu.analysis.lockgraph import make_lock
from theanompi_tpu.data.imagenet import (
    _load_shard,
    epoch_file_order,
    shuffle_rng,
)


class EpochOrder:
    """One (epoch, rank, size) view of the shard tree: sharded file
    order, per-file permutations, and random-access batch assembly.

    Construction draws every per-file permutation up front (the
    shuffle stream is sequential, so permutation ``i`` depends on the
    sizes of files ``0..i-1`` — sizes come from the manifest, not from
    opening the files).  ``assemble`` is then pure in (index,
    global_batch) and thread-safe: concurrent pulls share the mmap
    cache under a lock but gather outside it.
    """

    def __init__(self, files: Sequence[str], sizes: dict[str, int],
                 seed: int, epoch: int, rank: int = 0, size: int = 1):
        self.epoch = int(epoch)
        self.rank = int(rank)
        self.size = int(size)
        self.files = epoch_file_order(files, seed, epoch, rank, size)
        rng = shuffle_rng(seed, epoch, rank)
        # one permutation per file, drawn in epoch file order — the
        # exact draws _file_batches makes as readahead yields files
        self.perms = [rng.permutation(int(sizes[f])) for f in self.files]
        # offsets[i] = first global sample position of file i
        self.offsets = np.concatenate(
            ([0], np.cumsum([len(p) for p in self.perms]))).tolist()
        self.n_samples = self.offsets[-1]
        self._lock = make_lock("EpochOrder._lock")
        self._shards: dict[int, tuple] = {}  # guarded_by: self._lock

    def n_batches(self, global_batch: int) -> int:
        """Trailing remainder dropped, exactly like the streaming
        loader (which only yields while a full batch is buffered)."""
        return self.n_samples // int(global_batch)

    def _shard(self, i: int) -> tuple:
        with self._lock:
            cached = self._shards.get(i)
        if cached is not None:
            return cached
        loaded = _load_shard(self.files[i])  # mmap + fadvise + touch
        with self._lock:
            # a concurrent pull may have loaded it too; keep the first
            # so both gathers read one mapping
            return self._shards.setdefault(i, loaded)

    def files_for_batches(self, lo: int, hi: int,
                          global_batch: int) -> list[int]:
        """Indices of the shard files batches ``[lo, hi)`` touch — the
        reader's prefetch walks these in order."""
        if hi <= lo:
            return []
        b = int(global_batch)
        first = bisect.bisect_right(self.offsets, lo * b) - 1
        last = bisect.bisect_left(self.offsets, min(hi * b,
                                                    self.n_samples))
        return list(range(first, min(last, len(self.files))))

    def assemble(self, index: int, global_batch: int
                 ) -> tuple[np.ndarray, np.ndarray]:
        """Batch ``index``: positions ``[index*B, (index+1)*B)`` of the
        permuted sample sequence, one gather per contributing shard."""
        b = int(global_batch)
        if not 0 <= index < self.n_batches(b):
            raise IndexError(
                f"batch {index} out of range for epoch {self.epoch} "
                f"(rank {self.rank}/{self.size}): "
                f"{self.n_batches(b)} batches of {b}")
        start = index * b
        fi = bisect.bisect_right(self.offsets, start) - 1
        xb = None
        parts_y: list[np.ndarray] = []
        need, at, pos = b, 0, start - self.offsets[fi]
        while need:
            x, y = self._shard(fi)
            perm = self.perms[fi]
            take = min(need, len(perm) - pos)
            if take:
                sel = perm[pos:pos + take]
                if xb is None:
                    xb = np.empty((b,) + x.shape[1:], x.dtype)
                np.take(x, sel, axis=0, out=xb[at:at + take])
                parts_y.append(y[sel])
                at += take
                need -= take
            fi += 1
            pos = 0
        yb = parts_y[0] if len(parts_y) == 1 else np.concatenate(parts_y)
        return xb, yb

    def drop_shards(self) -> None:
        """Release the mmap cache (epoch rotation on the reader)."""
        with self._lock:
            self._shards.clear()
