"""Ingest reader — one process of the standalone reader fleet.

A reader owns the mmap shard tree read path for a slice of every
epoch: it derives the epoch permutation purely from (seed, epoch)
(``ingest/order.py``), pre-pages its ASSIGNED batch range in a
background thread (``posix_fadvise(WILLNEED)`` + page touch — the r5
cold-read fix), and serves ``ingest_batch`` pulls by gathering rows
straight from the mmaps into a uint8 batch that ships as a raw wire-v2
frame (``wire.RawArrays``: zero-copy buffers, no zlib attempt, no
re-dtype).  Because the permutation is pure, any reader can serve any
batch index byte-identically — assignment is read-ahead locality, not
correctness — which is what makes the coordinator's mid-epoch
reassignment after a reader death safe.

Backpressure (the serving discipline, docs/SERVING.md): concurrent
assemblies are admission-bounded at ``max_inflight``; a pull beyond
that is rejected in O(1) with the typed :class:`Overloaded` the
serving stack already defines — the class name rides the wire's err
prefix, the client backs off and retries.  A reader therefore never
holds more than ``max_inflight`` assembled batches (plus one in-flight
reply per connection), no matter how many trainers lean on it.

Runs behind the param-service wire loop (``parallel/service.py
serve``): HMAC auth via ``THEANOMPI_TPU_SERVICE_KEY``, negotiated v2
framing, typed err replies, faithful shutdown.

Launch:  ``python -m theanompi_tpu.ingest.reader --port 45951 \\
             --data-dir /data/imagenet --seed 0 --reader-id 0``
"""

from __future__ import annotations

import argparse
import os
import threading
import time
from collections import OrderedDict

from theanompi_tpu import monitor
from theanompi_tpu.analysis.lockgraph import make_lock
from theanompi_tpu.data.imagenet import (
    _file_size_map,
    _shard_glob,
    shard_tree_signature,
)
from theanompi_tpu.ingest import protocol
from theanompi_tpu.ingest.order import EpochOrder
from theanompi_tpu.parallel import wire
from theanompi_tpu.resilience import faults
from theanompi_tpu.serving.batcher import Overloaded

#: (epoch, rank, size) orders a reader keeps live.  One entry per
#: TRAINER STREAM per epoch — T trainers need T entries for the
#: current epoch alone, plus the next epoch being pre-paged and slack
#: for a straggler finishing the previous one.  Sized generously (an
#: order is perms + mmap handles, ~KBs/shard): an undersized cache is
#: catastrophic, not merely slow — T+1 streams over a cache of T
#: churns every pull into a full permutation rebuild + mmap reopen
#: (measured: 0.4 ms assemblies become 15 ms).
ORDER_CACHE = int(os.environ.get("THEANOMPI_TPU_INGEST_ORDER_CACHE",
                                 "32"))


def _default_max_inflight() -> int:
    """Admission default — the bounded QUEUE: total batch pulls a
    reader holds (executing + waiting) before it rejects in O(1).
    A memory bound (each admitted pull holds at most one assembled
    batch), sized comfortably above normal concurrent demand
    (trainers x client depth against one reader) because every
    rejection risks stalling a trainer's head-of-line index behind a
    backoff sleep."""
    return int(os.environ.get("THEANOMPI_TPU_INGEST_MAX_INFLIGHT",
                              "32"))


def _default_concurrency() -> int:
    """Dedicated assembly threads per reader.  The gather holds the
    GIL (numpy fancy indexing), so letting every connection's handler
    thread gather its own batch degenerates into the GIL convoy —
    measured on this box, a reader serving 4 pipelined connections
    that way collapses from ~940 to ~220 MB/s.  Funneling ALL gathers
    through one worker keeps exactly one GIL-holding thread while the
    handler threads do only GIL-released socket sends; the default of
    1 is the measured optimum (the gather is serial CPU either way)."""
    return int(os.environ.get("THEANOMPI_TPU_INGEST_CONCURRENCY", "1"))


#: how long an admitted pull waits for its assembly before the reader
#: calls itself wedged and sheds it (assemblies are ~ms; this only
#: trips if something is stuck)
_GATE_TIMEOUT_S = 30.0


class IngestReader:
    """The reader's service object (``serve(service=...)`` dispatch).

    Thread model: the wire loop runs one handler thread per
    connection; ``handle`` is therefore concurrent.  The order cache
    and stats counters live under one lock; batch assembly itself runs
    outside it (the mmap gathers are read-only and the admission
    semaphore bounds their concurrency)."""

    def __init__(self, data_dir: str, seed: int = 0, reader_id: int = 0,
                 max_inflight: int | None = None):
        self.reader_id = int(reader_id)
        self.data_dir = data_dir
        self.seed = int(seed)
        self.files = _shard_glob(data_dir, "train")
        if not self.files:
            raise FileNotFoundError(
                f"no train_* shard files under {data_dir!r} — ingest "
                "readers serve a prepared shard tree "
                "(tools/prepare_imagenet.py)")
        self.sizes = _file_size_map(data_dir, self.files)
        self.meta = shard_tree_signature(self.files, self.sizes,
                                         self.seed)
        self._max_inflight = (max_inflight if max_inflight is not None
                              else _default_max_inflight())
        #: RPC-substrate executor width (parallel/rpc.py): a handler
        #: blocks on its assembly future, so the pool must admit
        #: max_inflight concurrent pulls plus slack — the O(1)
        #: Overloaded rejection needs a worker free to run it
        self.RPC_MAX_WORKERS = self._max_inflight + 4
        #: O(1) admission bound = the bounded queue (class docstring);
        #: a Semaphore is internally synchronized
        self._admission = threading.Semaphore(self._max_inflight)
        #: ALL gathers run on this worker so exactly one thread holds
        #: the GIL for assembly (_default_concurrency) — handler
        #: threads wait on the future (parked, no GIL churn) and then
        #: do only the GIL-released reply send
        from concurrent.futures import ThreadPoolExecutor

        self._assembler = ThreadPoolExecutor(
            max_workers=_default_concurrency(),
            thread_name_prefix=f"ingest-assemble-r{self.reader_id}")
        self._lock = make_lock("IngestReader._lock")
        self._orders: OrderedDict = OrderedDict()  # guarded_by: self._lock
        self._served = 0                           # guarded_by: self._lock
        self._assigned: dict = {}                  # guarded_by: self._lock
        #: serializes assignment replacement end to end (swap, stop
        #: previous, START new) — without it a concurrent ingest_assign
        #: could observe a stored-but-not-yet-started thread and join
        #: it (RuntimeError).  Ordered strictly before self._lock.
        self._assign_serial = make_lock("IngestReader._assign_serial")
        self._prefetch_stop: threading.Event | None = None  # guarded_by: self._lock
        self._prefetch_thread: threading.Thread | None = None  # guarded_by: self._lock

    # -- epoch orders ---------------------------------------------------

    def _order(self, epoch: int, rank: int, size: int) -> EpochOrder:
        key = (int(epoch), int(rank), int(size))
        with self._lock:
            order = self._orders.get(key)
            if order is not None:
                self._orders.move_to_end(key)
                return order
        # construct outside the lock (permutation draws for the whole
        # file list); a racing handler's copy loses via setdefault
        order = EpochOrder(self.files, self.sizes, self.seed, *key)
        with self._lock:
            order = self._orders.setdefault(key, order)
            self._orders.move_to_end(key)
            evicted = []
            while len(self._orders) > ORDER_CACHE:
                _, old = self._orders.popitem(last=False)
                evicted.append(old)
        for old in evicted:
            old.drop_shards()  # release the retired epoch's mmaps
        return order

    # -- ops ------------------------------------------------------------

    def _batch(self, epoch, rank, size, global_batch, index):
        faults.fire("ingest_batch", reader=self.reader_id, epoch=epoch,
                    index=index)
        if not self._admission.acquire(blocking=False):
            monitor.inc("ingest/reader_overloaded_total",
                        reader=self.reader_id)
            raise Overloaded(
                f"reader {self.reader_id}: {self._max_inflight} "
                "assemblies already in flight; rejecting instead of "
                "queueing unboundedly")
        t0 = time.monotonic()
        try:
            order = self._order(epoch, rank, size)
            fut = self._assembler.submit(order.assemble, int(index),
                                         int(global_batch))
            import concurrent.futures

            try:
                x, y = fut.result(timeout=_GATE_TIMEOUT_S)
            except concurrent.futures.TimeoutError:
                fut.cancel()
                monitor.inc("ingest/reader_overloaded_total",
                            reader=self.reader_id)
                raise Overloaded(
                    f"reader {self.reader_id}: assembly not scheduled "
                    f"within {_GATE_TIMEOUT_S}s (wedged gather?)"
                ) from None
        finally:
            self._admission.release()
        with self._lock:
            self._served += 1
        monitor.inc("ingest/reader_batches_total", reader=self.reader_id)
        monitor.observe("ingest/reader_assemble_ms",
                        (time.monotonic() - t0) * 1e3,
                        reader=self.reader_id)
        monitor.progress(phase="ingest")
        return wire.RawArrays(x, y)

    def _assign(self, epoch, rank, size, global_batch, lo, hi):
        """Record the assigned batch range and (re)start the read-ahead
        thread pre-paging its shard files.  A new assignment replaces
        the previous one (epoch rotation / mid-epoch reassignment)."""
        key = (int(epoch), int(rank), int(size))
        order = self._order(*key)
        file_idx = order.files_for_batches(int(lo), int(hi),
                                           int(global_batch))
        stop = threading.Event()
        thread = threading.Thread(
            target=self._prefetch, args=(order, file_idx, stop),
            daemon=True, name=f"ingest-prefetch-r{self.reader_id}")
        with self._assign_serial:
            with self._lock:
                self._assigned[key] = (int(lo), int(hi))
                prev_stop, prev_thread = (self._prefetch_stop,
                                          self._prefetch_thread)
                self._prefetch_stop = stop
                self._prefetch_thread = thread
            if prev_stop is not None:
                prev_stop.set()
            if prev_thread is not None:
                prev_thread.join(timeout=5)
            # started INSIDE the serial section: whoever replaces this
            # assignment next is guaranteed to see a started thread
            thread.start()
        return "ok"

    def _prefetch(self, order: EpochOrder, file_idx: list[int],
                  stop: threading.Event) -> None:
        for i in file_idx:
            if stop.is_set():
                return
            order._shard(i)  # mmap + fadvise(WILLNEED) + page touch
            monitor.inc("ingest/reader_prefetch_files_total",
                        reader=self.reader_id)

    def stop_prefetch(self) -> None:
        """Stop the read-ahead thread (shutdown path; also keeps the
        test suite's thread-leak fence honest)."""
        with self._assign_serial:  # a mid-flight _assign finishes first
            with self._lock:
                stop, thread = self._prefetch_stop, self._prefetch_thread
                self._prefetch_stop = self._prefetch_thread = None
        if stop is not None:
            stop.set()
        if thread is not None:
            thread.join(timeout=5)

    def shutdown(self) -> None:
        """Full teardown: read-ahead thread + the assembly worker."""
        self.stop_prefetch()
        self._assembler.shutdown(wait=True)

    def stats(self) -> dict:
        with self._lock:
            return {"reader": self.reader_id,
                    "served": self._served,
                    "assigned": {f"{k[0]}/{k[1]}/{k[2]}": list(v)
                                 for k, v in self._assigned.items()},
                    "max_inflight": self._max_inflight,
                    "n_files": len(self.files)}

    #: control-plane ops (parallel/rpc.py): meta checks, assignment
    #: pushes, and stats must not queue behind a pool of batch pulls
    #: parked on assembly futures
    RPC_CONTROL_OPS = frozenset({protocol.OP_INFO, protocol.OP_META,
                                 protocol.OP_ASSIGN, "stats"})

    def handle(self, op: str, *args):
        if op == protocol.OP_BATCH:
            return self._batch(*args)
        if op == protocol.OP_INFO:
            return {"kind": "reader", "reader": self.reader_id,
                    "pid": os.getpid()}
        if op == protocol.OP_META:
            return dict(self.meta)
        if op == protocol.OP_ASSIGN:
            return self._assign(*args)
        if op == "stats":
            return self.stats()
        if op == "ping":
            return "pong"
        raise ValueError(f"unknown op {op!r}")


def serve_reader(host: str, port: int, reader: IngestReader,
                 ready_event: threading.Event | None = None,
                 stop_event: threading.Event | None = None,
                 authkey: bytes | None = None) -> None:
    """The param-service wire loop over an :class:`IngestReader`."""
    from theanompi_tpu.parallel.service import serve

    try:
        serve(host, port, ready_event=ready_event, stop_event=stop_event,
              authkey=authkey, service=reader)
    finally:
        reader.shutdown()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="theanompi-tpu ingest reader — one process of the "
                    "distributed ingest fleet (docs/DESIGN.md "
                    "'Distributed ingest')")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--data-dir", required=True,
                    help="prepared shard tree (train_*.x.npy pairs "
                         "and/or .npz)")
    ap.add_argument("--seed", type=int, default=0,
                    help="MUST equal the trainers' dataset seed — the "
                         "epoch permutation derives from it (the "
                         "client's meta check refuses a mismatch)")
    ap.add_argument("--reader-id", type=int, default=0)
    ap.add_argument("--max-inflight", type=int, default=None,
                    help="admission bound on concurrent batch pulls "
                         "(the bounded queue; default "
                         "$THEANOMPI_TPU_INGEST_MAX_INFLIGHT or 32)")
    args = ap.parse_args(argv)
    # the reader's work is numpy + sockets; jax (imported by the serve
    # loop's module) must never claim an accelerator from a data process
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    reader = IngestReader(args.data_dir, seed=args.seed,
                          reader_id=args.reader_id,
                          max_inflight=args.max_inflight)
    print(f"[ingest] reader {args.reader_id} serving {len(reader.files)} "
          f"shard files from {args.data_dir} on "
          f"{args.host}:{args.port}", flush=True)
    # request-driven progress, no stall watchdog; per-process file
    # suffix so N readers sharing a monitor dir never clobber each other
    with monitor.session(stall_after=float("inf"),
                         name=f"ingest_reader{args.reader_id}_"
                              f"{os.getpid()}"):
        monitor.progress(phase="ingest")
        serve_reader(args.host, args.port, reader)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
