"""RemoteBatchSource — the trainer's view of the reader fleet.

An iterator of host ``(x, y)`` batches that plugs into
``DevicePrefetcher`` exactly where ``Dataset.train_batches`` does
(models/base.py ``begin_epoch``), so the rules switch between local
and distributed ingest on nothing but the launcher's ``--ingest``
flag.  One instance covers one (epoch, rank, size) stream and yields
its batches IN EPOCH ORDER — byte-identical to the in-process loader
for the same seed (pinned by tests/test_ingest.py), because reader and
trainer derive the same permutation from (seed, epoch).

Mechanics:

* **plan** — from the coordinator (``--ingest coord:port``) or derived
  client-side over a static reader list (``--ingest r1:p,r2:p``);
  either way a contiguous batch-range assignment
  (``protocol.partition_batches``, rotated by trainer rank so a
  same-phase trainer fleet loads every reader concurrently).
* **meta check** — every reader's ``ingest_meta`` must equal the local
  dataset's ``ingest_signature()`` (same seed, same shard set); a
  mismatched fleet is a hard construction error, not a silently
  different permutation.
* **pipelined pulls, ONE fetch thread** — up to ``depth`` request
  frames are in flight at once, pipelined on a single connection per
  reader (the serve loop handles one connection's requests in order,
  so replies come back FIFO) and collected with a select-style
  ``multiprocessing.connection.wait`` over all pending connections.
  One thread by design: measured on this box, N recv threads in one
  client process collapse from ~1000 to ~40 pulls/s at N=12 — the
  classic GIL convoy (every IO wake-up pays the 5 ms switch interval
  against whichever thread holds the GIL); a single select loop
  streams at full socket rate.  The in-flight window doubles as the
  trainer-side backpressure: a slow consumer freezes the window,
  which idles the fleet — no queue anywhere grows past ``depth``.
* **one socket per reader peer (default ON)** — ``mux=True`` (opt out
  with env ``THEANOMPI_TPU_INGEST_MUX=0``) rides the RPC substrate's
  stream multiplexing (``parallel/rpc.py``): the meta/probe control
  clients and the pull pipeline to one reader share one authenticated
  socket; against a non-mux server every stream silently falls back
  to its own socket, which is what makes the default safe.
* **overload** — a reader's typed ``Overloaded`` rejection reschedules
  the pull after a short jittered backoff (kept small: a backed-off
  index can be the stream's head-of-line, and everything behind the
  reorder window waits on it).
* **failover** — a connect/transport failure marks the reader dead
  (reported to the coordinator, which verifies before reassigning;
  static mode re-partitions over the survivors), re-queues every
  index that was in flight on that connection, and retries on the new
  owners.  Correct because any reader serves any index identically.
"""

from __future__ import annotations

import heapq
import os
import threading
import time
from collections import deque
from multiprocessing.connection import Client as _MpClient

import numpy as np

from theanompi_tpu import monitor
from theanompi_tpu.analysis.lockgraph import make_condition, make_lock
from theanompi_tpu.ingest import protocol
from theanompi_tpu.ingest.protocol import ingest_addresses  # re-export
from theanompi_tpu.monitor import trace
from theanompi_tpu.parallel import shm, wire
from theanompi_tpu.parallel.rpc import unix_path as _unix_path
from theanompi_tpu.parallel.rpc import wait_readable as _wait_readable
from theanompi_tpu.resilience import faults
from theanompi_tpu.resilience.retry import CONNECTION_ERRORS, RetryPolicy

__all__ = ["RemoteBatchSource", "ingest_addresses"]

#: how many times one batch index may be re-queued (owner failovers +
#: overload retries) before the stream gives up
MAX_RESENDS_PER_BATCH = 64

#: overload backoff: base * 2^k, jittered, capped.  The cap stays
#: small because a backed-off pull can be the stream's HEAD-OF-LINE
#: index — everything behind the reorder window waits on it, so a
#: long sleep here converts one rejection into a whole-stream stall
_BACKOFF_BASE_S = 0.005
_BACKOFF_CAP_S = 0.05


def _default_depth() -> int:
    return int(os.environ.get("THEANOMPI_TPU_INGEST_DEPTH", "8"))


def _control_retry() -> RetryPolicy:
    """Fail-fast policy for control-plane calls (probe, meta, plan,
    report-dead): a dead fleet must answer in seconds, not wait out a
    30 s reconnect ladder."""
    return RetryPolicy(
        max_attempts=int(os.environ.get(
            "THEANOMPI_TPU_INGEST_PULL_RETRIES", "2")),
        base_delay=0.05, max_delay=0.2, multiplier=2.0, jitter=0.5,
        deadline_s=float(os.environ.get(
            "THEANOMPI_TPU_INGEST_PULL_DEADLINE_S", "3")),
        name="ingest_control")


class _ReaderPipe:
    """One pipelined stream to one reader, owned by the fetch thread
    (single-threaded by design — no locking): HMAC connect + the same
    silent wire-v2 negotiation ``ServiceClient`` does, plus a FIFO of
    in-flight (index, t_sent) — the serve loop answers one stream's
    requests in order, so reply k is the FIFO's head.

    ``transport`` (a ``rpc.MuxConnection``) makes the pipe one logical
    stream on a shared socket instead of its own connection — the
    control-plane clients and the pull pipeline to one reader then
    cost one fd between them (``THEANOMPI_TPU_INGEST_MUX``)."""

    def __init__(self, addr: str, transport=None,
                 offer_shm: bool = True):
        from theanompi_tpu.parallel.service import _authkey

        self.addr = addr
        self.wire: wire.WireOptions | None = None
        self.trace = False  # hello grant — batch pulls then carry ctx
        #: the shm lane channel THIS pipe negotiated (None when riding
        #: a mux transport, whose shared channel the transport owns)
        self._own_shm = None
        self.fifo: deque = deque()  # (index, t_sent)
        if transport is not None:
            self.conn, pre = transport.connect_stream()
            if pre is not None:
                self.wire = pre
                self.trace = transport.trace
                return  # negotiation inherited from the transport
        else:
            p = _unix_path(addr)
            if p is not None:
                self.conn = _MpClient(p, authkey=_authkey())
            else:
                host, _, port = addr.rpartition(":")
                self.conn = _MpClient((host or "127.0.0.1", int(port)),
                                      authkey=_authkey())
        if os.environ.get("THEANOMPI_TPU_WIRE_PROTOCOL", "v2") == "v2":
            want = wire.WireOptions.from_env()
            offer = shm.client_offer() if offer_shm else None
            self.conn.send((wire.HELLO_OP,
                            wire.hello_payload(want, shm_offer=offer)))
            status, payload = self.conn.recv()
            if (status == "ok" and isinstance(payload, dict)
                    and payload.get("version") == wire.WIRE_VERSION):
                self._own_shm = shm.client_channel(offer, payload)
                self.wire = wire.WireOptions(
                    compression=payload.get("compression", "none"),
                    dtype=payload.get("dtype", "f32"),
                    allow_pickle=want.allow_pickle,
                    shm=self._own_shm)
                self.trace = bool(payload.get("trace"))

    def send(self, msg) -> None:
        if self.trace:
            ctx = trace.inject()
            if ctx is not None:
                msg = (wire.TRACE_OP, ctx, *msg)
        if self.wire is not None:
            wire.send_msg(self.conn, msg, self.wire)
        else:
            self.conn.send(msg)

    def recv(self):
        if self.wire is not None:
            return wire.recv_msg(self.conn, self.wire)
        return self.conn.recv()

    def close(self) -> None:
        ch, self._own_shm = self._own_shm, None
        if ch is not None:
            ch.close()  # release leases the reader never acked
        try:
            self.conn.close()
        except OSError:
            pass


class RemoteBatchSource:
    """Iterator of host batches for ONE epoch stream (class docstring).

    ``data`` is the trainer's local dataset object — used for the
    byte-identity meta check (``ingest_signature()``), the batch count,
    and to refuse configurations the remote stream cannot reproduce
    (host-side augmentation)."""

    def __init__(self, addresses: list[str], data, epoch: int,
                 global_batch: int, rank: int = 0, size: int = 1,
                 depth: int | None = None, mux: bool | None = None):
        if getattr(data, "device_transform", None) is None:
            raise ValueError(
                "distributed ingest ships raw uint8 store batches; the "
                "dataset must augment on device (augment_on_device="
                "True) for the remote stream to be byte-identical to "
                "the local one (docs/DESIGN.md 'Distributed ingest')")
        sig = data.ingest_signature()  # raises for synthetic datasets
        self.epoch = int(epoch)
        self.rank = int(rank)
        self.size = int(size)
        self.global_batch = int(global_batch)
        self.n_batches = int(data.n_train_batches_for(
            epoch, global_batch, rank, size))
        self.depth = depth if depth is not None else _default_depth()
        if self.depth < 1:
            raise ValueError(f"depth must be >= 1, got {self.depth}")
        #: one multiplexed socket per reader peer (parallel/rpc.py):
        #: the meta/probe control clients and the pull pipeline share
        #: it, and against a non-mux server every stream silently gets
        #: its own socket — so this is safe to leave on either way.
        #: ON by default (THEANOMPI_TPU_INGEST_MUX=0 opts out) since
        #: the bench_rpc --soak byte-identity pins hold under load.
        #: A v1-pinned run keeps dedicated sockets — mux streams are
        #: wire-v2 framed by construction, so honoring the operator's
        #: v1 escape hatch means never negotiating a mux hello
        self._mux = (mux if mux is not None else (
            os.environ.get("THEANOMPI_TPU_INGEST_MUX", "1") == "1"
            and os.environ.get("THEANOMPI_TPU_WIRE_PROTOCOL", "v2")
            != "v1"))
        #: addr -> rpc.MuxConnection; fetch thread + constructor only
        self._transports: dict = {}
        #: offer the shared-memory batch lane to readers; a typed
        #: ShmRefusal flips this off and every later pull goes in-band
        #: (silent, never a stream failure)
        self._shm_on = True

        # consumer-facing state (fetch thread produces, __next__
        # consumes)
        self._lock = make_lock("RemoteBatchSource._lock")
        self._cond = make_condition(self._lock,
                                    "RemoteBatchSource._cond")
        self._next_yield = 0            # guarded_by: self._lock
        self._results: dict = {}        # guarded_by: self._lock
        self._err: BaseException | None = None  # guarded_by: self._lock
        self._closed = False            # guarded_by: self._lock
        # plan state (fetch thread mutates on failover; the
        # constructor writes it once before the thread starts)
        self._coord = None
        self._readers: list[str] = []   # guarded_by: self._lock
        self._owners: list = []         # guarded_by: self._lock

        self._resolve_fleet(list(addresses), sig)
        self._thread = threading.Thread(
            target=self._fetch_loop, daemon=True,
            name=f"ingest-fetch-r{self.rank}")
        self._thread.start()

    # -- fleet resolution (control plane: plain ServiceClient) ---------

    def _transport(self, addr: str):
        """The shared per-peer mux transport (None when mux is off)."""
        if not self._mux:
            return None
        t = self._transports.get(addr)
        if t is None:
            from theanompi_tpu.parallel.rpc import MuxConnection

            t = self._transports[addr] = MuxConnection(addr)
        return t

    def _drop_transport(self, addr: str) -> None:
        t = self._transports.pop(addr, None)
        if t is not None:
            t.close()

    def _control_client(self, addr: str):
        from theanompi_tpu.parallel.service import ServiceClient

        return ServiceClient(addr, retry=_control_retry(),
                             transport=self._transport(addr))

    def _resolve_fleet(self, addresses: list[str], sig: dict) -> None:
        probe = self._control_client(addresses[0])
        try:
            kind = probe.call(protocol.OP_INFO).get("kind")
        except Exception:
            probe.close()
            raise
        if kind == "coordinator":
            if len(addresses) > 1:
                probe.close()
                raise ValueError(
                    f"{addresses[0]} is a coordinator; pass EITHER one "
                    "coordinator address OR a comma-separated reader "
                    "list, not a mix")
            self._coord = probe
            self._refresh_plan()
        elif kind == "reader":
            probe.close()
            with self._lock:
                self._readers = list(addresses)
                self._owners = protocol.partition_batches(
                    self.n_batches, self._readers, rotation=self.rank)
        else:
            probe.close()
            raise ValueError(
                f"{addresses[0]} answered ingest_info with kind="
                f"{kind!r}; expected a reader or coordinator")
        # byte-identity fence: every reader in the plan must serve the
        # exact (seed, shard set) this trainer's dataset was built on
        with self._lock:
            fleet = sorted({addr for _, _, addr in self._owners})
        for addr in fleet:
            c = self._control_client(addr)
            try:
                meta = c.call(protocol.OP_META)
            finally:
                c.close()
            if meta != sig:
                raise ValueError(
                    f"ingest reader {addr} serves a different dataset "
                    f"than this trainer: reader {meta} vs local {sig} "
                    "— same --data-dir and --seed are required for a "
                    "byte-identical stream")

    def _refresh_plan(self) -> None:
        """(Re)fetch the assignment from the coordinator."""
        plan = self._coord.call(
            protocol.OP_PLAN, self.epoch, self.rank, self.size,
            self.global_batch, self.n_batches)
        with self._lock:
            self._owners = [tuple(o) for o in plan["owners"]]
            self._readers = sorted({a for _, _, a in self._owners})
        monitor.inc("ingest/plan_refreshes_total")

    def _fail_over(self, addr: str) -> None:
        """A pull could not reach ``addr``: drop it from the plan
        (verified via the coordinator when there is one) and
        re-partition over the survivors."""
        monitor.inc("ingest/reader_failovers_total", reader=addr)
        if self._coord is not None:
            self._coord.call(protocol.OP_REPORT_DEAD, addr)
            self._refresh_plan()
            with self._lock:
                survivors = [a for _, _, a in self._owners]
            if addr not in survivors:
                return
            # the coordinator still believes in it (its ping worked);
            # treat the failure as transient and keep the plan
            return
        with self._lock:
            survivors = [a for a in self._readers if a != addr]
            if not survivors:
                raise ConnectionError(
                    f"last ingest reader {addr} is unreachable; no "
                    "survivors to reassign its batch ranges to")
            self._readers = survivors
            self._owners = protocol.partition_batches(
                self.n_batches, survivors, rotation=self.rank)

    # -- the fetch loop (single thread, pipelined, select-driven) ------

    def _fetch_loop(self) -> None:
        pipes: dict[str, _ReaderPipe] = {}
        by_conn: dict = {}
        #: requeued indices awaiting their retry time: (not_before, i).
        #: A retried index was already claimed, so it is ALWAYS inside
        #: the window below — retries can never be starved by fresh
        #: sends (an earlier time-ordered design let later indices
        #: fill the window while a backed-off head-of-line index
        #: waited: permanent deadlock)
        retries: list = []
        resends: dict[int, int] = {}
        backoffs: dict[int, int] = {}
        next_seq = 0  # first never-sent index
        try:
            while True:
                with self._lock:
                    if self._closed or self._err is not None:
                        return
                    if self._next_yield >= self.n_batches:
                        return
                    # the bounded reorder window, by INDEX: everything
                    # outstanding (buffered results, in-flight pulls,
                    # pending retries) lives in [next_yield, window_hi)
                    window_hi = self._next_yield + self.depth
                now = time.monotonic()
                sent_any = False
                while retries and retries[0][0] <= now:
                    _, idx = heapq.heappop(retries)
                    if self._send(idx, pipes, by_conn, retries,
                                  resends):
                        sent_any = True
                while next_seq < min(window_hi, self.n_batches):
                    idx = next_seq
                    next_seq += 1
                    if self._send(idx, pipes, by_conn, retries,
                                  resends):
                        sent_any = True
                busy = [p.conn for p in pipes.values() if p.fifo]
                if not busy:
                    if not retries:
                        # window full of buffered results (or stream
                        # fully sent): wait for the consumer to drain
                        with self._cond:
                            if (self._next_yield < self.n_batches
                                    and not self._closed
                                    and next_seq >= min(
                                        self._next_yield + self.depth,
                                        self.n_batches)):
                                self._cond.wait(0.05)
                        continue
                    # retries pending their backoff window
                    if not sent_any:
                        time.sleep(0.005)
                    continue
                # rpc.wait_readable == multiprocessing.connection.wait
                # for plain sockets, and also understands mux streams
                for conn in _wait_readable(busy, timeout=0.05):
                    pipe = by_conn[conn]
                    self._collect(pipe, pipes, by_conn, retries,
                                  resends, backoffs)
        except BaseException as e:
            with self._cond:
                if self._err is None:
                    self._err = e
                self._cond.notify_all()
        finally:
            for p in pipes.values():
                p.close()

    def _send(self, idx: int, pipes, by_conn, pending,
              resends) -> bool:
        """Issue one pipelined request; False re-queued the index."""
        faults.fire("ingest_pull", index=idx, rank=self.rank)
        with self._lock:
            addr = protocol.owner_of(self._owners, idx)
        try:
            pipe = pipes.get(addr)
            if pipe is None:
                pipe = pipes[addr] = _ReaderPipe(
                    addr, transport=self._transport(addr),
                    offer_shm=self._shm_on)
                by_conn[pipe.conn] = pipe
            if trace.enabled():
                # each pipelined pull roots its own trace at the send
                # (nothing else is open on the fetch thread); the
                # injected context makes the reader's serve span its
                # child.  Gated so the untraced fetch loop is
                # unchanged to the byte.
                with monitor.span("ingest_request", reader=pipe.addr,
                                  index=str(idx)):
                    pipe.send((protocol.OP_BATCH, self.epoch,
                               self.rank, self.size,
                               self.global_batch, idx))
            else:
                pipe.send((protocol.OP_BATCH, self.epoch, self.rank,
                           self.size, self.global_batch, idx))
            pipe.fifo.append((idx, time.monotonic()))
            return True
        except CONNECTION_ERRORS:
            self._drop_pipe(addr, pipes, by_conn, pending, resends,
                            extra=[idx])
            return False

    def _collect(self, pipe: _ReaderPipe, pipes, by_conn, pending,
                 resends, backoffs) -> None:
        """Receive the reply at the head of one pipe's FIFO."""
        idx, t_sent = pipe.fifo[0]
        try:
            with monitor.span("ingest_pull", reader=pipe.addr):
                status, payload = pipe.recv()
        except CONNECTION_ERRORS as e:
            if isinstance(e, wire.ShmRefusal):
                # a reply carried shm content this side must refuse:
                # a LANE failure, not a reader failure — reconnect
                # in-band without failing the reader over
                self._drop_lane(pipe, pipes, by_conn, pending, resends)
                return
            self._drop_pipe(pipe.addr, pipes, by_conn, pending,
                            resends)
            return
        pipe.fifo.popleft()
        if status == "ok":
            x, y = payload
            monitor.observe("ingest/pull_ms",
                            (time.monotonic() - t_sent) * 1e3,
                            reader=pipe.addr)
            backoffs.pop(idx, None)
            with self._cond:
                self._results[idx] = (np.asarray(x), np.asarray(y))
                self._cond.notify_all()
            return
        err = str(payload)
        if wire.ShmRefusal.__name__ in err:
            # the reader refused our frame's shm content (its lane
            # state is gone — restart, swept lease): requeue the pull
            # and retry in-band.  Typed classification, same idiom as
            # Overloaded below.
            pipe.fifo.appendleft((idx, t_sent))
            self._drop_lane(pipe, pipes, by_conn, pending, resends)
            return
        from theanompi_tpu.serving.batcher import Overloaded

        if Overloaded.__name__ in err:
            # typed admission rejection: reschedule after a short
            # jittered backoff — load shedding, not failure
            monitor.inc("ingest/pull_overloaded_total",
                        reader=pipe.addr)
            k = backoffs.get(idx, 0)
            backoffs[idx] = k + 1
            self._requeue(idx, pending, resends, delay=min(
                _BACKOFF_CAP_S, _BACKOFF_BASE_S * (1 << min(k, 5))
            ) * (0.5 + (hash((idx, k)) % 100) / 100))
            return
        from theanompi_tpu.parallel.service import ServiceError

        raise ServiceError(
            f"ingest reader {pipe.addr} rejected batch {idx}: {err}")

    def _drop_lane(self, pipe: _ReaderPipe, pipes, by_conn, pending,
                   resends) -> None:
        """A typed shm refusal: disable the lane for the whole stream,
        drop only this PIPE (the reader itself is healthy — no
        failover) and requeue everything that was in flight on it."""
        self._shm_on = False
        if self._mux:
            t = self._transports.get(pipe.addr)
            if t is not None:
                t.disable_shm()
        pipes.pop(pipe.addr, None)
        by_conn.pop(pipe.conn, None)
        lost = [i for i, _ in pipe.fifo]
        pipe.close()
        for i in lost:
            self._requeue(i, pending, resends, delay=0.0)

    def _drop_pipe(self, addr: str, pipes, by_conn, pending, resends,
                   extra=()) -> None:
        """A connection failed: re-queue everything in flight on it
        and move the plan off the reader."""
        pipe = pipes.pop(addr, None)
        lost = list(extra)
        if pipe is not None:
            by_conn.pop(pipe.conn, None)
            lost += [idx for idx, _ in pipe.fifo]
            pipe.close()
        # a fresh retry must not inherit the dead peer's mux socket
        self._drop_transport(addr)
        self._fail_over(addr)
        for idx in lost:
            self._requeue(idx, pending, resends, delay=0.0)

    def _requeue(self, idx: int, pending, resends,
                 delay: float) -> None:
        n = resends.get(idx, 0) + 1
        resends[idx] = n
        if n > MAX_RESENDS_PER_BATCH:
            raise ConnectionError(
                f"batch {idx} failed after {n} attempts across the "
                "reader fleet")
        heapq.heappush(pending, (time.monotonic() + delay, idx))

    # -- consumer side --------------------------------------------------

    def __iter__(self):
        return self

    def __next__(self):
        with self._cond:
            while True:
                if self._err is not None:
                    err, self._err = self._err, None
                    self._closed = True
                    self._cond.notify_all()
                    raise err
                if self._next_yield >= self.n_batches:
                    raise StopIteration
                batch = self._results.pop(self._next_yield, None)
                if batch is not None:
                    self._next_yield += 1
                    self._cond.notify_all()  # window opens
                    return batch
                self._cond.wait(0.1)

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout=10)
        if self._coord is not None:
            self._coord.close()
        for t in list(self._transports.values()):
            t.close()
        self._transports.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
