"""Ingest fleet supervision — spawn + relaunch the reader processes.

``IngestProcessGroup`` is the ingest analogue of
``parallel/shards.ShardProcessGroup``: K real reader processes (plus,
by default, one coordinator) on free local ports, a watcher thread
that relaunches a dead process on its port within a per-process
restart budget, and the shared ``THEANOMPI_TPU_SERVICE_KEY`` exported
to every child.  A relaunched reader re-derives every epoch order
from (seed, epoch) — there is no state to restore — and the
coordinator's probe loop returns it to the assignment pool; the
trainers' client failover covers the gap in between
(docs/RESILIENCE.md "Reader death").

``python -m theanompi_tpu.ingest.fleet`` (console script ``tmingest``)
runs a fleet in the foreground for operators; benches and tests drive
the class directly.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import threading
import time

from theanompi_tpu import monitor
from theanompi_tpu.analysis.lockgraph import make_lock
from theanompi_tpu.ingest import protocol


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class IngestProcessGroup:
    """Spawn and supervise K reader processes (+ coordinator)."""

    def __init__(self, n_readers: int, data_dir: str, seed: int = 0,
                 host: str = "127.0.0.1", max_restarts: int = 1,
                 coordinator: bool = True,
                 max_inflight: int | None = None,
                 ready_timeout_s: float = 180.0):
        if n_readers < 1:
            raise ValueError(f"n_readers must be >= 1, got {n_readers}")
        from theanompi_tpu.parallel.service import _authkey

        self.host = host
        self.data_dir = data_dir
        self.seed = int(seed)
        self.max_restarts = int(max_restarts)
        self.max_inflight = max_inflight
        _authkey(generate=True)  # ensure + export the shared key
        self._lock = make_lock("IngestProcessGroup._lock")
        self._stopping = threading.Event()
        self._ports: list[int] = [_free_port() for _ in range(n_readers)]
        self._procs: list[subprocess.Popen] = []  # guarded_by: self._lock
        self._restarts: dict[int, int] = {}       # guarded_by: self._lock
        self._coord_port: int | None = None
        self._coord_proc: subprocess.Popen | None = None  # guarded_by: self._lock
        for i, port in enumerate(self._ports):
            self._procs.append(self._spawn_reader(i, port))
        self._wait_ready(ready_timeout_s)
        if coordinator:
            self._coord_port = _free_port()
            with self._lock:
                self._coord_proc = self._spawn_coordinator(
                    self._coord_port)
            self._wait_coordinator(ready_timeout_s)
        self._watcher = threading.Thread(
            target=self._watch, daemon=True, name="ingest-fleet-watcher")
        self._watcher.start()

    # -- addresses ------------------------------------------------------

    @property
    def reader_addresses(self) -> list[str]:
        return [f"{self.host}:{p}" for p in self._ports]

    @property
    def coordinator_address(self) -> str | None:
        return (None if self._coord_port is None
                else f"{self.host}:{self._coord_port}")

    @property
    def ingest_addr(self) -> str:
        """The value trainers pass as ``--ingest``: the coordinator
        when there is one, else the comma-joined static reader list."""
        coord = self.coordinator_address
        return coord if coord else ",".join(self.reader_addresses)

    # -- lifecycle ------------------------------------------------------

    def _spawn_reader(self, index: int, port: int) -> subprocess.Popen:
        cmd = [sys.executable, "-m", "theanompi_tpu.ingest.reader",
               "--host", self.host, "--port", str(port),
               "--data-dir", self.data_dir, "--seed", str(self.seed),
               "--reader-id", str(index)]
        if self.max_inflight is not None:
            cmd += ["--max-inflight", str(self.max_inflight)]
        return subprocess.Popen(cmd, env=dict(os.environ))

    def _spawn_coordinator(self, port: int) -> subprocess.Popen:
        cmd = [sys.executable, "-m", "theanompi_tpu.ingest.coordinator",
               "--host", self.host, "--port", str(port),
               "--readers", ",".join(self.reader_addresses)]
        return subprocess.Popen(cmd, env=dict(os.environ))

    def _probe(self, addr: str) -> dict | None:
        from theanompi_tpu.parallel.service import ServiceClient

        c = None
        try:
            c = ServiceClient(addr)
            info = c.call(protocol.OP_INFO)
            # callers validate kind/index themselves (they need the
            # wrong answer for their diagnostics, not a bare None)
            return info
        except Exception:
            return None
        finally:
            if c is not None:
                c.close()

    def _wait_ready(self, timeout_s: float) -> None:
        deadline = time.monotonic() + timeout_s
        for i, addr in enumerate(self.reader_addresses):
            while True:
                info = self._probe(addr)
                if info is not None:
                    if (info.get("kind") != "reader"
                            or info.get("reader") != i):
                        self.stop()
                        raise RuntimeError(
                            f"address {addr} answered as {info!r}, "
                            f"expected reader {i} — another process "
                            "is listening on that port")
                    break
                with self._lock:
                    rc = self._procs[i].poll()
                if rc is not None:
                    self.stop()
                    raise RuntimeError(
                        f"ingest reader {i} died during startup "
                        f"(rc={rc})")
                if time.monotonic() > deadline:
                    self.stop()
                    raise RuntimeError(
                        f"ingest reader {i} at {addr} never came up "
                        f"within {timeout_s}s")
                time.sleep(0.3)

    def _wait_coordinator(self, timeout_s: float) -> None:
        deadline = time.monotonic() + timeout_s
        addr = self.coordinator_address
        while True:
            info = self._probe(addr)
            if info is not None and info.get("kind") == "coordinator":
                return
            with self._lock:
                rc = self._coord_proc.poll()
            if rc is not None:
                self.stop()
                raise RuntimeError(
                    f"ingest coordinator died during startup (rc={rc})")
            if time.monotonic() > deadline:
                self.stop()
                raise RuntimeError(
                    f"ingest coordinator at {addr} never came up "
                    f"within {timeout_s}s")
            time.sleep(0.3)

    def _watch(self) -> None:
        while not self._stopping.wait(0.5):
            with self._lock:
                procs = list(self._procs)
                coord = self._coord_proc
            for i, proc in enumerate(procs):
                if proc.poll() is None or self._stopping.is_set():
                    continue
                with self._lock:
                    n = self._restarts.get(i, 0)
                    if n >= self.max_restarts:
                        continue  # budget spent: leave the corpse
                    self._restarts[i] = n + 1
                    self._procs[i] = self._spawn_reader(i, self._ports[i])
                print(f"[ingest] reader {i} died (rc={proc.returncode});"
                      f" relaunched on port {self._ports[i]} "
                      f"({n + 1}/{self.max_restarts})",
                      file=sys.stderr, flush=True)
                monitor.inc("ingest/reader_restarts_total", reader=i)
            if (coord is not None and coord.poll() is not None
                    and not self._stopping.is_set()):
                with self._lock:
                    n = self._restarts.get("coord", 0)
                    if n < self.max_restarts:
                        self._restarts["coord"] = n + 1
                        self._coord_proc = self._spawn_coordinator(
                            self._coord_port)
                        print(f"[ingest] coordinator died "
                              f"(rc={coord.returncode}); relaunched "
                              f"({n + 1}/{self.max_restarts})",
                              file=sys.stderr, flush=True)
                        monitor.inc("ingest/coordinator_restarts_total")

    def restart_counts(self) -> dict:
        with self._lock:
            return dict(self._restarts)

    def kill_reader(self, index: int) -> None:
        """Hard-kill one reader (fault-matrix smoke); the watcher
        relaunches it within a poll interval if budget remains."""
        with self._lock:
            self._procs[index].kill()

    def wait_restarted(self, index: int, timeout_s: float = 60.0) -> None:
        deadline = time.monotonic() + timeout_s
        addr = self.reader_addresses[index]
        while True:
            info = self._probe(addr)
            if info is not None and info.get("reader") == index:
                return
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"ingest reader {index} did not come back within "
                    f"{timeout_s}s")
            time.sleep(0.3)

    def stop(self) -> None:
        self._stopping.set()
        if getattr(self, "_watcher", None) is not None \
                and self._watcher.is_alive():
            self._watcher.join(timeout=5)
        with self._lock:
            procs = list(self._procs)
            if self._coord_proc is not None:
                procs.append(self._coord_proc)
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=5)

    def __enter__(self) -> "IngestProcessGroup":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="theanompi-tpu ingest fleet — spawn + supervise N "
                    "reader processes and a coordinator (docs/DESIGN.md"
                    " 'Distributed ingest')")
    ap.add_argument("--readers", type=int, default=2, metavar="N")
    ap.add_argument("--data-dir", required=True)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--max-restarts", type=int, default=1)
    ap.add_argument("--no-coordinator", action="store_true",
                    help="static fleet: trainers get the comma-joined "
                         "reader list and derive the plan client-side")
    args = ap.parse_args(argv)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    group = IngestProcessGroup(
        args.readers, args.data_dir, seed=args.seed, host=args.host,
        max_restarts=args.max_restarts,
        coordinator=not args.no_coordinator)
    print(f"[ingest] fleet up — pass to trainers:  "
          f"--ingest {group.ingest_addr}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        group.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
