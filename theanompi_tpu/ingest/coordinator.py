"""Ingest coordinator — range assignment, epoch boundaries, reader
liveness.

The coordinator is deliberately dataset-agnostic: the epoch
permutation is a pure function of (seed, epoch) that readers and
trainers both derive locally (``ingest/order.py``), so the only global
state worth coordinating is *membership* — which readers are alive —
and the contiguous batch-range assignment derived from it
(``protocol.partition_batches``).  Per epoch it

* answers ``ingest_plan`` with the current assignment (computing it
  once per (epoch, rank, size, batch, n) and pinning it until
  membership changes), and
* **drives the shuffle-epoch boundary**: on a plan's first
  computation it pushes ``ingest_assign`` to every owner so the fleet
  starts pre-paging the new epoch's shard ranges before trainers pull.

Reader death is handled two ways, both converging on a version bump +
recomputed plans over the survivors:

* a **probe thread** pings every reader each ``probe_interval_s`` —
  covers silent deaths and notices a supervised relaunch
  (``ingest/fleet.py``) coming back, returning the reader to the pool
  for subsequent plans;
* ``ingest_report_dead`` — a trainer that hit a connect failure
  reports the address; the coordinator re-verifies (one ping) before
  believing it, so a flaky client cannot evict a healthy reader.

Mid-epoch reassignment is safe because assignment is locality, not
correctness: any reader serves any batch index byte-identically.

Launch:  ``python -m theanompi_tpu.ingest.coordinator --port 45950 \\
             --readers host:45951,host:45952``
"""

from __future__ import annotations

import argparse
import os
import threading
import time

from theanompi_tpu import monitor
from theanompi_tpu.analysis.lockgraph import make_lock
from theanompi_tpu.ingest import protocol

PROBE_INTERVAL_S = 2.0


def _probe_retry():
    """One-shot connect policy for liveness probes and assignment
    pushes: a probe must answer 'dead' in ~a second, not inherit the
    service client's 30 s restart patience."""
    from theanompi_tpu.resilience.retry import RetryPolicy

    return RetryPolicy(max_attempts=1, base_delay=0.05, max_delay=0.1,
                       deadline_s=2.0, name="ingest_probe")


class IngestCoordinator:
    """The coordinator's service object (``serve(service=...)``)."""

    def __init__(self, readers: list[str],
                 probe_interval_s: float = PROBE_INTERVAL_S):
        if not readers:
            raise ValueError("coordinator needs at least one reader "
                             "address (--readers)")
        self._lock = make_lock("IngestCoordinator._lock")
        #: addr -> alive?  (registration order is the assignment order)
        self._readers: dict[str, bool] = {a: True for a in readers}  # guarded_by: self._lock
        self._version = 1              # guarded_by: self._lock
        #: (epoch, rank, size, batch, n) -> (version, owners)
        self._plans: dict = {}         # guarded_by: self._lock
        self._reassignments = 0        # guarded_by: self._lock
        self._probe_interval_s = float(probe_interval_s)
        self._stop = threading.Event()
        self._probe_thread: threading.Thread | None = None

    # -- membership -----------------------------------------------------

    def start_probing(self) -> "IngestCoordinator":
        self._probe_thread = threading.Thread(
            target=self._probe_loop, daemon=True,
            name="ingest-coordinator-probe")
        self._probe_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5)
            self._probe_thread = None

    def _ping(self, addr: str) -> bool:
        from theanompi_tpu.parallel.service import ServiceClient

        c = None
        try:
            c = ServiceClient(addr, retry=_probe_retry())
            return c.call(protocol.OP_INFO).get("kind") == "reader"
        except Exception:
            return False
        finally:
            if c is not None:
                c.close()

    def _probe_loop(self) -> None:
        while not self._stop.wait(self._probe_interval_s):
            with self._lock:
                addrs = list(self._readers)
            flips: dict[str, bool] = {}
            for addr in addrs:
                if self._stop.is_set():
                    return
                flips[addr] = self._ping(addr)
            with self._lock:
                changed = [a for a, ok in flips.items()
                           if self._readers.get(a) not in (None, ok)]
                for a in changed:
                    self._readers[a] = flips[a]
                if changed:
                    self._bump_locked()
            for a in changed:
                print(f"[ingest] coordinator: reader {a} is now "
                      f"{'alive' if flips[a] else 'DEAD'}", flush=True)
                monitor.inc("ingest/reader_liveness_flips_total",
                            alive=flips[a])

    def _bump_locked(self) -> None:  # requires_lock: self._lock
        """Membership changed: invalidate pinned plans."""
        self._version += 1
        self._plans.clear()

    def _alive_locked(self) -> list[str]:  # requires_lock: self._lock
        return [a for a, ok in self._readers.items() if ok]

    # -- ops ------------------------------------------------------------

    def _plan(self, epoch, rank, size, global_batch, n_batches):
        key = (int(epoch), int(rank), int(size), int(global_batch),
               int(n_batches))
        with self._lock:
            cached = self._plans.get(key)
            if cached is not None:
                version, owners = cached
                return {"version": version,
                        "owners": [list(o) for o in owners]}
            alive = self._alive_locked()
            if not alive:
                raise RuntimeError(
                    "no ingest readers alive; cannot assign batch "
                    "ranges (are the reader processes up?)")
            # rotation = the trainer's rank (see partition_batches):
            # concurrent same-phase trainers start on DIFFERENT
            # readers, so the fleet serves in parallel instead of one
            # reader at a time
            owners = protocol.partition_batches(key[4], alive,
                                                rotation=key[1])
            version = self._version
            self._plans[key] = (version, owners)
        # first computation of this plan = the epoch boundary for this
        # (rank, size) stream: push assignments so every owner starts
        # pre-paging its range before the trainer pulls into it
        self._push_assignments(key, owners)
        monitor.inc("ingest/plans_total")
        return {"version": version, "owners": [list(o) for o in owners]}

    def _push_assignments(self, key, owners) -> None:
        from theanompi_tpu.parallel.service import ServiceClient

        epoch, rank, size, global_batch, _ = key
        for lo, hi, addr in owners:
            if lo >= hi:
                continue
            c = None
            try:
                c = ServiceClient(addr, retry=_probe_retry())
                c.call(protocol.OP_ASSIGN, epoch, rank, size,
                       global_batch, lo, hi)
            except Exception:
                # best-effort: a reader that missed its assignment
                # still serves pulls (assignment is read-ahead only);
                # the probe loop will notice if it is actually dead
                pass
            finally:
                if c is not None:
                    c.close()

    def _report_dead(self, addr):
        addr = str(addr)
        with self._lock:
            known = addr in self._readers
        # verify OUTSIDE the lock (a ping takes ~ms); a flaky trainer
        # must not evict a healthy reader
        alive = self._ping(addr) if known else False
        with self._lock:
            if known and not alive and self._readers.get(addr):
                self._readers[addr] = False
                self._bump_locked()
                self._reassignments += 1
                monitor.inc("ingest/reassignments_total")
                print(f"[ingest] coordinator: reader {addr} reported "
                      "dead and confirmed unreachable; reassigning "
                      "its ranges", flush=True)
            return {"dead": not alive, "version": self._version}

    def stats(self) -> dict:
        with self._lock:
            return {"version": self._version,
                    "readers": dict(self._readers),
                    "alive": len(self._alive_locked()),
                    "plans": len(self._plans),
                    "reassignments": self._reassignments}

    def handle(self, op: str, *args):
        if op == protocol.OP_INFO:
            with self._lock:
                return {"kind": "coordinator",
                        "readers": len(self._readers),
                        "pid": os.getpid()}
        if op == protocol.OP_PLAN:
            return self._plan(*args)
        if op == protocol.OP_REPORT_DEAD:
            return self._report_dead(*args)
        if op == "stats":
            return self.stats()
        if op == "ping":
            return "pong"
        raise ValueError(f"unknown op {op!r}")


def serve_coordinator(host: str, port: int,
                      coordinator: IngestCoordinator,
                      ready_event: threading.Event | None = None,
                      stop_event: threading.Event | None = None,
                      authkey: bytes | None = None) -> None:
    from theanompi_tpu.parallel.service import serve

    coordinator.start_probing()
    try:
        serve(host, port, ready_event=ready_event,
              stop_event=stop_event, authkey=authkey,
              service=coordinator)
    finally:
        coordinator.stop()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="theanompi-tpu ingest coordinator — batch-range "
                    "assignment + reader liveness (docs/DESIGN.md "
                    "'Distributed ingest')")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int,
                    default=protocol.DEFAULT_COORDINATOR_PORT)
    ap.add_argument("--readers", required=True,
                    help="comma-separated reader addresses host:port")
    ap.add_argument("--probe-interval-s", type=float,
                    default=PROBE_INTERVAL_S)
    args = ap.parse_args(argv)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    readers = protocol.ingest_addresses(args.readers)
    coord = IngestCoordinator(readers,
                              probe_interval_s=args.probe_interval_s)
    print(f"[ingest] coordinator on {args.host}:{args.port} over "
          f"{len(readers)} reader(s)", flush=True)
    with monitor.session(stall_after=float("inf"),
                         name=f"ingest_coord_{os.getpid()}"):
        monitor.progress(phase="ingest")
        serve_coordinator(args.host, args.port, coord)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
