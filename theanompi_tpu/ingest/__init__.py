"""theanompi_tpu.ingest — distributed ingest service.

A standalone reader fleet that feeds M trainers like one loader
(docs/DESIGN.md "Distributed ingest"): N reader processes own disjoint
batch ranges of the mmap shard tree and stream assembled uint8 batches
to trainers over raw wire-v2 frames; a coordinator assigns ranges,
drives shuffle-epoch boundaries, and reassigns a dead reader's ranges
mid-epoch; a trainer-side :class:`RemoteBatchSource` plugs into
``DevicePrefetcher`` so the rules switch on nothing but the launcher's
``--ingest`` flag.  The remote stream is byte-identical to the
in-process loader for the same seed — reader and trainer derive one
epoch permutation from (seed, epoch) with zero coordination.
"""

from theanompi_tpu.ingest.client import RemoteBatchSource, ingest_addresses
from theanompi_tpu.ingest.coordinator import IngestCoordinator
from theanompi_tpu.ingest.fleet import IngestProcessGroup
from theanompi_tpu.ingest.order import EpochOrder
from theanompi_tpu.ingest.reader import IngestReader

__all__ = [
    "EpochOrder", "IngestCoordinator", "IngestProcessGroup",
    "IngestReader", "RemoteBatchSource", "ingest_addresses",
]
