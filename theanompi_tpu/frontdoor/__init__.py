"""Disaggregated serving front door (docs/SERVING.md).

Three roles over the shared RPC substrate: a PREFILL fleet running
only the prompt-phase programs (``frontdoor/prefill.py``), the
existing decode-mode servers adopting migrated KV pages
(``decode/migrate.py`` + the ``adopt`` op), and a mux-native ROUTER
(``frontdoor/router.py``) splitting each client stream across them —
plus a signal-driven autoscaler (``frontdoor/autoscale.py``) growing
and shrinking each role without dropping a stream.
"""

from theanompi_tpu.frontdoor.autoscale import (
    Autoscaler,
    HysteresisController,
    RoleGroup,
)
from theanompi_tpu.frontdoor.fleet import DisaggregatedFleet
from theanompi_tpu.frontdoor.prefill import PrefillClient, PrefillServer
from theanompi_tpu.frontdoor.router import Router, RouterClient

__all__ = [
    "Autoscaler",
    "DisaggregatedFleet",
    "HysteresisController",
    "PrefillClient",
    "PrefillServer",
    "RoleGroup",
    "Router",
    "RouterClient",
]
