"""Signal-driven autoscaling for the disaggregated serving fleet.

Three layers, separable on purpose:

* :class:`HysteresisController` — the pure decision unit: a normalized
  load signal in, ``+1 / -1 / 0`` out.  Hysteresis (distinct up/down
  thresholds), a consecutive-breach hold (one hot poll never scales),
  and a post-event cooldown (no flapping) — all against an injected
  clock, so the unit tests drive time instead of sleeping through it.
* :class:`RoleGroup` — one role's supervised process group, the
  ``IngestProcessGroup`` pattern: real subprocesses on free local
  ports, a watcher thread that relaunches a dead replica on its port
  within a restart budget, and intentional removals (scale-down)
  excluded from supervision so a drained replica stays dead.
* :class:`Autoscaler` — the loop: polls each role's replicas for the
  signals they already emit (queue depth, page-pool occupancy,
  intertoken p99, overload counts), folds them into one load scalar
  per role, asks the controller, and executes the decision against the
  router's backend set.

Scale events drop nothing, by construction: scale-UP spawns the
replica, waits until it answers, and only then adds it to the router
(new traffic lands on a warm replica); scale-DOWN removes the backend
from the router FIRST (drain — no new streams route to it), waits for
the router to report zero in-flight streams on it, and only then kills
the process (tests/test_frontdoor.py pins both directions).
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time

from theanompi_tpu import monitor
from theanompi_tpu.analysis.lockgraph import make_lock


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class HysteresisController:
    """Pure scale decision: hysteresis + breach hold + cooldown.

    ``decide(load, size)`` returns ``+1`` (grow), ``-1`` (shrink) or
    ``0``.  A decision needs ``hold`` CONSECUTIVE polls breaching the
    same threshold, at least ``cooldown_s`` since the last event, and
    room inside ``[min_size, max_size]``.  Loads between the two
    thresholds reset both breach counters — the dead band is what
    keeps a noisy signal from sawtoothing the fleet."""

    def __init__(self, up: float = 0.8, down: float = 0.2,
                 hold: int = 2, cooldown_s: float = 10.0,
                 min_size: int = 1, max_size: int = 4,
                 clock=time.monotonic):
        if not down < up:
            raise ValueError(f"need down < up, got {down} >= {up}")
        if not 1 <= min_size <= max_size:
            raise ValueError(f"need 1 <= min_size <= max_size, got "
                             f"[{min_size}, {max_size}]")
        self.up = float(up)
        self.down = float(down)
        self.hold = int(hold)
        self.cooldown_s = float(cooldown_s)
        self.min_size = int(min_size)
        self.max_size = int(max_size)
        self._clock = clock
        self._above = 0
        self._below = 0
        self._last_event: float | None = None

    def decide(self, load: float, size: int) -> int:
        load = float(load)
        if load >= self.up:
            self._above += 1
            self._below = 0
        elif load <= self.down:
            self._below += 1
            self._above = 0
        else:
            self._above = self._below = 0
        if (self._last_event is not None
                and self._clock() - self._last_event < self.cooldown_s):
            return 0
        if self._above >= self.hold and size < self.max_size:
            self._above = 0
            self._last_event = self._clock()
            return 1
        if self._below >= self.hold and size > self.min_size:
            self._below = 0
            self._last_event = self._clock()
            return -1
        return 0


class RoleGroup:
    """One role's supervised process group (module docstring).

    ``spawn_argv(port)`` builds the child's argv; every child inherits
    the environment (the shared ``THEANOMPI_TPU_SERVICE_KEY``, monitor
    and collector settings).  ``probe(addr)`` answers whether the
    replica at ``addr`` serves — default: one ``ping`` RPC."""

    def __init__(self, role: str, spawn_argv, initial: int = 1,
                 host: str = "127.0.0.1", max_restarts: int = 1,
                 ready_timeout_s: float = 180.0, probe=None):
        from theanompi_tpu.parallel.service import _authkey

        _authkey(generate=True)  # ensure + export the shared key
        self.role = str(role)
        self.host = host
        self.spawn_argv = spawn_argv
        self.max_restarts = int(max_restarts)
        self.ready_timeout_s = float(ready_timeout_s)
        self._probe_fn = probe or self._rpc_probe
        self._lock = make_lock("frontdoor.RoleGroup._lock")
        self._stopping = threading.Event()
        self._procs: dict[int, subprocess.Popen] = {}  # guarded_by: self._lock
        self._restarts: dict[int, int] = {}            # guarded_by: self._lock
        for _ in range(int(initial)):
            self.grow()
        self._watcher = threading.Thread(
            target=self._watch, daemon=True,
            name=f"frontdoor-{self.role}-watcher")
        self._watcher.start()

    # -- addresses ------------------------------------------------------

    def addresses(self) -> list[str]:
        with self._lock:
            ports = sorted(self._procs)
        return [f"{self.host}:{p}" for p in ports]

    def __len__(self) -> int:
        with self._lock:
            return len(self._procs)

    # -- probing --------------------------------------------------------

    def _rpc_probe(self, addr: str) -> bool:
        from theanompi_tpu.parallel.service import ServiceClient
        from theanompi_tpu.resilience.retry import RetryPolicy

        c = None
        try:
            c = ServiceClient(addr, retry=RetryPolicy(
                max_attempts=1, name="frontdoor-probe"))
            return c.call("ping") == "pong"
        except Exception:
            return False
        finally:
            if c is not None:
                c.close()

    # -- lifecycle ------------------------------------------------------

    def grow(self) -> str:
        """Spawn one replica on a free port, wait until it serves,
        return its address — the caller adds it to the router AFTER
        this returns, so new traffic only ever lands on a warm one."""
        port = _free_port()
        proc = subprocess.Popen(self.spawn_argv(port),
                                env=dict(os.environ))
        addr = f"{self.host}:{port}"
        deadline = time.monotonic() + self.ready_timeout_s
        while not self._probe_fn(addr):
            if proc.poll() is not None:
                raise RuntimeError(
                    f"frontdoor {self.role} replica died during "
                    f"startup (rc={proc.returncode})")
            if time.monotonic() > deadline:
                proc.terminate()
                raise RuntimeError(
                    f"frontdoor {self.role} replica at {addr} never "
                    f"came up within {self.ready_timeout_s}s")
            time.sleep(0.3)
        with self._lock:
            self._procs[port] = proc
        return addr

    def release(self, addr: str) -> None:
        """Kill one DRAINED replica intentionally (scale-down): it
        leaves supervision first, so the watcher does not resurrect
        what the autoscaler just removed."""
        port = int(str(addr).rpartition(":")[2])
        with self._lock:
            proc = self._procs.pop(port, None)
            self._restarts.pop(port, None)
        if proc is None:
            return
        self._stop_proc(proc)

    def kill(self, addr: str) -> None:
        """Hard-kill one replica WITHOUT removing it from supervision
        (fault drills: the watcher relaunches it on its port within
        the restart budget)."""
        port = int(str(addr).rpartition(":")[2])
        with self._lock:
            proc = self._procs.get(port)
        if proc is not None:
            proc.kill()

    def _watch(self) -> None:
        while not self._stopping.wait(0.5):
            with self._lock:
                procs = dict(self._procs)
            for port, proc in procs.items():
                if proc.poll() is None or self._stopping.is_set():
                    continue
                with self._lock:
                    if self._procs.get(port) is not proc:
                        continue  # released/replaced concurrently
                    n = self._restarts.get(port, 0)
                    if n >= self.max_restarts:
                        continue  # budget spent: leave the corpse
                    self._restarts[port] = n + 1
                    self._procs[port] = subprocess.Popen(
                        self.spawn_argv(port), env=dict(os.environ))
                print(f"[frontdoor] {self.role} replica on port {port} "
                      f"died (rc={proc.returncode}); relaunched "
                      f"({n + 1}/{self.max_restarts})",
                      file=sys.stderr, flush=True)
                monitor.inc("frontdoor/replica_restarts_total",
                            role=self.role)

    def restart_counts(self) -> dict:
        with self._lock:
            return dict(self._restarts)

    @staticmethod
    def _stop_proc(proc: subprocess.Popen) -> None:
        if proc.poll() is None:
            proc.terminate()
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=5)

    def stop(self) -> None:
        self._stopping.set()
        if self._watcher.is_alive():
            self._watcher.join(timeout=5)
        with self._lock:
            procs = list(self._procs.values())
            self._procs.clear()
        for p in procs:
            self._stop_proc(p)


class Autoscaler:
    """The loop: poll signals, fold to a load scalar, act.

    The load scalar per role is the MAX over that role's replicas of:

    * queue depth — in-flight prefills / ``max_pending`` (prefill) or
      pending generate requests / ``max_pending`` (decode);
    * page-pool occupancy — ``1 - free_pages / n_pages`` (decode);
    * overload rate — any typed ``Overloaded`` shed since the last
      poll saturates the signal to 1.0 (shedding IS the queue being
      full, whatever the gauges say);
    * intertoken p99 vs. ``slo_p99_ms`` (decode, when an SLO is set).

    MAX, not mean: one saturated replica is a reason to grow even when
    its siblings idle — the router round-robins, so sustained skew
    means the fleet, not the balance, is short."""

    def __init__(self, router, groups: dict, controllers: dict,
                 poll_s: float = 1.0, slo_p99_ms: float | None = None,
                 drain_timeout_s: float = 30.0):
        self.router = router
        self.groups = dict(groups)
        self.controllers = dict(controllers)
        self.poll_s = float(poll_s)
        self.slo_p99_ms = slo_p99_ms
        self.drain_timeout_s = float(drain_timeout_s)
        self._lock = make_lock("frontdoor.Autoscaler._lock")
        self._stopping = threading.Event()
        self._thread: threading.Thread | None = None
        self._clients: dict = {}        # guarded_by: self._lock
        self._last_overloaded: dict = {}  # guarded_by: self._lock
        #: executed scale events [(role, direction, addr)] — the test
        #: and bench evidence surface
        self.events: list = []          # guarded_by: self._lock
        for role, group in self.groups.items():
            monitor.set_gauge("frontdoor/fleet_size", len(group),
                              role=role)

    # -- signal polling -------------------------------------------------

    def _stats(self, addr: str) -> dict | None:
        from theanompi_tpu.parallel.service import ServiceClient
        from theanompi_tpu.resilience.retry import RetryPolicy

        with self._lock:
            client = self._clients.get(addr)
        try:
            if client is None:
                client = ServiceClient(addr, retry=RetryPolicy(
                    max_attempts=1, name="frontdoor-scale-stats"))
                with self._lock:
                    self._clients[addr] = client
            return client.call("stats")
        except Exception:
            with self._lock:
                self._clients.pop(addr, None)
            if client is not None:
                try:
                    client.close()
                except Exception:
                    pass
            return None

    def _overload_delta(self, addr: str, count: int) -> int:
        with self._lock:
            prev = self._last_overloaded.get(addr, count)
            self._last_overloaded[addr] = count
        return max(0, count - prev)

    def _replica_load(self, addr: str, stats: dict) -> float:
        load = 0.0
        if stats.get("role") == "prefill":
            cap = max(1, int(stats.get("max_pending", 1)))
            load = max(load, float(stats.get("inflight", 0)) / cap)
            shed = int(stats.get("overloaded", 0))
        else:
            # a decode-mode tmserver: fold its replicas' signals
            shed = int(stats.get("overloaded", 0))
            for rep in stats.get("replicas", []):
                pend = float(rep.get("pending", 0))
                load = max(load, pend / 8.0)
                free = rep.get("free_pages")
                active = float(rep.get("active", 0))
                if free is not None:
                    total = free + active * 8.0  # pages_per_seq bound
                    if total > 0:
                        load = max(load, 1.0 - free / total)
                p99 = (rep.get("intertoken_ms") or {}).get("p99")
                if self.slo_p99_ms and p99:
                    load = max(load, float(p99) / float(self.slo_p99_ms))
        if self._overload_delta(addr, shed) > 0:
            load = max(load, 1.0)
        return load

    def role_load(self, role: str) -> float:
        load = 0.0
        for addr in self.groups[role].addresses():
            stats = self._stats(addr)
            if stats is None:
                continue  # dead/booting replica: supervision's job
            load = max(load, self._replica_load(addr, stats))
        monitor.set_gauge("frontdoor/role_load", load, role=role)
        return load

    # -- acting ---------------------------------------------------------

    def _scale_up(self, role: str) -> str:
        group = self.groups[role]
        addr = group.grow()
        self.router.add_backend(role, addr)
        with self._lock:
            self.events.append((role, "up", addr))
        monitor.inc("frontdoor/scale_events_total", role=role,
                    direction="up")
        monitor.set_gauge("frontdoor/fleet_size", len(group), role=role)
        print(f"[frontdoor] scale-up {role} -> {len(group)} "
              f"(added {addr})", flush=True)
        return addr

    def _scale_down(self, role: str) -> str | None:
        group = self.groups[role]
        addrs = group.addresses()
        if len(addrs) <= 1:
            return None
        addr = addrs[-1]  # newest replica drains first
        # drain FIRST: the router stops routing new streams to it,
        # in-flight streams finish, and only a zero-stream backend dies
        self.router.remove_backend(role, addr)
        if role == "decode":
            # scale-down page re-migration (docs/SERVING.md): tell the
            # replica to hand its LIVE streams back as pages — each
            # parked generate returns a MigratedStream the router
            # re-adopts on a survivor, so the drain barrier clears at
            # the next step boundary instead of after a full stream.
            # Best effort: on a pre-migration server the RPC fails and
            # in-flight streams simply finish where they are.
            try:
                from theanompi_tpu.resilience.retry import RetryPolicy
                from theanompi_tpu.serving.server import InferenceClient

                c = InferenceClient(addr, retry=RetryPolicy(
                    max_attempts=1, name="frontdoor-drain"))
                try:
                    c.drain_migrate()
                finally:
                    c.close()
            except Exception as e:
                print(f"[frontdoor] scale-down {role} {addr}: drain "
                      f"RPC failed ({type(e).__name__}: {e}); waiting "
                      "for in-flight streams to finish instead",
                      flush=True)
        deadline = time.monotonic() + self.drain_timeout_s
        while self.router.backend_streams(role, addr) > 0:
            if time.monotonic() > deadline:
                print(f"[frontdoor] scale-down {role} {addr}: drain "
                      f"timed out after {self.drain_timeout_s}s; "
                      "killing anyway", flush=True)
                break
            time.sleep(0.05)
        group.release(addr)
        with self._lock:
            self.events.append((role, "down", addr))
            self._clients.pop(addr, None)
            self._last_overloaded.pop(addr, None)
        monitor.inc("frontdoor/scale_events_total", role=role,
                    direction="down")
        monitor.set_gauge("frontdoor/fleet_size", len(group), role=role)
        print(f"[frontdoor] scale-down {role} -> {len(group)} "
              f"(drained {addr})", flush=True)
        return addr

    def tick(self) -> None:
        """One poll → decide → act pass over every role."""
        for role, controller in self.controllers.items():
            decision = controller.decide(self.role_load(role),
                                         len(self.groups[role]))
            if decision > 0:
                self._scale_up(role)
            elif decision < 0:
                self._scale_down(role)

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "Autoscaler":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="frontdoor-autoscaler")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stopping.wait(self.poll_s):
            try:
                self.tick()
            except Exception as e:
                # one bad poll (a replica mid-restart) must not kill
                # the loop; next tick re-reads the world
                print(f"[frontdoor] autoscaler tick failed: "
                      f"{type(e).__name__}: {e}", flush=True)

    def stop(self) -> None:
        self._stopping.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        with self._lock:
            clients = list(self._clients.values())
            self._clients.clear()
        for c in clients:
            try:
                c.close()
            except Exception:
                pass
