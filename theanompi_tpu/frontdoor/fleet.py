"""Disaggregated serving fleet — spawn the roles, run the front door.

:class:`DisaggregatedFleet` is the operator surface (``tmfront``,
``tmlocal SERVE --decode --disaggregate``): it spawns the PREFILL
fleet (``python -m theanompi_tpu.frontdoor.prefill`` per replica) and
the DECODE fleet (``python -m theanompi_tpu.serving.server --decode``
per replica — the same server binary a single-role deployment runs,
now answering the ``adopt`` op) as supervised
:class:`~theanompi_tpu.frontdoor.autoscale.RoleGroup` process groups,
runs the :class:`~theanompi_tpu.frontdoor.router.Router` in-process
behind the shared RPC substrate, and (optionally) starts the
:class:`~theanompi_tpu.frontdoor.autoscale.Autoscaler` over both
roles.

Every child inherits the environment, so the shared
``THEANOMPI_TPU_SERVICE_KEY``, the monitor dir, and a collector
address fan out automatically — one ``tools/traces.py`` invocation
stitches client → router → prefill → decode spans from the collector
file the roles all ship to.

Both role fleets MUST agree on page geometry (page size, pages per
sequence, dtype follows the export): the router ships prefilled pages
verbatim, and a decode replica refuses mismatched pages with the typed
``IncompatiblePages``.  The fleet passes one set of knobs to both
sides so a single deployment cannot disagree with itself.
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
import time

from theanompi_tpu import monitor
from theanompi_tpu.frontdoor import router as router_mod
from theanompi_tpu.frontdoor.autoscale import (
    Autoscaler,
    HysteresisController,
    RoleGroup,
    _free_port,
)
from theanompi_tpu.frontdoor.router import Router


class DisaggregatedFleet:
    """Prefill fleet + decode fleet + in-process router (+ autoscaler)."""

    def __init__(self, export_dir: str, prefill: int = 1,
                 decode: int = 1, host: str = "127.0.0.1",
                 router_host: str = "0.0.0.0",
                 router_port: int | None = None,
                 max_streams: int = 64, failover_attempts: int = 2,
                 page_size: int = 16, pages_per_seq: int = 8,
                 max_seqs: int = 8,
                 prefill_buckets: tuple[int, ...] | None = None,
                 prefill_max_pending: int = 8,
                 decode_max_pending: int = 32,
                 prefix_cache: bool = True,
                 prefill_batch: int = 8,
                 prefill_delay_ms: float = 2.0,
                 fleet_cache: bool = True,
                 draft_export_dir: str | None = None,
                 speculate_k: int = 4, autoscale: bool = False,
                 scale_min: int = 1, scale_max: int = 4,
                 scale_poll_s: float = 1.0,
                 slo_p99_ms: float | None = None,
                 max_restarts: int = 1,
                 ready_timeout_s: float = 180.0):
        self.export_dir = os.path.abspath(export_dir)
        self.host = host
        self.page_size = int(page_size)
        self.pages_per_seq = int(pages_per_seq)
        self.max_seqs = int(max_seqs)
        self.prefill_buckets = prefill_buckets
        self.prefill_max_pending = int(prefill_max_pending)
        self.decode_max_pending = int(decode_max_pending)
        self.prefix_cache = bool(prefix_cache)
        self.prefill_batch = int(prefill_batch)
        self.prefill_delay_ms = float(prefill_delay_ms)
        #: fleet-wide prefix cache (decode/fleetcache.py): the FIRST
        #: prefill replica spawned becomes the authority; every later
        #: replica — prefill peers and decode — points at it.  Best
        #: effort by design: losing the authority degrades to local
        #: misses, never failed admissions.  Needs the local prefix
        #: cache (the authority stores entries in its own PrefixCache).
        self.fleet_cache = bool(fleet_cache) and self.prefix_cache
        self._authority_addr: str | None = None
        self.draft_export_dir = draft_export_dir
        self.speculate_k = int(speculate_k)

        self.prefill_group = RoleGroup(
            "prefill", self._prefill_argv, initial=int(prefill),
            host=host, max_restarts=max_restarts,
            ready_timeout_s=ready_timeout_s)
        try:
            self.decode_group = RoleGroup(
                "decode", self._decode_argv, initial=int(decode),
                host=host, max_restarts=max_restarts,
                ready_timeout_s=ready_timeout_s)
        except BaseException:
            self.prefill_group.stop()
            raise

        self.router = Router(prefill=self.prefill_group.addresses(),
                             decode=self.decode_group.addresses(),
                             max_streams=max_streams,
                             failover_attempts=failover_attempts)
        self.router_host = router_host
        self.router_port = int(router_port or _free_port())
        self._stop_serve = threading.Event()
        ready = threading.Event()
        self._serve_thread = threading.Thread(
            target=router_mod.serve, daemon=True,
            name="frontdoor-router",
            kwargs=dict(router=self.router, host=router_host,
                        port=self.router_port, ready_event=ready,
                        stop_event=self._stop_serve))
        self._serve_thread.start()
        if not ready.wait(timeout=30):
            self.stop()
            raise RuntimeError("frontdoor router never bound its port")

        self.autoscaler: Autoscaler | None = None
        if autoscale:
            groups = {"prefill": self.prefill_group,
                      "decode": self.decode_group}
            controllers = {
                role: HysteresisController(min_size=int(scale_min),
                                           max_size=int(scale_max))
                for role in groups
            }
            self.autoscaler = Autoscaler(
                self.router, groups, controllers,
                poll_s=scale_poll_s, slo_p99_ms=slo_p99_ms).start()

    # -- child argv -----------------------------------------------------

    def _prefill_argv(self, port: int) -> list[str]:
        cmd = [sys.executable, "-m", "theanompi_tpu.frontdoor.prefill",
               "--export-dir", self.export_dir, "--host", self.host,
               "--port", str(port),
               "--page-size", str(self.page_size),
               "--pages-per-seq", str(self.pages_per_seq),
               "--max-seqs", str(self.max_seqs),
               "--max-pending", str(self.prefill_max_pending),
               "--prefill-batch", str(self.prefill_batch),
               "--prefill-delay-ms", str(self.prefill_delay_ms)]
        if self.prefill_buckets:
            cmd += ["--prefill-buckets",
                    ",".join(str(b) for b in self.prefill_buckets)]
        if not self.prefix_cache:
            cmd += ["--no-prefix-cache"]
        if self.fleet_cache:
            if self._authority_addr is None:
                # first prefill replica spawned = the cache authority
                # (serves cache_lookup/register/decref; needs no
                # client of its own)
                self._authority_addr = f"{self.host}:{port}"
            else:
                cmd += ["--fleet-cache", self._authority_addr]
        return cmd

    def _decode_argv(self, port: int) -> list[str]:
        cmd = [sys.executable, "-m", "theanompi_tpu.serving.server",
               "--export-dir", self.export_dir, "--host", self.host,
               "--port", str(port), "--replicas", "1", "--decode",
               "--decode-page-size", str(self.page_size),
               "--decode-pages-per-seq", str(self.pages_per_seq),
               "--decode-max-seqs", str(self.max_seqs),
               "--decode-max-pending", str(self.decode_max_pending),
               "--decode-prefill-batch", str(self.prefill_batch),
               "--decode-prefill-delay-ms",
               str(self.prefill_delay_ms)]
        if self.fleet_cache and self._authority_addr is not None:
            cmd += ["--decode-fleet-cache", self._authority_addr]
        if self.prefill_buckets:
            cmd += ["--decode-prefill-buckets",
                    ",".join(str(b) for b in self.prefill_buckets)]
        if self.draft_export_dir:
            cmd += ["--decode-draft-export-dir", self.draft_export_dir,
                    "--decode-speculate-k", str(self.speculate_k)]
        if not self.prefix_cache:
            cmd += ["--decode-no-prefix-cache"]
        return cmd

    # -- surface --------------------------------------------------------

    @property
    def router_addr(self) -> str:
        host = ("127.0.0.1" if self.router_host == "0.0.0.0"
                else self.router_host)
        return f"{host}:{self.router_port}"

    def stop(self) -> None:
        if self.autoscaler is not None:
            self.autoscaler.stop()
        self._stop_serve.set()
        if self._serve_thread.is_alive():
            self._serve_thread.join(timeout=10)
        self.router.close()
        self.decode_group.stop()
        self.prefill_group.stop()

    def __enter__(self) -> "DisaggregatedFleet":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


def run_foreground(**fleet_kwargs) -> int:
    """Build a fleet and serve until interrupted — the shared body of
    ``tmfront`` and ``tmlocal SERVE --decode --disaggregate``."""
    with monitor.session(stall_after=float("inf"),
                         name=f"router{os.getpid()}"):
        monitor.progress(phase="frontdoor")
        fleet = DisaggregatedFleet(**fleet_kwargs)
        print(f"[frontdoor] fleet up — router at {fleet.router_addr} "
              f"({len(fleet.prefill_group)} prefill / "
              f"{len(fleet.decode_group)} decode, autoscale="
              f"{'on' if fleet.autoscaler is not None else 'off'})",
              flush=True)
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            pass
        finally:
            fleet.stop()
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="theanompi-tpu disaggregated serving fleet: "
                    "prefill replicas + decode replicas + front-door "
                    "router (docs/SERVING.md 'Disaggregated serving')")
    ap.add_argument("--export-dir", required=True)
    ap.add_argument("--prefill", type=int, default=1, metavar="N",
                    help="initial prefill replica count")
    ap.add_argument("--decode", type=int, default=1, metavar="N",
                    help="initial decode replica count")
    ap.add_argument("--host", default="127.0.0.1",
                    help="backend bind/connect host")
    ap.add_argument("--router-host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=router_mod.DEFAULT_PORT,
                    help="router listen port (the client-facing one)")
    ap.add_argument("--max-streams", type=int, default=64)
    ap.add_argument("--failover-attempts", type=int, default=2)
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--pages-per-seq", type=int, default=8)
    ap.add_argument("--max-seqs", type=int, default=8)
    ap.add_argument("--prefill-buckets", default=None, metavar="N,N,...")
    ap.add_argument("--prefill-max-pending", type=int, default=8)
    ap.add_argument("--decode-max-pending", type=int, default=32)
    ap.add_argument("--no-prefix-cache", action="store_true")
    ap.add_argument("--prefill-batch", type=int, default=8,
                    help="max prompts coalesced into ONE batched "
                         "prefill program call, both roles "
                         "(docs/SERVING.md 'Batched prefill'; 1 = "
                         "serial prefill)")
    ap.add_argument("--prefill-delay-ms", type=float, default=2.0,
                    help="oldest-prompt coalescing deadline for "
                         "batched prefill")
    ap.add_argument("--no-fleet-cache", action="store_true",
                    help="disable the fleet-wide prefix cache "
                         "(prefill replica 0 as authority — "
                         "docs/SERVING.md 'Fleet prefix cache')")
    ap.add_argument("--draft-export-dir", default=None, metavar="DIR",
                    help="speculative decoding on the decode fleet")
    ap.add_argument("--speculate-k", type=int, default=4)
    ap.add_argument("--autoscale", action="store_true",
                    help="grow/shrink both roles from load signals "
                         "(frontdoor/autoscale.py)")
    ap.add_argument("--scale-min", type=int, default=1)
    ap.add_argument("--scale-max", type=int, default=4,
                    help="max replicas per role (the fleet budget)")
    ap.add_argument("--scale-poll-s", type=float, default=1.0)
    ap.add_argument("--slo-p99-ms", type=float, default=None,
                    help="intertoken p99 target feeding the decode "
                         "role's scale signal")
    ap.add_argument("--max-restarts", type=int, default=1)
    ap.add_argument("--platform", default=None,
                    help="jax platform for the CHILD processes (e.g. "
                         "'cpu'; exported via JAX_PLATFORMS)")
    args = ap.parse_args(argv)
    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
    buckets = (tuple(int(b) for b in args.prefill_buckets.split(","))
               if args.prefill_buckets else None)
    return run_foreground(
        export_dir=args.export_dir, prefill=args.prefill,
        decode=args.decode, host=args.host,
        router_host=args.router_host, router_port=args.port,
        max_streams=args.max_streams,
        failover_attempts=args.failover_attempts,
        page_size=args.page_size, pages_per_seq=args.pages_per_seq,
        max_seqs=args.max_seqs, prefill_buckets=buckets,
        prefill_max_pending=args.prefill_max_pending,
        decode_max_pending=args.decode_max_pending,
        prefix_cache=not args.no_prefix_cache,
        prefill_batch=args.prefill_batch,
        prefill_delay_ms=args.prefill_delay_ms,
        fleet_cache=not args.no_fleet_cache,
        draft_export_dir=args.draft_export_dir,
        speculate_k=args.speculate_k, autoscale=args.autoscale,
        scale_min=args.scale_min, scale_max=args.scale_max,
        scale_poll_s=args.scale_poll_s, slo_p99_ms=args.slo_p99_ms,
        max_restarts=args.max_restarts)


if __name__ == "__main__":
    raise SystemExit(main())
