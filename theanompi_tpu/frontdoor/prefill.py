"""Prefill fleet — the compute-bound half of disaggregated serving.

A :class:`PrefillServer` owns one :class:`~theanompi_tpu.decode.session
.DecodeSession` and runs ONLY the prompt-phase programs: ``prefill``
(and ``extend`` on a prefix-cache hit).  One ``prefill`` RPC admits the
prompt, reads the first generated token off the prefill logits, exports
the sequence's KV pages as host arrays (ring layout verbatim,
``decode/migrate.py``), releases the pages back to the pool, and ships
``(manifest, RawArrays(k, v))`` — the raw uint8 frame path, because KV
bytes must arrive at the decode fleet EXACTLY as prefilled.

The replica holds NO stream state across requests: pages live on it
only for the duration of one RPC (the prefix cache keeps page-aligned
prefixes hot across prompts, exactly like a decode replica's).  That is
what makes the prefill role trivially scalable — the autoscaler
(``frontdoor/autoscale.py``) can kill any prefill replica between RPCs
without dropping a stream.

Admission is a counter, not a queue: past ``max_pending`` concurrent
prefills the RPC is refused with the typed
:class:`~theanompi_tpu.serving.batcher.Overloaded` in O(1) — the
router treats it as load-shedding and tries the next replica, never a
destructive retry.
"""

from __future__ import annotations

import argparse
import os
import threading
import time

import numpy as np

from theanompi_tpu import monitor
from theanompi_tpu.analysis.lockgraph import make_lock
from theanompi_tpu.decode import migrate
from theanompi_tpu.decode.session import DecodeSession
from theanompi_tpu.parallel import rpc, wire
from theanompi_tpu.parallel.service import ServiceClient, ServiceError
from theanompi_tpu.resilience import faults
from theanompi_tpu.serving.batcher import Overloaded
from theanompi_tpu.serving.export import build_model_from_meta, load_export

#: one above the serving block's 45900
DEFAULT_PORT = 45950


class PrefillServer:
    """One prefill replica: prompt in, (manifest, KV pages) out."""

    def __init__(self, export_dir: str, page_size: int = 16,
                 pages_per_seq: int = 8, max_seqs: int = 8,
                 prefill_buckets: tuple[int, ...] | None = None,
                 max_pending: int = 8, warmup: bool = True,
                 model=None, prefix_cache: bool = True):
        self.export_dir = os.path.abspath(export_dir)
        loaded = load_export(self.export_dir)
        if not loaded.meta.get("decode"):
            raise ValueError(
                "the prefill role needs a decode-capable export "
                "(TransformerLM family; export_meta 'decode' is "
                f"false/absent in {self.export_dir})")
        self.model = (model if model is not None
                      else build_model_from_meta(loaded.meta))
        self.session = DecodeSession(
            self.model, params=loaded.params, version=loaded.version,
            page_size=page_size, pages_per_seq=pages_per_seq,
            max_seqs=max_seqs, prefill_buckets=prefill_buckets,
            prefix_cache=prefix_cache)
        self.max_pending = int(max_pending)
        # the session's host-side state (pool, prefix cache, jit calls)
        # is built for a single scheduler thread; RPC handlers are a
        # pool, so one lock serializes the admit→export→release window
        self._lock = make_lock("PrefillServer._lock")
        self.n_prefills = 0        # guarded_by: self._lock
        self._gate = make_lock("PrefillServer._gate")
        self._inflight = 0         # guarded_by: self._gate
        self.n_shed = 0            # guarded_by: self._gate
        if warmup:
            self.session.warmup()

    # -- request path --------------------------------------------------

    def prefill(self, prompt) -> tuple[dict, wire.RawArrays]:
        """One prompt pass: returns the page manifest and the filled
        pages.  O(1) typed ``Overloaded`` past the admission bound; a
        bad prompt (too long, empty) raises ``ValueError`` — a
        per-request refusal either way, the replica keeps serving."""
        with self._gate:
            if self._inflight >= self.max_pending:
                self.n_shed += 1
                monitor.inc("frontdoor/prefill_shed_total")
                raise Overloaded(
                    f"prefill admission: {self._inflight} in flight "
                    f">= max_pending {self.max_pending}")
            self._inflight += 1
        try:
            faults.fire("page_migrate", side="export")
            prompt = np.asarray(prompt, np.int32).reshape(-1)
            t0 = time.perf_counter()
            with self._lock:
                seq, logits = self.session.admit(prompt)
                first = int(np.argmax(logits))
                k, v = self.session.export_pages(seq)
                manifest = migrate.page_manifest(
                    self.session.cfg, prompt, seq.length, first,
                    version=self.session.version)
                # pages are exported — this replica is done with the
                # stream; only the prefix cache may keep them shared
                self.session.release(seq)
                self.n_prefills += 1
            monitor.inc("frontdoor/prefills_total")
            monitor.observe("frontdoor/prefill_ms",
                            (time.perf_counter() - t0) * 1000.0)
            return manifest, wire.RawArrays(k, v)
        finally:
            with self._gate:
                self._inflight -= 1

    # -- introspection -------------------------------------------------

    def stats(self) -> dict:
        with self._gate:
            inflight, shed = self._inflight, self.n_shed
        with self._lock:
            prefills = self.n_prefills
            pc = self.session.prefix_cache
            hits = (None if pc is None
                    else {"hits": pc.hits, "misses": pc.misses,
                          "entries": len(pc)})
        return {
            "role": "prefill",
            "version": self.session.version,
            "prefills": prefills,
            "inflight": inflight,
            "max_pending": self.max_pending,
            "overloaded": shed,
            "prefix_cache": hits,
            "compiles": dict(self.session.compiles),
        }

    # -- wire dispatch -------------------------------------------------

    def rpc_max_workers(self) -> int:
        # every admissible prefill may block in a handler + slack so
        # O(1) Overloaded refusals never park behind them
        return self.max_pending + 8

    def handle(self, op: str, *args):
        if op == "prefill":
            (prompt,) = args
            return self.prefill(prompt)
        if op == "stats":
            return self.stats()
        if op == "ping":
            return "pong"
        raise ValueError(f"unknown op {op!r}")


def serve(server: PrefillServer, host: str = "0.0.0.0",
          port: int = DEFAULT_PORT,
          ready_event: threading.Event | None = None,
          stop_event: threading.Event | None = None,
          authkey: bytes | None = None,
          loop: str | None = None) -> None:
    """The shared RPC substrate over a :class:`PrefillServer` (same
    HMAC/wire-v2/typed-err stack as every other plane)."""
    from theanompi_tpu.parallel.service import _authkey

    if authkey is None:
        authkey = _authkey(generate=True)
    rpc.serve(server, host, port, ready_event=ready_event,
              stop_event=stop_event, authkey=authkey,
              hooks=rpc.RpcHooks(), loop=loop,
              max_workers=server.rpc_max_workers())


class PrefillClient(ServiceClient):
    """Wire client for the prefill role: ``prefill`` is pure (the
    replica keeps no stream state), so at-least-once transport retries
    are safe; typed ``Overloaded`` re-raises as itself and is never
    retried by the transport — the ROUTER owns what happens next."""

    def prefill(self, prompt) -> tuple[dict, np.ndarray, np.ndarray]:
        try:
            manifest, pages = self.call(
                "prefill", np.asarray(prompt, np.int32))
        except ServiceError as e:
            if Overloaded.__name__ in str(e):
                raise Overloaded(str(e)) from None
            raise
        k, v = pages          # RawArrays decodes to a plain tuple
        return manifest, k, v

    def stats(self) -> dict:
        return self.call("stats")

    def ping(self) -> str:
        return self.call("ping")

    def shutdown(self) -> None:
        self.call("shutdown")


# ---------------------------------------------------------------------------
# Entry point (frontdoor/fleet.py spawns this module per prefill proc)
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="theanompi-tpu prefill replica (disaggregated "
                    "serving, docs/SERVING.md 'Disaggregated serving')")
    ap.add_argument("--export-dir", required=True)
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=DEFAULT_PORT)
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--pages-per-seq", type=int, default=8)
    ap.add_argument("--max-seqs", type=int, default=8)
    ap.add_argument("--prefill-buckets", default=None, metavar="N,N,...")
    ap.add_argument("--max-pending", type=int, default=8)
    ap.add_argument("--no-prefix-cache", action="store_true")
    ap.add_argument("--platform", default=None)
    args = ap.parse_args(argv)
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    from theanompi_tpu.utils.helper_funcs import enable_compilation_cache

    enable_compilation_cache()
    buckets = (tuple(int(b) for b in args.prefill_buckets.split(","))
               if args.prefill_buckets else None)
    with monitor.session(stall_after=float("inf"),
                         name=f"prefill{os.getpid()}"):
        monitor.progress(phase="frontdoor")
        server = PrefillServer(
            args.export_dir, page_size=args.page_size,
            pages_per_seq=args.pages_per_seq, max_seqs=args.max_seqs,
            prefill_buckets=buckets, max_pending=args.max_pending,
            prefix_cache=not args.no_prefix_cache)
        s = server.session
        print(f"[frontdoor] PREFILL v{s.version} on "
              f"{args.host}:{args.port} (window={s.window}, "
              f"page_size={s.cfg.page_size}, "
              f"prefill_buckets={s.prefill_buckets}, "
              f"max_pending={server.max_pending})", flush=True)
        serve(server, args.host, args.port)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
