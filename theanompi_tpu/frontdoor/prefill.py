"""Prefill fleet — the compute-bound half of disaggregated serving.

A :class:`PrefillServer` owns one :class:`~theanompi_tpu.decode.session
.DecodeSession` and runs ONLY the prompt-phase programs: ``prefill``
(and ``extend`` on a prefix-cache hit).  One ``prefill`` RPC admits the
prompt, reads the first generated token off the prefill logits, exports
the sequence's KV pages as host arrays (ring layout verbatim,
``decode/migrate.py``), releases the pages back to the pool, and ships
``(manifest, RawArrays(k, v))`` — the raw uint8 frame path, because KV
bytes must arrive at the decode fleet EXACTLY as prefilled.

The replica holds NO stream state across requests: pages live on it
only for the duration of one RPC (the prefix cache keeps page-aligned
prefixes hot across prompts, exactly like a decode replica's).  That is
what makes the prefill role trivially scalable — the autoscaler
(``frontdoor/autoscale.py``) can kill any prefill replica between RPCs
without dropping a stream.

Admission is a counter, not a queue: past ``max_pending`` concurrent
prefills the RPC is refused with the typed
:class:`~theanompi_tpu.serving.batcher.Overloaded` in O(1) — the
router treats it as load-shedding and tries the next replica, never a
destructive retry.

Concurrent prefills COALESCE: handler threads enqueue their prompt and
elect a leader (whoever lands the session lock first), and the leader
drains up to ``prefill_batch`` queued prompts — waiting out a
``DynamicBatcher``-style deadline measured from the OLDEST queued
request — into ONE ``admit_batch`` program call plus one batched page
export.  Followers park on their job's event; a prompt-heavy burst
costs one device dispatch instead of N
(docs/SERVING.md "Batched prefill").

The fleet-wide prefix cache (``decode/fleetcache.py``) also lives
here: ONE prefill replica serves the ``cache_lookup`` /
``cache_register`` / ``cache_decref`` ops as the fleet's cache
AUTHORITY, with a lease table whose page references make remote LRU
eviction safe; every other replica attaches a ``FleetCacheClient`` to
its session via ``--fleet-cache``.
"""

from __future__ import annotations

import argparse
import collections
import os
import threading
import time

import numpy as np

from theanompi_tpu import monitor
from theanompi_tpu.analysis.lockgraph import make_condition, make_lock
from theanompi_tpu.decode import fleetcache, migrate
from theanompi_tpu.decode.session import DecodeSession
from theanompi_tpu.parallel import rpc, wire
from theanompi_tpu.parallel.service import ServiceClient, ServiceError
from theanompi_tpu.resilience import faults
from theanompi_tpu.serving.batcher import Overloaded
from theanompi_tpu.serving.export import build_model_from_meta, load_export

#: one above the serving block's 45900
DEFAULT_PORT = 45950


class _PrefillJob:
    """One queued prompt awaiting the coalescing leader."""

    __slots__ = ("prompt", "t0", "done", "result", "error")

    def __init__(self, prompt: np.ndarray):
        self.prompt = prompt
        self.t0 = time.monotonic()
        self.done = threading.Event()
        self.result = None
        self.error: BaseException | None = None


class PrefillServer:
    """One prefill replica: prompt in, (manifest, KV pages) out."""

    def __init__(self, export_dir: str, page_size: int = 16,
                 pages_per_seq: int = 8, max_seqs: int = 8,
                 prefill_buckets: tuple[int, ...] | None = None,
                 max_pending: int = 8, warmup: bool = True,
                 model=None, prefix_cache: bool = True,
                 prefill_batch: int = 8,
                 prefill_delay_ms: float = 2.0,
                 fleet_cache: str | None = None):
        self.export_dir = os.path.abspath(export_dir)
        loaded = load_export(self.export_dir)
        if not loaded.meta.get("decode"):
            raise ValueError(
                "the prefill role needs a decode-capable export "
                "(TransformerLM family; export_meta 'decode' is "
                f"false/absent in {self.export_dir})")
        self.model = (model if model is not None
                      else build_model_from_meta(loaded.meta))
        self.session = DecodeSession(
            self.model, params=loaded.params, version=loaded.version,
            page_size=page_size, pages_per_seq=pages_per_seq,
            max_seqs=max_seqs, prefill_buckets=prefill_buckets,
            prefix_cache=prefix_cache)
        self.max_pending = int(max_pending)
        #: coalescing cap (1 = the pre-batching serial program path)
        self.prefill_batch = max(1, int(prefill_batch))
        #: how long the OLDEST queued prompt waits for company before
        #: the leader launches a partial batch
        self.prefill_delay_ms = float(prefill_delay_ms)
        # the session's host-side state (pool, prefix cache, jit calls)
        # is built for a single scheduler thread; RPC handlers are a
        # pool, so one lock serializes the admit→export→release window
        # (the coalescing LEADER of each batch holds it)
        self._lock = make_lock("PrefillServer._lock")
        self.n_prefills = 0        # guarded_by: self._lock
        self.n_batches = 0         # guarded_by: self._lock
        #: live fleet-cache leases: lease id -> increfed page ids
        self._leases: dict[str, list[int]] = {}  # guarded_by: self._lock
        self._lease_seq = 0        # guarded_by: self._lock
        self._gate = make_lock("PrefillServer._gate")
        self._inflight = 0         # guarded_by: self._gate
        self.n_shed = 0            # guarded_by: self._gate
        #: prompts awaiting a coalescing leader (lock order: _lock
        #: before _bq_cond — the leader gathers under the session lock)
        self._bq: collections.deque[_PrefillJob] = collections.deque()
        self._bq_cond = make_condition(name="PrefillServer._bq_cond")
        if fleet_cache:
            # this replica is a fleet-cache CLIENT: local misses fetch
            # from (and cold prefills register with) the authority
            self.session.fleet = fleetcache.FleetCacheClient(fleet_cache)
        if warmup:
            self.session.warmup()
            if self.prefill_batch > 1:
                self.session.warmup_prefill_batch()

    # -- request path --------------------------------------------------

    def prefill(self, prompt) -> tuple[dict, wire.RawArrays]:
        """One prompt pass: returns the page manifest and the filled
        pages.  O(1) typed ``Overloaded`` past the admission bound; a
        bad prompt (too long, empty) raises ``ValueError`` — a
        per-request refusal either way, the replica keeps serving.

        Concurrent calls coalesce (leader/follower over the session
        lock): up to ``prefill_batch`` queued prompts run as one
        batched program + one batched export, each caller still
        getting exactly its own ``(manifest, pages)``."""
        with self._gate:
            if self._inflight >= self.max_pending:
                self.n_shed += 1
                monitor.inc("frontdoor/prefill_shed_total")
                raise Overloaded(
                    f"prefill admission: {self._inflight} in flight "
                    f">= max_pending {self.max_pending}")
            self._inflight += 1
        try:
            faults.fire("page_migrate", side="export")
            prompt = np.asarray(prompt, np.int32).reshape(-1)
            t = prompt.shape[0]
            if not 1 <= t <= self.session.max_prompt:
                # refuse BEFORE enqueue so one bad prompt can never
                # fail the batch it would have ridden in
                raise ValueError(
                    f"prompt length {t} outside "
                    f"[1, {self.session.max_prompt}] (largest prefill "
                    "bucket)")
            job = _PrefillJob(prompt)
            with self._bq_cond:
                self._bq.append(job)
                self._bq_cond.notify_all()
            # leader election: whoever lands the session lock first
            # drains a batch (which may or may not include this job —
            # loop until someone's batch carried it)
            while not job.done.is_set():
                with self._lock:
                    if not job.done.is_set():
                        self._run_batch_locked()
            if job.error is not None:
                raise job.error
            return job.result
        finally:
            with self._gate:
                self._inflight -= 1

    def _run_batch_locked(self) -> None:  # requires_lock: self._lock
        """Leader leg (session lock held): wait out the oldest queued
        prompt's coalescing deadline, drain up to ``prefill_batch``
        jobs, run ONE admit + export for all of them, and resolve each
        job's event.  Pages always release — an exported batch leaves
        no stream state behind, success or failure."""
        cap = min(self.prefill_batch, self.session.cfg.max_seqs)
        with self._bq_cond:
            if not self._bq:
                return
            if cap > 1 and self.prefill_delay_ms > 0:
                deadline = self._bq[0].t0 + self.prefill_delay_ms / 1e3
                while len(self._bq) < cap:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._bq_cond.wait(remaining)
            jobs = [self._bq.popleft()
                    for _ in range(min(cap, len(self._bq)))]
        if not jobs:
            return
        t0 = time.perf_counter()
        try:
            if cap == 1:
                # serial program path, byte-for-byte the pre-batching
                # behavior (the bench's serial comparison leg)
                admitted = [self.session.admit(jobs[0].prompt)]
            else:
                admitted = self.session.admit_batch(
                    [j.prompt for j in jobs])
        except Exception as e:
            for job in jobs:
                job.error = e
                job.done.set()
            return
        try:
            exported = self.session.export_pages_batch(
                [s for s, _ in admitted])
            for job, (seq, logits), (k, v) in zip(jobs, admitted,
                                                  exported):
                first = int(np.argmax(logits))
                manifest = migrate.page_manifest(
                    self.session.cfg, job.prompt, seq.length, first,
                    version=self.session.version)
                job.result = (manifest, wire.RawArrays(k, v))
            self.n_prefills += len(jobs)
            self.n_batches += 1
        except Exception as e:
            for job in jobs:
                job.error = e
        finally:
            # pages are exported (or the batch failed) — this replica
            # is done with the streams; only the prefix cache may keep
            # their pages shared
            for seq, _ in admitted:
                self.session.release(seq)
            for job in jobs:
                job.done.set()
        monitor.inc("frontdoor/prefills_total", float(len(jobs)))
        monitor.observe("frontdoor/prefill_batch_occupancy",
                        float(len(jobs)))
        monitor.observe("frontdoor/prefill_ms",
                        (time.perf_counter() - t0) * 1000.0)

    # -- fleet prefix-cache authority (decode/fleetcache.py) -----------

    def cache_lookup(self, prompt):
        """Authority op: longest page-aligned cached prefix of
        ``prompt``.  A hit increfs the entry's pages under a fresh
        lease and ships their bytes — the lease's reference is what
        makes remote eviction safe: evicting the entry drops ITS
        references, but a page cannot reach zero (and free) until
        :meth:`cache_decref` drops the lease too."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        with self._lock:
            pc = self.session.prefix_cache
            entry = pc.lookup(prompt) if pc is not None else None
            if entry is None:
                monitor.inc("frontdoor/fleet_cache_lookups_total",
                            result="miss")
                return None
            self.session.pool.incref(entry.pages)
            self._lease_seq += 1
            lease = f"lease-{os.getpid()}-{self._lease_seq}"
            self._leases[lease] = list(entry.pages)
            k, v = self.session.export_page_ids(entry.pages)
            manifest = fleetcache.prefix_manifest(
                self.session.cfg, prompt[:entry.n_tokens],
                version=self.session.version)
            monitor.inc("frontdoor/fleet_cache_lookups_total",
                        result="hit")
            monitor.set_gauge("frontdoor/fleet_cache_leases",
                              float(len(self._leases)))
        return manifest, wire.RawArrays(k, v), lease

    def cache_decref(self, lease_id) -> str:
        """Authority op: release a lease's page reference.  Unknown
        leases (foreign, double decref) raise the typed
        :class:`~theanompi_tpu.decode.fleetcache.LeaseError` — a
        per-call refusal that can never unbalance the refcounts."""
        with self._lock:
            pages = self._leases.pop(str(lease_id), None)
            if pages is None:
                raise fleetcache.LeaseError(
                    f"unknown lease {lease_id!r} (foreign, or already "
                    "released)")
            self.session.pool.decref(pages)
            monitor.set_gauge("frontdoor/fleet_cache_leases",
                              float(len(self._leases)))
        return "ok"

    def cache_register(self, manifest, pages) -> dict:
        """Authority op: adopt a peer's just-prefilled prefix pages as
        cache content.  Geometry/shape mismatches raise the typed
        ``IncompatiblePages`` refusal before the pool is touched."""
        k, v = pages          # RawArrays decodes to a plain tuple
        with self._lock:
            if self.session.prefix_cache is None:
                return {"added": False,
                        "reason": "prefix cache disabled"}
            reason = fleetcache.prefix_incompatibility(
                manifest, k, v, self.session.cfg)
            if reason is not None:
                raise migrate.IncompatiblePages(reason)
            added = self.session.adopt_prefix(
                np.asarray(manifest["prefix"], np.int32), k, v)
        monitor.inc("frontdoor/fleet_cache_registers_total")
        return {"added": bool(added)}

    # -- introspection -------------------------------------------------

    def stats(self) -> dict:
        with self._gate:
            inflight, shed = self._inflight, self.n_shed
        with self._lock:
            prefills, batches = self.n_prefills, self.n_batches
            leases = len(self._leases)
            pc = self.session.prefix_cache
            hits = (None if pc is None
                    else {"hits": pc.hits, "misses": pc.misses,
                          "entries": len(pc)})
        return {
            "role": "prefill",
            "version": self.session.version,
            "prefills": prefills,
            "prefill_batches": batches,
            "prefill_batch": self.prefill_batch,
            "fleet_cache_leases": leases,
            "inflight": inflight,
            "max_pending": self.max_pending,
            "overloaded": shed,
            "prefix_cache": hits,
            "compiles": dict(self.session.compiles),
        }

    # -- wire dispatch -------------------------------------------------

    def rpc_max_workers(self) -> int:
        # every admissible prefill may block in a handler + slack so
        # O(1) Overloaded refusals never park behind them
        return self.max_pending + 8

    def handle(self, op: str, *args):
        if op == "prefill":
            (prompt,) = args
            return self.prefill(prompt)
        if op == "cache_lookup":
            (prompt,) = args
            return self.cache_lookup(prompt)
        if op == "cache_register":
            manifest, pages = args
            return self.cache_register(manifest, pages)
        if op == "cache_decref":
            (lease_id,) = args
            return self.cache_decref(lease_id)
        if op == "stats":
            return self.stats()
        if op == "ping":
            return "pong"
        raise ValueError(f"unknown op {op!r}")


def serve(server: PrefillServer, host: str = "0.0.0.0",
          port: int = DEFAULT_PORT,
          ready_event: threading.Event | None = None,
          stop_event: threading.Event | None = None,
          authkey: bytes | None = None,
          loop: str | None = None) -> None:
    """The shared RPC substrate over a :class:`PrefillServer` (same
    HMAC/wire-v2/typed-err stack as every other plane)."""
    from theanompi_tpu.parallel.service import _authkey

    if authkey is None:
        authkey = _authkey(generate=True)
    rpc.serve(server, host, port, ready_event=ready_event,
              stop_event=stop_event, authkey=authkey,
              hooks=rpc.RpcHooks(), loop=loop,
              max_workers=server.rpc_max_workers())


class PrefillClient(ServiceClient):
    """Wire client for the prefill role: ``prefill`` is pure (the
    replica keeps no stream state), so at-least-once transport retries
    are safe; typed ``Overloaded`` re-raises as itself and is never
    retried by the transport — the ROUTER owns what happens next."""

    def prefill(self, prompt) -> tuple[dict, np.ndarray, np.ndarray]:
        try:
            manifest, pages = self.call(
                "prefill", np.asarray(prompt, np.int32))
        except ServiceError as e:
            if Overloaded.__name__ in str(e):
                raise Overloaded(str(e)) from None
            raise
        k, v = pages          # RawArrays decodes to a plain tuple
        return manifest, k, v

    def stats(self) -> dict:
        return self.call("stats")

    def ping(self) -> str:
        return self.call("ping")

    def shutdown(self) -> None:
        self.call("shutdown")


# ---------------------------------------------------------------------------
# Entry point (frontdoor/fleet.py spawns this module per prefill proc)
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="theanompi-tpu prefill replica (disaggregated "
                    "serving, docs/SERVING.md 'Disaggregated serving')")
    ap.add_argument("--export-dir", required=True)
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=DEFAULT_PORT)
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--pages-per-seq", type=int, default=8)
    ap.add_argument("--max-seqs", type=int, default=8)
    ap.add_argument("--prefill-buckets", default=None, metavar="N,N,...")
    ap.add_argument("--max-pending", type=int, default=8)
    ap.add_argument("--no-prefix-cache", action="store_true")
    ap.add_argument("--prefill-batch", type=int, default=8,
                    help="max prompts coalesced into one batched "
                         "prefill (1 = serial programs)")
    ap.add_argument("--prefill-delay-ms", type=float, default=2.0,
                    help="how long the oldest queued prompt waits "
                         "for company before a partial batch runs")
    ap.add_argument("--fleet-cache", default=None, metavar="HOST:PORT",
                    help="fleet prefix-cache authority address (this "
                         "replica becomes a fleet-cache client)")
    ap.add_argument("--platform", default=None)
    args = ap.parse_args(argv)
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    from theanompi_tpu.utils.helper_funcs import enable_compilation_cache

    enable_compilation_cache()
    buckets = (tuple(int(b) for b in args.prefill_buckets.split(","))
               if args.prefill_buckets else None)
    with monitor.session(stall_after=float("inf"),
                         name=f"prefill{os.getpid()}"):
        monitor.progress(phase="frontdoor")
        server = PrefillServer(
            args.export_dir, page_size=args.page_size,
            pages_per_seq=args.pages_per_seq, max_seqs=args.max_seqs,
            prefill_buckets=buckets, max_pending=args.max_pending,
            prefix_cache=not args.no_prefix_cache,
            prefill_batch=args.prefill_batch,
            prefill_delay_ms=args.prefill_delay_ms,
            fleet_cache=args.fleet_cache)
        s = server.session
        print(f"[frontdoor] PREFILL v{s.version} on "
              f"{args.host}:{args.port} (window={s.window}, "
              f"page_size={s.cfg.page_size}, "
              f"prefill_buckets={s.prefill_buckets}, "
              f"max_pending={server.max_pending})", flush=True)
        serve(server, args.host, args.port)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
