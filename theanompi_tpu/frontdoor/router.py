"""Front-door router — one client stream, two fleets.

The router terminates client ``generate`` streams and splits each one
across the disaggregated roles (docs/SERVING.md "Disaggregated
serving"): the prompt phase goes to a PREFILL replica
(``frontdoor/prefill.py``), the filled KV pages come back as raw
wire-v2 frames, and the token phase ships pages + manifest to a DECODE
replica's ``adopt`` op — one RPC per phase, so a stream's decode leg
has natural per-stream affinity (all its tokens come from the backend
that adopted its pages).

This is the first consumer built natively on the shared RPC substrate's
mux transport (ROADMAP item 6): every backend is ONE
:class:`~theanompi_tpu.parallel.rpc.MuxConnection` (one socket + one
reader thread) carrying a pool of :class:`ServiceClient` streams, so a
hundred concurrent streams to a backend cost one fd, not a hundred.

Failure discipline, per leg:

* **Overloaded** (typed, from a backend's admission bound) — try the
  next live backend of that role ONCE EACH; when every one sheds, the
  router sheds too, propagating the typed ``Overloaded`` to the client.
  Load shedding composes; nothing is retried destructively.
* **Transport loss on the decode leg** (a replica died mid-stream) —
  FAILOVER: re-prefill from the prompt (the manifest carries it for
  exactly this) and adopt onto a surviving replica.  The adopt RPC
  returns the whole stream at once, so no token was delivered before
  the loss and greedy decode makes the retried stream byte-identical
  (tests/test_frontdoor.py pins it against the single-role oracle).
* **IncompatiblePages / ValueError** (typed refusals) — propagate to
  the client untouched; refusals are answers, not failures.

Backend sets are dynamic (``set_backends`` — the autoscaler's seam):
a removed backend DRAINS — no new streams route to it, in-flight
streams finish, and the autoscaler kills the process only once the
router reports zero streams on it.  Scale events drop nothing.

Trace context rides the existing substrate envelopes: the client's
span parents the router's dispatch span, whose context every backend
RPC injects — ``tools/traces.py`` stitches client → router → prefill →
decode from one collector file.
"""

from __future__ import annotations

import argparse
import os
import threading
import time

import numpy as np

from theanompi_tpu import monitor
from theanompi_tpu.analysis.lockgraph import make_lock
from theanompi_tpu.decode.migrate import IncompatiblePages
from theanompi_tpu.frontdoor.prefill import PrefillClient
from theanompi_tpu.parallel import rpc
from theanompi_tpu.parallel.service import ServiceClient, ServiceError
from theanompi_tpu.resilience import faults
from theanompi_tpu.resilience.retry import CONNECTION_ERRORS, RetryPolicy
from theanompi_tpu.serving.batcher import Overloaded
from theanompi_tpu.serving.server import InferenceClient

#: one above the prefill role's 45950
DEFAULT_PORT = 46000

#: the two downstream roles a router balances over
ROLES = ("prefill", "decode")


def _backend_retry() -> RetryPolicy:
    """Backend RPCs fail FAST: the router owns recovery (next backend,
    re-prefill failover), so the transport layer must not sit in a
    reconnect loop against a replica the autoscaler just killed."""
    return RetryPolicy(max_attempts=1, name="frontdoor-backend")


class _Backend:
    """One downstream replica: a shared mux transport + client pool.

    Clients serialize their own ``call`` — one concurrent stream needs
    one client — so the pool hands each stream a private client riding
    the backend's single multiplexed socket."""

    def __init__(self, role: str, addr: str):
        self.role = role
        self.addr = addr
        self._cls = PrefillClient if role == "prefill" else InferenceClient
        self._lock = make_lock("frontdoor._Backend._lock")
        self._mux: rpc.MuxConnection | None = None  # guarded_by: self._lock
        self._free: list = []      # guarded_by: self._lock
        self.streams = 0           # guarded_by: self._lock
        self.draining = False      # guarded_by: self._lock
        self.errors = 0            # guarded_by: self._lock

    def _transport(self) -> rpc.MuxConnection:
        with self._lock:
            mux = self._mux
        if mux is not None:
            return mux
        mux = rpc.MuxConnection(self.addr)      # network IO: no lock
        with self._lock:
            if self._mux is None:
                self._mux = mux
                return mux
            extra = mux
        extra.close()
        return self._transport()

    def acquire(self):
        """A client for one stream (pooled), counting the stream in."""
        with self._lock:
            self.streams += 1
            if self._free:
                return self._free.pop()
        try:
            return self._cls(self.addr, transport=self._transport(),
                             retry=_backend_retry())
        except BaseException:
            with self._lock:
                self.streams -= 1
            raise

    def release(self, client, ok: bool) -> bool:
        """Return a stream's client; a transport-broken one is closed
        instead of pooled.  Returns True when this was a draining
        backend's LAST stream — the caller closes the backend."""
        with self._lock:
            self.streams -= 1
            if not ok:
                self.errors += 1
            if ok and not self.draining:
                self._free.append(client)
                return False
            draining = self.draining
            last = draining and self.streams == 0
        if not ok or draining:
            try:
                client.close()
            except Exception:
                pass
        return last

    def close(self) -> None:
        with self._lock:
            free, self._free = self._free, []
            mux, self._mux = self._mux, None
        for c in free:
            try:
                c.close()
            except Exception:
                pass
        if mux is not None:
            mux.close()

    def snapshot(self) -> dict:
        with self._lock:
            return {"addr": self.addr, "role": self.role,
                    "streams": self.streams, "draining": self.draining,
                    "errors": self.errors}


class Router:
    """Stream terminator + role balancer (module docstring)."""

    def __init__(self, prefill: list[str] | None = None,
                 decode: list[str] | None = None,
                 max_streams: int = 64, failover_attempts: int = 2):
        self.max_streams = int(max_streams)
        self.failover_attempts = int(failover_attempts)
        self._lock = make_lock("frontdoor.Router._lock")
        self._backends: dict[str, list[_Backend]] = {
            r: [] for r in ROLES}                 # guarded_by: self._lock
        self._rr = {r: 0 for r in ROLES}          # guarded_by: self._lock
        self._active = 0                          # guarded_by: self._lock
        self.n_streams = 0                        # guarded_by: self._lock
        self.n_shed = 0                           # guarded_by: self._lock
        self.n_failovers = 0                      # guarded_by: self._lock
        #: streams re-adopted onto a survivor after a decode backend
        #: drained mid-stream (scale-down page re-migration)
        self.n_migrations = 0                     # guarded_by: self._lock
        for addr in prefill or []:
            self.add_backend("prefill", addr)
        for addr in decode or []:
            self.add_backend("decode", addr)

    # -- backend set (the autoscaler's seam) ---------------------------

    def add_backend(self, role: str, addr: str) -> None:
        if role not in ROLES:
            raise ValueError(f"unknown role {role!r} (want {ROLES})")
        addr = str(addr)
        with self._lock:
            for b in self._backends[role]:
                if b.addr == addr:
                    # re-adding a draining backend revives it — the
                    # autoscaler flip-flopped inside one drain window
                    with b._lock:  # lint: ok TM101
                        b.draining = False
                    return
            self._backends[role].append(_Backend(role, addr))
        monitor.set_gauge("frontdoor/backends", self._role_size(role),
                          role=role)

    def remove_backend(self, role: str, addr: str) -> None:
        """Start DRAINING one backend: no new streams route to it;
        in-flight streams finish and the last one out closes it.  The
        autoscaler kills the process only at ``streams == 0``
        (``backend_streams``) — scale-down drops nothing."""
        drained = None
        with self._lock:
            for b in self._backends[role]:
                if b.addr == str(addr):
                    with b._lock:  # lint: ok TM101
                        b.draining = True
                        if b.streams == 0:
                            drained = b
                    break
            if drained is not None:
                self._backends[role].remove(drained)
        if drained is not None:
            drained.close()
        monitor.set_gauge("frontdoor/backends", self._role_size(role),
                          role=role)

    def set_backends(self, role: str, addrs: list[str]) -> None:
        """Reconcile one role's backend set (adds + drains)."""
        want = [str(a) for a in addrs]
        with self._lock:
            have = [b.addr for b in self._backends[role]]
        for a in want:
            if a not in have:
                self.add_backend(role, a)
        for a in have:
            if a not in want:
                self.remove_backend(role, a)

    def backend_streams(self, role: str, addr: str) -> int:
        """In-flight streams on one backend (0 also when the backend
        is already gone) — the autoscaler's drain barrier."""
        with self._lock:
            for b in self._backends[role]:
                if b.addr == str(addr):
                    with b._lock:  # lint: ok TM101
                        return b.streams
        return 0

    def _role_size(self, role: str) -> int:
        with self._lock:
            return sum(1 for b in self._backends[role]
                       if not b.draining)

    def _candidates(self, role: str) -> list[_Backend]:
        """Live (non-draining) backends in round-robin order, rotated
        one step per call — each stream starts on the next backend and
        fails over through the rest."""
        with self._lock:
            live = [b for b in self._backends[role] if not b.draining]
            if not live:
                return []
            start = self._rr[role] % len(live)
            self._rr[role] += 1
            return live[start:] + live[:start]

    def _drop_if_drained(self, b: _Backend) -> None:
        with self._lock:
            try:
                self._backends[b.role].remove(b)
            except ValueError:
                return  # a concurrent releaser already dropped it
        b.close()

    # -- request path --------------------------------------------------

    def _prefill_leg(self, prompt: np.ndarray):
        """Prompt phase: first willing prefill replica wins.  Typed
        ``Overloaded`` tries the next; transport loss tries the next;
        any other typed error (bad prompt) propagates — it would fail
        identically everywhere."""
        backends = self._candidates("prefill")
        if not backends:
            with self._lock:
                self.n_shed += 1
            monitor.inc("frontdoor/shed_total", role="prefill")
            raise Overloaded("no live prefill backends (the fleet is "
                             "scaled to zero or still coming up)")
        t0 = time.perf_counter()
        last: BaseException | None = None
        for b in backends:
            client = b.acquire()
            ok = True
            try:
                manifest, k, v = client.prefill(prompt)
            except Overloaded as e:
                last = e
                continue
            except ServiceError:
                raise
            except CONNECTION_ERRORS as e:
                ok = False
                last = e
                continue
            finally:
                if b.release(client, ok):
                    self._drop_if_drained(b)
            monitor.inc("frontdoor/routed_total", role="prefill")
            monitor.observe("frontdoor/migrate_ms",
                            (time.perf_counter() - t0) * 1000.0)
            return manifest, k, v
        if isinstance(last, Overloaded):
            with self._lock:
                self.n_shed += 1
            monitor.inc("frontdoor/shed_total", role="prefill")
            raise Overloaded(f"every prefill backend shed: {last}")
        raise ConnectionError(
            f"every prefill backend unreachable: {last}") from last

    def _decode_leg(self, manifest: dict, k, v, max_new):
        """Token phase: adopt the pages on one decode replica and run
        the stream there (per-stream affinity = one RPC, one backend).
        Transport loss raises for :meth:`generate`'s failover loop."""
        backends = self._candidates("decode")
        if not backends:
            with self._lock:
                self.n_shed += 1
            monitor.inc("frontdoor/shed_total", role="decode")
            raise Overloaded("no live decode backends (the fleet is "
                             "scaled to zero or still coming up)")
        last: Overloaded | None = None
        for b in backends:
            client = b.acquire()
            ok = True
            try:
                out = client.adopt(manifest, k, v, max_new)
            except Overloaded as e:
                last = e
                continue
            except CONNECTION_ERRORS as e:
                ok = False
                raise ConnectionError(
                    f"decode backend {b.addr} lost mid-stream: {e}"
                ) from e
            finally:
                if b.release(client, ok):
                    self._drop_if_drained(b)
            monitor.inc("frontdoor/routed_total", role="decode")
            return out
        with self._lock:
            self.n_shed += 1
        monitor.inc("frontdoor/shed_total", role="decode")
        raise Overloaded(f"every decode backend shed: {last}")

    def generate(self, prompt, max_new: int | None = None) -> np.ndarray:
        """One full client stream across the two fleets; returns the
        generated token ids (first token included), byte-identical to
        a single-role decode server's ``generate`` of the same prompt.
        A decode backend that DRAINS mid-stream (scale-down) hands the
        stream back as pages + partial tokens; the router adopts them
        onto a survivor and stitches the halves — still
        byte-identical."""
        from theanompi_tpu.decode.scheduler import MigratedStream

        prompt = np.asarray(prompt, np.int32).reshape(-1)
        with self._lock:
            if self._active >= self.max_streams:
                self.n_shed += 1
                monitor.inc("frontdoor/shed_total", role="router")
                raise Overloaded(
                    f"router admission: {self._active} streams in "
                    f"flight >= max_streams {self.max_streams}")
            self._active += 1
            self.n_streams += 1
        monitor.add_gauge("frontdoor/streams_active", 1.0)
        try:
            with monitor.span("page_migrate", phase="prefill"):
                manifest, k, v = self._prefill_leg(prompt)
            total: list[int] = []
            remaining = max_new
            failovers = 0
            migrations = 0
            while True:
                try:
                    out = self._decode_leg(manifest, k, v, remaining)
                except ConnectionError as e:
                    if failovers >= self.failover_attempts:
                        raise
                    failovers += 1
                    # the decode replica died mid-stream; none of THIS
                    # leg's tokens were delivered (the adopt RPC
                    # returns whole streams), so re-prefilling the
                    # manifest's prompt — the original prompt, or the
                    # resume prompt after a drain migration — and
                    # adopting onto a survivor reproduces the greedy
                    # stream byte-for-byte
                    with self._lock:
                        self.n_failovers += 1
                    monitor.inc("frontdoor/failovers_total")
                    print(f"[frontdoor] decode leg failover "
                          f"({failovers}/{self.failover_attempts}): "
                          f"{e}", flush=True)
                    seed = np.asarray(manifest["prompt"], np.int32)
                    with monitor.span("page_migrate", phase="failover"):
                        manifest, k, v = self._prefill_leg(seed)
                    continue
                if isinstance(out, MigratedStream):
                    # the backend drained (scale-down): accumulate its
                    # partial tokens, adopt the exported pages onto a
                    # survivor — the resume manifest's first_token is
                    # the pending token, so nothing is lost or doubled
                    if migrations >= 8:
                        raise Overloaded(
                            "stream migrated 8 times without "
                            "finishing (decode fleet is thrashing)")
                    migrations += 1
                    with self._lock:
                        self.n_migrations += 1
                    monitor.inc("frontdoor/drain_migrations_total")
                    total.extend(int(t) for t in out.tokens)
                    if remaining is not None:
                        remaining -= len(out.tokens)
                    manifest, k, v = out.manifest, out.k, out.v
                    continue
                return np.asarray(total + [int(t) for t in out],
                                  np.int32)
        finally:
            with self._lock:
                self._active -= 1
            monitor.add_gauge("frontdoor/streams_active", -1.0)

    # -- introspection -------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            backends = {r: [b.snapshot() for b in self._backends[r]]
                        for r in ROLES}
            out = {
                "role": "router",
                "active_streams": self._active,
                "max_streams": self.max_streams,
                "streams": self.n_streams,
                "shed": self.n_shed,
                "failovers": self.n_failovers,
                "migrations": self.n_migrations,
            }
        out["backends"] = backends
        return out

    def close(self) -> None:
        with self._lock:
            backends = [b for r in ROLES for b in self._backends[r]]
            self._backends = {r: [] for r in ROLES}
        for b in backends:
            b.close()

    # -- wire dispatch -------------------------------------------------

    def rpc_max_workers(self) -> int:
        # every admissible stream may park in a handler for its whole
        # decode leg + slack for O(1) sheds and control ops
        return self.max_streams + 8

    def handle(self, op: str, *args):
        if op == "generate":
            prompt, max_new = args
            return self.generate(prompt,
                                 None if max_new is None else int(max_new))
        if op == "stats":
            return self.stats()
        if op == "set_backends":
            role, addrs = args
            self.set_backends(str(role), list(addrs))
            return "ok"
        if op == "ping":
            return "pong"
        raise ValueError(f"unknown op {op!r}")


class _FrontdoorRpcHooks(rpc.RpcHooks):
    """The frontdoor plane's seams into the shared RPC substrate:
    literal ``frontdoor/*`` series names (the TM403/404 docs-coverage
    contract) and the ``router_route`` fault site."""

    plane = "frontdoor"

    def on_connect(self) -> None:
        monitor.add_gauge("frontdoor/clients", 1.0)

    def on_disconnect(self) -> None:
        monitor.add_gauge("frontdoor/clients", -1.0)

    def on_request(self, op: str, ms: float) -> None:
        monitor.inc("frontdoor/requests_total", op=op)
        monitor.observe("frontdoor/rpc_ms", ms, op=op)
        monitor.progress(phase="frontdoor")

    def on_error(self, op: str) -> None:
        monitor.inc("frontdoor/errors_total", op=op)

    def on_negotiate(self, opts) -> None:
        monitor.inc("frontdoor/wire_negotiations_total",
                    compression=opts.compression, dtype=opts.dtype)

    def fire(self, op: str) -> None:
        # fault plane: 'raise' rejects this routed request (the client
        # sees the typed err), 'delay' adds router latency — with the
        # fleets live, which is the point
        faults.fire("router_route", op=op)


def serve(router: Router, host: str = "0.0.0.0",
          port: int = DEFAULT_PORT,
          ready_event: threading.Event | None = None,
          stop_event: threading.Event | None = None,
          authkey: bytes | None = None,
          loop: str | None = None) -> None:
    """The shared RPC substrate over a :class:`Router`."""
    from theanompi_tpu.parallel.service import _authkey

    if authkey is None:
        authkey = _authkey(generate=True)
    rpc.serve(router, host, port, ready_event=ready_event,
              stop_event=stop_event, authkey=authkey,
              hooks=_FrontdoorRpcHooks(), loop=loop,
              max_workers=router.rpc_max_workers())


class RouterClient(ServiceClient):
    """Wire client for the front door: what a serving client points at
    when the fleet is disaggregated.  Same surface as
    :class:`~theanompi_tpu.serving.server.InferenceClient.generate`,
    same typed re-raises — callers cannot tell the topologies apart."""

    def generate(self, prompt, max_new: int | None = None) -> np.ndarray:
        try:
            return np.asarray(
                self.call("generate", np.asarray(prompt, np.int32),
                          None if max_new is None else int(max_new)),
                np.int32)
        except ServiceError as e:
            if Overloaded.__name__ in str(e):
                raise Overloaded(str(e)) from None
            if IncompatiblePages.__name__ in str(e):
                raise IncompatiblePages(str(e)) from None
            raise

    def stats(self) -> dict:
        return self.call("stats")

    def set_backends(self, role: str, addrs: list[str]) -> None:
        self.call("set_backends", str(role), [str(a) for a in addrs])

    def ping(self) -> str:
        return self.call("ping")

    def shutdown(self) -> None:
        self.call("shutdown")


# ---------------------------------------------------------------------------
# Entry point (a bare router over existing fleets; frontdoor/fleet.py
# spawns whole fleets and runs the router in-process)
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="theanompi-tpu front-door router (disaggregated "
                    "serving, docs/SERVING.md)")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=DEFAULT_PORT)
    ap.add_argument("--prefill", default="", metavar="HOST:PORT,...",
                    help="comma-separated prefill backends")
    ap.add_argument("--decode", default="", metavar="HOST:PORT,...",
                    help="comma-separated decode backends "
                         "(decode-mode tmserver instances)")
    ap.add_argument("--max-streams", type=int, default=64)
    ap.add_argument("--failover-attempts", type=int, default=2)
    args = ap.parse_args(argv)
    prefill = [a for a in args.prefill.split(",") if a]
    decode = [a for a in args.decode.split(",") if a]
    with monitor.session(stall_after=float("inf"),
                         name=f"router{os.getpid()}"):
        monitor.progress(phase="frontdoor")
        router = Router(prefill=prefill, decode=decode,
                        max_streams=args.max_streams,
                        failover_attempts=args.failover_attempts)
        print(f"[frontdoor] ROUTER on {args.host}:{args.port} "
              f"({len(prefill)} prefill / {len(decode)} decode "
              f"backends, max_streams={args.max_streams})", flush=True)
        try:
            serve(router, args.host, args.port)
        finally:
            router.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
