"""Dynamic request batching for the inference path.

The serving throughput lever is the same one the training stack
already optimizes: amortize per-dispatch overhead by putting MANY
samples through ONE accelerator step (the TensorFlow system paper's
serving story, arXiv:1605.08695 §4 — and the reason per-request
inference wastes an MXU).  A :class:`DynamicBatcher` coalesces
concurrent requests until either ``max_batch`` rows are pending or the
OLDEST request has waited ``max_delay_ms`` — latency is bounded by the
delay knob, throughput by the batch knob.

**Buckets** (the no-recompile contract): every coalesced batch is
padded up to one of a small fixed set of row counts
(``BatchPolicy.buckets``, default powers of two up to ``max_batch``),
so steady-state serving only ever presents ``len(buckets)`` distinct
input shapes to the jitted inference fn — each compiles once (at
warmup or on first use) and never again.  Padding rows are zeros;
eval-mode inference is row-independent (BatchNorm uses running stats —
tests/test_fused_bn.py eval-parity), so pad rows cannot perturb real
rows and are simply sliced off the result.

**Admission control** (overload semantics, docs/SERVING.md): the
pending-request queue is bounded at ``max_queue``.  When it is full,
``submit`` raises :class:`Overloaded` IMMEDIATELY instead of
enqueueing — under sustained overload every accepted request keeps a
bounded latency and the excess is rejected in O(1), rather than every
request's latency collapsing as an unbounded queue grows.  The typed
class name rides the service wire in the ``err`` reply prefix (the
same mechanism as ``SessionDisplaced`` in parallel/service.py), so a
remote client re-raises ``Overloaded`` rather than parsing prose.

Telemetry (all strictly no-op when the monitor is disabled):
``serving/request_ms`` (submit→result latency histogram),
``serving/batch_rows`` / ``serving/batch_occupancy`` (dynamic batch
formation), ``serving/queue_depth`` gauge, ``serving/overloaded_total``,
``serving/padding_rows_total``, and a per-replica heartbeat gauge
``serving/replica_heartbeat``.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Callable

import numpy as np

from theanompi_tpu import monitor
from theanompi_tpu.analysis.lockgraph import make_condition, make_lock


class Overloaded(RuntimeError):
    """Admission-control rejection: the queue is at capacity (or the
    replica is dead).  Deliberately NOT retried by the transport —
    the server answered, fast, and the correct reactions (client-side
    backoff, load shedding, more replicas) live above the wire."""


def default_buckets(max_batch: int) -> tuple[int, ...]:
    """Powers of two up to ``max_batch`` (always included) — a handful
    of compiled programs covering every occupancy."""
    out = set()
    b = 1
    while b < max_batch:
        out.add(b)
        b *= 2
    out.add(max_batch)
    return tuple(sorted(out))


def pick_bucket(rows: int, buckets: tuple[int, ...]) -> int:
    """Smallest bucket >= rows (buckets sorted ascending)."""
    for b in buckets:
        if b >= rows:
            return b
    raise ValueError(f"{rows} rows exceed the largest bucket "
                     f"{buckets[-1]}")


@dataclasses.dataclass(frozen=True)
class BatchPolicy:
    """Batching/admission knobs for one replica (docs/SERVING.md)."""

    #: max rows per coalesced batch (= the largest bucket)
    max_batch: int = 8
    #: max time the OLDEST pending request waits for company before
    #: the batch dispatches regardless of occupancy
    max_delay_ms: float = 5.0
    #: padded batch shapes (sorted ascending); None = powers of two up
    #: to max_batch.  The largest bucket must equal max_batch.
    buckets: tuple[int, ...] | None = None
    #: admission bound: pending REQUESTS beyond this are rejected with
    #: Overloaded instead of queued
    max_queue: int = 32
    #: a submitted request gives up after this long (a dead/wedged
    #: replica must not hang its clients forever)
    submit_timeout_s: float = 60.0

    def resolved_buckets(self) -> tuple[int, ...]:
        if self.buckets is None:
            return default_buckets(self.max_batch)
        bs = tuple(sorted(set(int(b) for b in self.buckets)))
        if not bs or bs[0] < 1:
            raise ValueError(f"invalid buckets {self.buckets!r}")
        if bs[-1] != self.max_batch:
            raise ValueError(
                f"largest bucket {bs[-1]} != max_batch {self.max_batch} "
                "— a full batch must have a shape to land in")
        return bs


class _Request:
    __slots__ = ("x", "rows", "done", "result", "error", "t0")

    def __init__(self, x: np.ndarray):
        self.x = x
        self.rows = int(x.shape[0])
        self.done = threading.Event()
        self.result: np.ndarray | None = None
        self.error: BaseException | None = None
        self.t0 = time.monotonic()


class DynamicBatcher:
    """One replica's coalescing queue + collector thread.

    ``run_batch(x_padded) -> y`` executes one padded batch (leading
    dim is a bucket size); it is called from the collector thread
    only, so it needs no locking of its own.  A batch-execution
    exception fails THAT batch's requests (each ``submit`` re-raises
    it) and is handed to ``on_batch_error``; if the hook returns
    falsy the batcher marks itself dead — pending and future submits
    are rejected with :class:`Overloaded` so the server routes around
    the corpse (serving/server.py owns the restart-from-export
    policy)."""

    def __init__(self, run_batch: Callable[[np.ndarray], np.ndarray],
                 policy: BatchPolicy | None = None, replica: int = 0,
                 on_batch_error: Callable[[BaseException], bool]
                 | None = None):
        self.policy = policy or BatchPolicy()
        self.buckets = self.policy.resolved_buckets()
        self.replica = int(replica)
        self._run_batch = run_batch
        self._on_batch_error = on_batch_error
        self._q: deque[_Request] = deque()      # guarded_by: self._lock
        self._qrows = 0                         # guarded_by: self._lock
        self._lock = make_lock("DynamicBatcher._lock")
        self._cond = make_condition(self._lock)
        self._stop = threading.Event()
        self._dead = False                      # guarded_by: self._lock
        self._thread: threading.Thread | None = None
        # plain-int stats (read without the lock — torn reads of a
        # monotonically-increasing int are harmless for stats())
        self.n_batches = 0
        self.n_rows = 0
        self.n_overloaded = 0
        self.n_batch_errors = 0
        self.max_occupancy = 0

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "DynamicBatcher":
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"serving-batcher-{self.replica}")
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
        self._fail_pending(Overloaded(
            f"replica {self.replica} is shutting down"))

    @property
    def alive(self) -> bool:
        # _dead is declared guarded_by this lock, so the probe honors
        # the discipline.  alive is inherently check-then-act either
        # way — the server re-checks under the lock in submit() and
        # converts a lost race into Overloaded failover; the cost here
        # is one uncontended acquire per routing probe (the collector
        # releases the lock while it waits in _collect).
        with self._lock:
            return not self._dead and not self._stop.is_set()

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._q)

    def stats(self) -> dict:
        return {
            "replica": self.replica,
            "alive": self.alive,
            "batches": self.n_batches,
            "rows": self.n_rows,
            "overloaded": self.n_overloaded,
            "batch_errors": self.n_batch_errors,
            "max_occupancy": self.max_occupancy,
            "queue_depth": self.queue_depth(),
        }

    # -- client side ---------------------------------------------------

    def submit(self, x: np.ndarray) -> np.ndarray:
        """Enqueue one request (``x``: (rows, *sample)) and block for
        its rows of the batched result.  Raises :class:`Overloaded`
        on admission rejection, or re-raises the batch-execution
        error that consumed this request."""
        x = np.asarray(x)
        if x.ndim < 1 or x.shape[0] < 1:
            raise ValueError(f"request needs a leading rows dim >= 1, "
                             f"got shape {x.shape}")
        if x.shape[0] > self.policy.max_batch:
            raise ValueError(
                f"request rows {x.shape[0]} exceed max_batch "
                f"{self.policy.max_batch}; split the request")
        req = _Request(x)
        with self._cond:
            if self._dead or self._stop.is_set():
                self.n_overloaded += 1
                monitor.inc("serving/overloaded_total",
                            replica=self.replica)
                raise Overloaded(
                    f"replica {self.replica} is not serving")
            if len(self._q) >= self.policy.max_queue:
                self.n_overloaded += 1
                monitor.inc("serving/overloaded_total",
                            replica=self.replica)
                raise Overloaded(
                    f"replica {self.replica} queue is full "
                    f"({self.policy.max_queue} pending); rejecting "
                    "instead of queueing unboundedly")
            self._q.append(req)
            self._qrows += req.rows
            monitor.set_gauge("serving/queue_depth", len(self._q),
                              replica=self.replica)
            self._cond.notify_all()
        if not req.done.wait(self.policy.submit_timeout_s):
            # reclaim the admission slot: an abandoned request must not
            # keep counting against max_queue (starving live requests
            # with Overloaded) nor burn a device batch nobody awaits.
            # If the collector already popped it into an in-flight
            # batch (ValueError below) it executes once regardless —
            # there is no cancelling a dispatched batch.
            with self._cond:
                try:
                    self._q.remove(req)
                    self._qrows -= req.rows
                    monitor.set_gauge("serving/queue_depth",
                                      len(self._q),
                                      replica=self.replica)
                except ValueError:
                    pass
            raise TimeoutError(
                f"request timed out after "
                f"{self.policy.submit_timeout_s}s on replica "
                f"{self.replica} (wedged batch?)")
        if req.error is not None:
            raise req.error
        monitor.observe("serving/request_ms",
                        (time.monotonic() - req.t0) * 1e3)
        return req.result

    # -- collector thread ---------------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            group = self._collect()
            if group:
                self._execute(group)

    def _collect(self) -> list[_Request]:
        """Block for the first request, then hold the batch open until
        ``max_batch`` rows are pending or the oldest request has
        waited ``max_delay_ms``; pop whole requests up to the row
        cap."""
        max_rows = self.policy.max_batch
        with self._cond:
            while not self._q and not self._stop.is_set():
                # bounded wait so the heartbeat stays fresh while idle
                self._cond.wait(0.25)
                monitor.set_gauge("serving/replica_heartbeat",
                                  time.time(), replica=self.replica)
            if self._stop.is_set():
                return []
            deadline = self._q[0].t0 + self.policy.max_delay_ms / 1e3
            while self._qrows < max_rows and not self._stop.is_set():
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            group: list[_Request] = []
            rows = 0
            while self._q and rows + self._q[0].rows <= max_rows:
                req = self._q.popleft()
                self._qrows -= req.rows
                group.append(req)
                rows += req.rows
            monitor.set_gauge("serving/queue_depth", len(self._q),
                              replica=self.replica)
            return group

    def _execute(self, group: list[_Request]) -> None:
        rows = sum(r.rows for r in group)
        bucket = pick_bucket(rows, self.buckets)
        x = (group[0].x if len(group) == 1
             else np.concatenate([r.x for r in group], axis=0))
        if bucket > rows:
            pad = np.zeros((bucket - rows, *x.shape[1:]), x.dtype)
            x = np.concatenate([x, pad], axis=0)
            monitor.inc("serving/padding_rows_total", bucket - rows,
                        replica=self.replica)
        try:
            out = np.asarray(self._run_batch(x))
        except Exception as e:
            self.n_batch_errors += 1
            monitor.inc("serving/batch_errors_total",
                        replica=self.replica)
            for r in group:
                r.error = e
                r.done.set()
            if self._on_batch_error is not None:
                if not self._on_batch_error(e):
                    self._mark_dead()
            return
        self.n_batches += 1
        self.n_rows += rows
        self.max_occupancy = max(self.max_occupancy, len(group))
        monitor.observe("serving/batch_rows", rows,
                        replica=self.replica)
        monitor.observe("serving/batch_occupancy", rows / bucket,
                        replica=self.replica)
        monitor.inc("serving/batches_total", replica=self.replica)
        monitor.set_gauge("serving/replica_heartbeat", time.time(),
                          replica=self.replica)
        off = 0
        for r in group:
            r.result = out[off:off + r.rows]
            off += r.rows
            r.done.set()

    def _mark_dead(self) -> None:
        with self._cond:
            self._dead = True
            self._cond.notify_all()
        self._fail_pending(Overloaded(
            f"replica {self.replica} died (restart budget exhausted)"))

    def _fail_pending(self, err: BaseException) -> None:
        with self._cond:
            pending, self._q = list(self._q), deque()
            self._qrows = 0
        for r in pending:
            if not r.done.is_set():
                r.error = err
                r.done.set()

    # -- warmup ---------------------------------------------------------

    def warmup(self, sample_shape: tuple[int, ...],
               dtype: np.dtype, fn: Callable | None = None) -> None:
        """Compile every bucket shape up front (zeros through
        ``run_batch``), so steady-state serving never recompiles —
        call BEFORE start() or from the server's init.  ``fn``
        overrides the batch fn: the server passes the raw session so
        warmup bypasses the ``serve_step`` fault site and the served-
        batch counter — an injected fault must hit serving, not crash
        construction before the port is even bound."""
        fn = fn or self._run_batch
        for b in self.buckets:
            fn(np.zeros((b, *sample_shape), dtype))
