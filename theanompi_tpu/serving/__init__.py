"""theanompi_tpu.serving — dynamic-batching inference over exported
checkpoints (docs/SERVING.md).

The training stack ends at a checkpoint; this package is the other
half of the north star ("serve heavy traffic"): freeze a trained
model into a versioned, verified export (``export.py``), coalesce
concurrent requests into padded bucket-shaped device batches
(``batcher.py``), and answer them from a supervised multi-replica
server with admission control and hot reload (``server.py``) behind
the same authenticated wire transport the async rules use.

    # trainer / exporter side
    from theanompi_tpu.serving import export_model
    export_model(model, "exports/cifar10")

    # server:  tmlocal SERVE --export-dir exports/cifar10
    # client
    from theanompi_tpu.serving import InferenceClient
    logits = InferenceClient("host:45900").infer(batch)
"""

from theanompi_tpu.serving.batcher import (
    BatchPolicy,
    DynamicBatcher,
    Overloaded,
    default_buckets,
    pick_bucket,
)
from theanompi_tpu.serving.export import (
    IncompatibleExport,
    InferenceSession,
    LoadedExport,
    build_model_from_meta,
    dequantize_tree,
    draft_incompatibility,
    export_incompatibility,
    export_model,
    latest_export_version,
    load_export,
    quantize_tree,
)
from theanompi_tpu.serving.server import (
    DEFAULT_PORT,
    InferenceClient,
    InferenceServer,
    Replica,
    serve,
    serve_main,
)

__all__ = [
    "BatchPolicy", "DynamicBatcher", "Overloaded", "default_buckets",
    "pick_bucket", "IncompatibleExport", "InferenceSession",
    "LoadedExport", "build_model_from_meta", "dequantize_tree",
    "draft_incompatibility", "export_incompatibility", "export_model",
    "latest_export_version",
    "load_export", "quantize_tree", "DEFAULT_PORT", "InferenceClient",
    "InferenceServer", "Replica", "serve", "serve_main",
]
