"""Model export: freeze a trained zoo state into a versioned, verified,
eval-mode inference artifact.

Training-side state (``TrainState``: params + optimizer state + batch
stats) is NOT what serving loads — the optimizer state is dead weight
and the module must run its EVAL path (``train=False``:
``BatchNormAct``/``BatchNorm`` switch to running statistics, dropout
off), with the model's ``bn_act_impl``/``pool_impl`` threading intact
so a recipe benched with the fused epilogue serves with it too.

An export is a directory of numbered versions written through the same
:class:`~theanompi_tpu.utils.checkpoint.Checkpointer` machinery the
training checkpoints use — synchronous save, per-file sha256 manifest
(resilience.recovery) — plus one ``export_meta_{v}.json`` sidecar
carrying what the loader needs to REBUILD the model around the arrays:
modelfile/modelclass (the reference's resolution convention) and the
full ``ModelConfig``.  Serving readers open the directory with
``Checkpointer(read_only=True)`` — no write fence, no manifest writes,
no quarantine moves — and load via ``restore_latest_verified``, so a
half-written or bit-rotted newest version costs a fallback, never the
server.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from theanompi_tpu.parallel.wire import BF16
from theanompi_tpu.utils.checkpoint import Checkpointer

PyTree = Any

#: export weight storage dtypes (docs/SERVING.md "Quantized exports"):
#: 'bf16' halves the artifact/device bytes (the wire-v2 dtype reused at
#: rest), 'int8' quarters them with a per-output-channel scale
WEIGHT_DTYPES = ("f32", "bf16", "int8")


class IncompatibleExport(RuntimeError):
    """A published export the live server must NOT hot-swap in:
    different model, sample shape, weight dtype, or decode capability
    than what is serving.  Typed (rides the wire ``err`` prefix like
    :class:`~theanompi_tpu.serving.batcher.Overloaded`) so the reload
    watcher refuses and keeps serving instead of crashing a replica
    mid-swap."""


def meta_path(export_dir: str, version: int) -> str:
    return os.path.join(export_dir, f"export_meta_{int(version)}.json")


# ---------------------------------------------------------------------------
# Weight quantization (bf16 / int8 weight-only)
# ---------------------------------------------------------------------------

#: structural marker of one int8-quantized leaf: a dict holding exactly
#: the quantized bytes and their per-output-channel f32 scale
_INT8_KEYS = frozenset({"int8_data", "int8_scale"})


def is_quantized_leaf(node: Any) -> bool:
    return isinstance(node, dict) and set(node.keys()) == _INT8_KEYS


def quantize_tree(params: PyTree, weight_dtype: str) -> PyTree:
    """Quantize a HOST param tree for storage (export side).

    Weight-only, matmul-applied tensors only: float32 leaves of
    ndim >= 2 (kernels, embeddings).  Biases, norms and other 1-D
    state stay f32 — their bytes are noise and their precision is not.

    * ``bf16``: the wire-v2 discipline at rest — bfloat16 keeps f32's
      exponent range, costs 16 of 24 mantissa bits (error-bound pinned
      in tests/test_decode.py).
    * ``int8``: symmetric per-output-channel scale (amax over all axes
      but the last / 127); dequantized as ``data * scale`` either at
      load or inside the jitted step (``dequantize_tree``).
    """
    if weight_dtype not in WEIGHT_DTYPES:
        raise ValueError(f"weight_dtype must be one of {WEIGHT_DTYPES}, "
                         f"got {weight_dtype!r}")
    if weight_dtype == "f32":
        return params
    if BF16 is None:  # pragma: no cover - ml_dtypes ships with jax
        raise RuntimeError("quantized exports need ml_dtypes")

    def q(leaf):
        a = np.asarray(leaf)
        if a.dtype != np.float32 or a.ndim < 2:
            return a
        if weight_dtype == "bf16":
            return a.astype(BF16)
        amax = np.max(np.abs(a), axis=tuple(range(a.ndim - 1)),
                      keepdims=True)
        scale = (np.where(amax > 0, amax, 1.0) / 127.0).astype(
            np.float32)
        data = np.clip(np.rint(a / scale), -127, 127).astype(np.int8)
        return {"int8_data": data, "int8_scale": scale}

    return jax.tree.map(q, params)


def dequantize_tree(tree: PyTree, upcast_bf16: bool = False) -> PyTree:
    """Collapse quantized nodes back to float arrays.

    jit-safe (pure ``astype``/multiply — the decode session calls it
    INSIDE the traced step so int8 weights stay int8 on device,
    docs/SERVING.md).  ``upcast_bf16=True`` additionally converts
    bf16-stored leaves to f32 — the dequantize-ON-LOAD path
    (``load_export`` default), restoring exactly what a non-quantized
    session expects.
    """
    if is_quantized_leaf(tree):
        return tree["int8_data"].astype("float32") * tree["int8_scale"]
    if isinstance(tree, dict):
        return {k: dequantize_tree(v, upcast_bf16)
                for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return type(tree)(dequantize_tree(v, upcast_bf16)
                          for v in tree)
    if upcast_bf16 and BF16 is not None \
            and getattr(tree, "dtype", None) == BF16:
        return np.asarray(tree, np.float32)
    return tree


def export_incompatibility(live_meta: dict, new_meta: dict) -> str | None:
    """Why a newly published export must NOT be hot-swapped into a
    server currently serving ``live_meta`` — None when compatible.
    The refusal contract the reload watcher enforces (typed
    :class:`IncompatibleExport`, docs/SERVING.md)."""
    for key in ("modelfile", "modelclass"):
        if live_meta.get(key) != new_meta.get(key):
            return (f"{key} changed "
                    f"{live_meta.get(key)!r} -> {new_meta.get(key)!r}")
    if list(live_meta.get("sample_shape") or []) != \
            list(new_meta.get("sample_shape") or []):
        return (f"sample_shape changed "
                f"{live_meta.get('sample_shape')} -> "
                f"{new_meta.get('sample_shape')}")
    if (live_meta.get("net") or {}) != (new_meta.get("net") or {}):
        # constructor dims (the transformer family's vocab/layers/
        # d_model/heads): a resized export's arrays cannot adopt into
        # sessions built around the live module — swapping it in would
        # crash-loop every replica, the exact failure refusal exists
        # to prevent
        return (f"net dims changed {live_meta.get('net')} -> "
                f"{new_meta.get('net')}")
    live_wd = live_meta.get("weight_dtype") or "f32"
    new_wd = new_meta.get("weight_dtype") or "f32"
    if live_wd != new_wd:
        return (f"weight_dtype changed {live_wd!r} -> {new_wd!r} "
                "(a live replica's compiled programs and memory plan "
                "assume the serving dtype; restart the server to "
                "change it)")
    if bool(live_meta.get("decode")) != bool(new_meta.get("decode")):
        return ("decode capability changed "
                f"{bool(live_meta.get('decode'))} -> "
                f"{bool(new_meta.get('decode'))}")
    return None


def draft_incompatibility(target_meta: dict,
                          draft_meta: dict) -> str | None:
    """Why a draft export must NOT speculate for a live target — None
    when compatible.  The draft's DIMS are free (a smaller net is the
    whole point); what must agree is the token space and the
    positional range, because the target verifies draft TOKENS, not
    draft activations:

    * ``decode`` capability — the draft runs the same decode plane;
    * ``vocab`` — a draft emitting ids the target never trained on
      (or missing ids it would propose) breaks the accept comparison;
    * the positional table must cover the target's — a draft that
      clamps positions earlier than the target silently degrades
      accept rate deep into long streams, so it is refused loudly.

    Enforced at replica construction AND by the reload watcher's
    draft poll (typed :class:`IncompatibleExport`, remembered like
    every refused publish — server keeps serving)."""
    if not draft_meta.get("decode"):
        return "draft export is not decode-capable"
    t_net = target_meta.get("net") or {}
    d_net = draft_meta.get("net") or {}
    if t_net.get("vocab") != d_net.get("vocab"):
        return (f"draft vocab {d_net.get('vocab')} != target vocab "
                f"{t_net.get('vocab')}")
    # TransformerLM's positional table: max(2048, seq_len)
    t_max = max(2048, int(t_net.get("seq_len") or 0))
    d_max = max(2048, int(d_net.get("seq_len") or 0))
    if d_max < t_max:
        return (f"draft positional table {d_max} shorter than the "
                f"target's {t_max}")
    return None


def _host(tree: PyTree) -> PyTree:
    return jax.tree.map(np.asarray, jax.device_get(tree))


def _sample_dtype(model) -> str:
    """The dtype requests arrive in — the dataset's raw row dtype when
    it ships one (uint8 under device-side augment), else the model's
    declared input dtype."""
    xv = getattr(model.data, "x_val", None)
    if xv is not None:
        return str(np.asarray(xv[:0]).dtype)
    return str(np.dtype(model._input_dtype()))


def export_model(model, export_dir: str, version: int | None = None,
                 max_to_keep: int = 5, weight_dtype: str = "f32") -> int:
    """Write one export version from a live model; returns the version.

    ``version`` defaults to the model's current epoch.  Re-exporting
    an existing version is refused (Orbax would silently skip the
    write, blessing stale files under a new manifest) — bump the
    version instead; the serving reload protocol is strictly
    monotonic.

    ``weight_dtype`` selects the stored precision of matmul-applied
    weights (``quantize_tree``): 'bf16' halves and 'int8' quarters the
    artifact and (with on-the-fly dequant) device bytes — the
    replicas-per-chip lever.  The dtype is recorded in the meta
    sidecar; a live server refuses to hot-swap across a dtype change
    (``export_incompatibility``)."""
    if version is None:
        version = int(model.current_epoch)
    version = int(version)
    payload = {"params": quantize_tree(_host(model.state.params),
                                       weight_dtype),
               "model_state": _host(model.state.model_state)}
    # sync save: when export_model returns, files AND manifest are on
    # disk — the atomic publish a watching server's poll keys off
    ckpt = Checkpointer(export_dir, max_to_keep=max_to_keep,
                        async_save=False)
    try:
        if version in ckpt.kept_epochs():
            raise ValueError(
                f"export version {version} already exists in "
                f"{export_dir}; versions are immutable — export the "
                "next one")
        ckpt.save(version, payload)
        kept = ckpt.kept_epochs()
    finally:
        ckpt.close()
    meta = {
        "version": version,
        "name": model.name,
        "modelfile": type(model).__module__,
        "modelclass": type(model).__qualname__,
        "config": dataclasses.asdict(model.config),
        "sample_shape": list(model.data.sample_shape),
        "sample_dtype": _sample_dtype(model),
        "n_classes": getattr(model.data, "n_classes", None),
        # constructor kwargs beyond ModelConfig (the transformer
        # family's vocab/seq_len/layers/dims) — without these a
        # CLI-resized export would rebuild at DEFAULT dims and fail to
        # adopt the restored arrays
        "net": getattr(model, "_net_cfg", None),
        "weight_dtype": weight_dtype,
        # decode capability: may this export serve the autoregressive
        # path (theanompi_tpu/decode)?  The hot-reload watcher refuses
        # to swap a capability change into a live replica
        "decode": bool(getattr(model, "decode_capable", False)),
        "created": time.time(),
    }
    path = meta_path(export_dir, version)
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, path)
    # prune metas of versions max_to_keep dropped (mirrors
    # recovery.prune_manifests)
    import glob
    import re

    for p in glob.glob(os.path.join(export_dir, "export_meta_*.json")):
        m = re.search(r"export_meta_(\d+)\.json$", p)
        if m and int(m.group(1)) not in kept:
            try:
                os.unlink(p)
            except OSError:
                pass
    return version


def latest_export_version(export_dir: str) -> int | None:
    """Digest-free poll hint for the reload watcher: the newest version
    whose MANIFEST and META sidecar are BOTH on disk.  export_model
    writes checkpoint files, then manifest, then meta — so the meta is
    the completed-publish marker; a manifest alone means the exporter
    died (or is still) mid-publish and the version must not be
    offered to the reload watcher yet.  Full verification happens at
    actual load."""
    import glob
    import re

    from theanompi_tpu.resilience.recovery import manifest_path

    if not os.path.isdir(export_dir):
        return None
    best = None
    for p in glob.glob(os.path.join(export_dir, "export_meta_*.json")):
        m = re.search(r"export_meta_(\d+)\.json$", p)
        if not m:
            continue
        v = int(m.group(1))
        if os.path.exists(manifest_path(export_dir, v)):
            best = v if best is None else max(best, v)
    return best


@dataclasses.dataclass
class LoadedExport:
    version: int
    params: PyTree
    model_state: dict
    meta: dict


def load_export(export_dir: str, version: int | None = None,
                dequantize: bool = True) -> LoadedExport:
    """Read-only verified load (newest verified version by default).

    ``dequantize=True`` (default) collapses any stored bf16/int8
    weights back to f32 — callers see the same tree regardless of the
    export's ``weight_dtype``.  Pass ``False`` to keep the quantized
    leaves (``{int8_data, int8_scale}`` nodes / bf16 arrays) for
    on-the-fly dequantization inside a jitted step
    (``dequantize_tree``), which keeps device memory at the quantized
    footprint."""
    from theanompi_tpu.resilience.recovery import verify_checkpoint

    ckpt = Checkpointer(export_dir, read_only=True)
    try:
        if version is None:
            v, payload = ckpt.restore_latest_verified()
            if v is None:
                raise FileNotFoundError(
                    f"no restorable export in {export_dir}")
            if not os.path.exists(meta_path(export_dir, v)):
                # the exporter died between the checkpoint publish and
                # the meta-sidecar write: the arrays restore but the
                # loader cannot rebuild a model around them.  The
                # directory contract says a half-published newest
                # version costs a fallback, never the server — walk
                # the older versions that DID finish publishing.
                for e in sorted(ckpt.kept_epochs(), reverse=True):
                    if (e >= v or not
                            os.path.exists(meta_path(export_dir, e))):
                        continue
                    if verify_checkpoint(export_dir, e)[0] is False:
                        continue
                    try:
                        v, payload = e, ckpt.restore(e)
                        break
                    except Exception:
                        continue
                else:
                    raise FileNotFoundError(
                        f"newest restorable export v{v} in "
                        f"{export_dir} has no meta sidecar and no "
                        "older fully-published version exists")
        else:
            v, payload = int(version), ckpt.restore(int(version))
    finally:
        ckpt.close()
    meta = {}
    mp = meta_path(export_dir, v)
    if os.path.exists(mp):
        with open(mp) as f:
            meta = json.load(f)
    params = payload["params"]
    if dequantize:
        params = dequantize_tree(params, upcast_bf16=True)
    return LoadedExport(int(v), params,
                        payload.get("model_state") or {}, meta)


def build_model_from_meta(meta: dict, mesh=None):
    """Reconstruct the exported model (module + config threading —
    ``bn_act_impl``, ``pool_impl``, dtypes) around restored arrays.
    JSON round-trips ModelConfig's tuple fields as lists; they are
    re-tupled here so the rebuilt config equals the exporter's."""
    from theanompi_tpu.models.base import ModelConfig
    from theanompi_tpu.rules.base import resolve_model_class

    cls = resolve_model_class(meta["modelfile"], meta["modelclass"])
    fields = {f.name: f for f in dataclasses.fields(ModelConfig)}
    kw = {}
    for k, v in (meta.get("config") or {}).items():
        if k not in fields:
            continue  # a field a newer exporter knew and we don't
        kw[k] = tuple(v) if isinstance(v, list) else v
    # net kwargs: the transformer family's constructor dims (vocab,
    # seq_len, n_layers, ...) — absent for the CNN zoo
    net = meta.get("net") or {}
    return cls(config=ModelConfig(**kw), mesh=mesh, verbose=False,
               **net)


class InferenceSession:
    """One jitted eval-mode inference fn over swappable arrays.

    The compiled fn takes ``(params, model_state, x)`` — params and
    stats as ARGUMENTS, not captured constants, so a hot reload swaps
    arrays without recompiling (shapes are fixed by the export).  The
    input ``x`` is DONATED: the batcher stages a fresh padded batch
    per call, so XLA may reuse its buffer for the logits
    (tests/test_serving.py pins the aliasing in the lowering).

    ``swap``/``infer`` synchronize by publishing one tuple attribute:
    readers snapshot ``(version, params, model_state)`` in a single
    reference read, so an in-flight batch finishes entirely on the
    arrays it started with while the next batch picks up the new ones
    — the zero-dropped-requests half of the reload protocol
    (docs/SERVING.md)."""

    def __init__(self, model, params: PyTree | None = None,
                 model_state: dict | None = None, version: int = 0,
                 donate: bool = True):
        self.model = model
        self.module = model.module
        self._transform = getattr(model.data, "device_transform", None)
        params = params if params is not None else model.state.params
        ms = (model_state if model_state is not None
              else model.state.model_state)
        self._live = (int(version), self._place(params), self._place(ms))
        self._swap_lock = threading.Lock()
        self._jit = jax.jit(
            self._infer_fn, donate_argnums=(2,) if donate else ())

    @staticmethod
    def _place(tree: PyTree) -> PyTree:
        return jax.tree.map(jnp.asarray, tree)

    @property
    def version(self) -> int:
        return self._live[0]

    def _infer_fn(self, params, model_state, x):
        if self._transform is not None:
            # the dataset's EVAL transform (center crop / normalize) —
            # requests ship rows exactly as val batches do
            x = self._transform(x, None, train=False)
        variables = {"params": params, **model_state}
        logits = self.module.apply(variables, x, train=False)
        if isinstance(logits, (tuple, list)):  # aux heads (GoogLeNet)
            logits = logits[0]
        return logits.astype(jnp.float32)

    def infer(self, x) -> np.ndarray:
        version, params, ms = self._live  # one-read snapshot
        out = self._jit(params, ms, jnp.asarray(x))
        return np.asarray(jax.device_get(out))

    def swap(self, version: int, params: PyTree,
             model_state: dict) -> bool:
        """Publish a new model version (host or device trees); the
        next ``infer`` snapshot picks it up, in-flight calls finish on
        the old one.  MONOTONIC: a swap to an OLDER version than the
        live one is refused (returns False) — a replica restart that
        loaded the export while a concurrent hot reload published a
        newer version must not roll the replica back; the reload's
        arrays are themselves a fresh verified load, so the restart's
        known-good-bytes goal is already met.  Same-version swaps are
        allowed (that IS the restart: fresh bytes of what we serve)."""
        with self._swap_lock:
            if int(version) < self._live[0]:
                return False
            self._live = (int(version), self._place(params),
                          self._place(model_state))
            return True

    @classmethod
    def from_export(cls, export_dir: str, version: int | None = None,
                    mesh=None, donate: bool = True) -> "InferenceSession":
        loaded = load_export(export_dir, version)
        model = build_model_from_meta(loaded.meta, mesh=mesh)
        return cls(model, params=loaded.params,
                   model_state=loaded.model_state,
                   version=loaded.version, donate=donate)
