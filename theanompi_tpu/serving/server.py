"""Multi-replica inference server + wire client.

The transport is the shared RPC substrate (``parallel/rpc.py``) —
selector event loop, HMAC auth with a handshake deadline (NO default
key; ``THEANOMPI_TPU_SERVICE_KEY`` gates both ends), negotiated
wire-v2 framing, typed error names riding the ``err`` reply prefix —
so everything learned on the param service (reconnect-with-backoff
clients, fast-failing server errors) carries over to serving.

Topology: one :class:`InferenceServer` owns N :class:`Replica`\\ s.
Each replica is an :class:`~theanompi_tpu.serving.export.InferenceSession`
(its own jitted eval fn — on real hardware each would pin its own
device) behind its own :class:`~theanompi_tpu.serving.batcher.DynamicBatcher`
queue.  Requests round-robin over live replicas with overflow
failover; when EVERY live replica's queue is full the request is
rejected with :class:`Overloaded` — bounded queues, bounded latency
(docs/SERVING.md).

Resilience wiring: ``serve_rpc`` (per-request, in the connection
handler) and ``serve_step`` (per-batch, in the replica) are fault
sites (resilience.faults).  A batch-execution failure fails that
batch's requests, then the replica is RESTARTED FROM THE EXPORT — a
fresh verified load of the current version — up to ``max_restarts``
times, after which the replica is lost and traffic routes around it
(the quorum analogue: a server with zero live replicas rejects, it
does not crash).

Hot reload: a watcher polls the export directory for a newer version
(meta-sidecar presence = completed publish); a new one is VERIFIED-loaded
once and swapped into every replica atomically — in-flight batches
finish on the old arrays, zero requests dropped
(tests/test_serving.py pins this).
"""

from __future__ import annotations

import argparse
import os
import threading
import time
from typing import Any

import numpy as np

from theanompi_tpu import monitor
from theanompi_tpu.analysis.lockgraph import make_lock
from theanompi_tpu.decode.migrate import IncompatiblePages
from theanompi_tpu.parallel import rpc, wire
from theanompi_tpu.resilience import faults
from theanompi_tpu.serving.batcher import (
    BatchPolicy,
    DynamicBatcher,
    Overloaded,
)
from theanompi_tpu.serving.export import (
    IncompatibleExport,
    InferenceSession,
    build_model_from_meta,
    draft_incompatibility,
    export_incompatibility,
    latest_export_version,
    load_export,
)

PyTree = Any

#: default port one above the param service's 45800 block
DEFAULT_PORT = 45900


class Replica:
    """One inference session + batcher under restart supervision."""

    def __init__(self, idx: int, export_dir: str, policy: BatchPolicy,
                 loaded, model, max_restarts: int = 2,
                 donate: bool = True):
        self.idx = int(idx)
        self.export_dir = export_dir
        self.max_restarts = int(max_restarts)
        self.restarts = 0
        self._steps = 0
        self.session = InferenceSession(
            model, params=loaded.params, model_state=loaded.model_state,
            version=loaded.version, donate=donate)
        self.batcher = DynamicBatcher(
            self._run_batch, policy, replica=self.idx,
            on_batch_error=self._on_batch_error)

    @property
    def alive(self) -> bool:
        return self.batcher.alive

    def submit(self, x: np.ndarray) -> np.ndarray:
        return self.batcher.submit(x)

    def _run_batch(self, x: np.ndarray) -> np.ndarray:
        self._steps += 1
        faults.fire("serve_step", replica=self.idx, step=self._steps)
        return self.session.infer(x)

    def _on_batch_error(self, exc: BaseException) -> bool:
        """Supervised recovery (resilience, docs/SERVING.md): reload
        this replica's arrays from the export — a fresh read of THE
        VERSION BEING SERVED, so a batch failure caused by in-memory
        corruption starts over from known-good bytes.  Pinning the
        version matters: loading "newest" here would silently swap in
        a just-published export the reload watcher may have REFUSED as
        incompatible (weight dtype / net dims) — upgrades go through
        `check_reload`'s compatibility gate, never through a crash.
        Returns False (replica lost) once the budget is spent."""
        self.restarts += 1
        monitor.inc("serving/replica_restarts_total", replica=self.idx)
        if self.restarts > self.max_restarts:
            print(f"[serving] replica {self.idx} exhausted "
                  f"{self.max_restarts} restarts "
                  f"({type(exc).__name__}: {exc}); marking it lost",
                  flush=True)
            return False
        try:
            loaded = load_export(self.export_dir,
                                 version=self.session.version)
        except Exception as e:
            print(f"[serving] replica {self.idx} restart-from-export "
                  f"failed ({type(e).__name__}: {e}); marking it lost",
                  flush=True)
            return False
        swapped = self.session.swap(loaded.version, loaded.params,
                                    loaded.model_state)
        print(f"[serving] replica {self.idx} restarted "
              + (f"from export v{loaded.version}" if swapped else
                 f"on v{self.session.version} (a concurrent hot "
                 f"reload superseded the v{loaded.version} load)")
              + f" after {type(exc).__name__} "
              f"(restart {self.restarts}/{self.max_restarts})",
              flush=True)
        return True

    def swap(self, version: int, params, model_state) -> None:
        self.session.swap(version, params, model_state)


class InferenceServer:
    """Replica pool + admission + hot reload (module docstring)."""

    def __init__(self, export_dir: str, replicas: int = 1,
                 policy: BatchPolicy | None = None,
                 max_restarts: int = 2, reload_poll_s: float = 1.0,
                 warmup: bool = True, mesh=None, donate: bool = True,
                 model=None, decode: bool = False,
                 decode_opts: dict | None = None):
        if replicas < 1:
            raise ValueError(f"need >= 1 replica, got {replicas}")
        self.export_dir = os.path.abspath(export_dir)
        self.policy = policy or BatchPolicy()
        self.reload_poll_s = float(reload_poll_s)
        self.decode = bool(decode)
        loaded = load_export(self.export_dir)
        # ONE model rebuild (module + config threading) shared by all
        # replicas; each replica jits its own fn over the shared
        # module.  ``model=`` skips the rebuild when the caller (a
        # test, an embedded exporter-server) already holds the
        # instance — the ARRAYS still come from the verified export.
        self.model = (model if model is not None
                      else build_model_from_meta(loaded.meta, mesh=mesh))
        self.version = loaded.version        # guarded_by: self._reload_lock
        #: meta of the version being served — the hot-reload
        #: compatibility anchor (export_incompatibility)
        self._meta = loaded.meta             # guarded_by: self._reload_lock
        self.draft_export_dir = None
        self.draft_version = None            # guarded_by: self._reload_lock
        self._draft_meta = None              # guarded_by: self._reload_lock
        if self.decode:
            # autoregressive mode (theanompi_tpu/decode): replicas are
            # DecodeReplicas (paged KV-cache + continuous batcher) and
            # the wire surface is the 'generate' op
            if not loaded.meta.get("decode"):
                raise ValueError(
                    "decode mode needs a decode-capable export "
                    "(TransformerLM family; export_meta 'decode' is "
                    f"false/absent in {self.export_dir})")
            from theanompi_tpu.decode import DecodePolicy, DecodeReplica

            opts = dict(decode_opts or {})
            pol_kw = {k: opts.pop(k)
                      for k in ("max_pending", "max_new_cap",
                                "submit_timeout_s", "eos_token",
                                "speculate_k", "prefill_batch",
                                "prefill_delay_ms")
                      if k in opts}
            self.replicas = [
                DecodeReplica(i, self.export_dir, self.model, loaded,
                              policy=DecodePolicy(**pol_kw),
                              max_restarts=max_restarts, donate=donate,
                              **opts)
                for i in range(int(replicas))
            ]
            #: draft-export watcher state (speculative decoding): the
            #: replicas validated + loaded the draft at construction;
            #: the watcher polls its dir like the target's
            self.draft_export_dir = (
                os.path.abspath(opts["draft_export_dir"])
                if opts.get("draft_export_dir") else None)
            r0 = self.replicas[0]
            self.draft_version = (            # guarded_by: self._reload_lock
                r0.draft_session.version
                if r0.draft_session is not None else None)
            self._draft_meta = r0.draft_meta  # guarded_by: self._reload_lock
            if warmup:
                for r in self.replicas:
                    r.warmup()
        else:
            self.replicas = [
                Replica(i, self.export_dir, self.policy, loaded,
                        self.model, max_restarts=max_restarts,
                        donate=donate)
                for i in range(int(replicas))
            ]
            if warmup:
                shape = tuple(loaded.meta.get("sample_shape")
                              or self.model.data.sample_shape)
                dtype = np.dtype(loaded.meta.get("sample_dtype") or
                                 np.float32)
                for r in self.replicas:
                    # fn=session.infer: warmup compiles the same jitted
                    # fn but skips the serve_step fault site — a fault
                    # plan must take down served batches (supervised
                    # restart), not construction before the port is
                    # bound
                    r.batcher.warmup(shape, dtype, fn=r.session.infer)
        self._rr_lock = make_lock("InferenceServer._rr_lock")
        self._rr = 0                          # guarded_by: self._rr_lock
        self._stop = threading.Event()
        self._watcher: threading.Thread | None = None
        self._reload_lock = make_lock("InferenceServer._reload_lock")
        #: newest published version that failed verification or was
        #: refused as incompatible — not re-LOADED by the reload poll
        #: until a strictly newer one appears
        self._bad_newest: int | None = None  # guarded_by: self._reload_lock
        #: refusal reason when _bad_newest was an IncompatibleExport:
        #: re-raised (from memory, no disk load) on every further
        #: reload of that version, so a client's reload() RPC gets the
        #: typed error regardless of whether the background watcher
        #: observed the publish first
        self._bad_reason: str | None = None  # guarded_by: self._reload_lock
        #: same memory for the DRAFT export's poll (speculative
        #: decoding): a published draft whose dims/vocab are
        #: incompatible with the live target is refused once, loudly,
        #: and remembered until a strictly newer draft publish
        self._bad_draft_newest: int | None = None  # guarded_by: self._reload_lock
        self._bad_draft_reason: str | None = None  # guarded_by: self._reload_lock
        monitor.set_gauge("serving/model_version", self.version)
        monitor.set_gauge("serving/replicas", len(self.replicas))

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "InferenceServer":
        for r in self.replicas:
            r.batcher.start()
        if self.reload_poll_s > 0:
            self._watcher = threading.Thread(
                target=self._watch_reload, daemon=True,
                name="serving-reload-watcher")
            self._watcher.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        for r in self.replicas:
            r.batcher.stop()
        if self._watcher is not None:
            self._watcher.join(timeout=5)

    # -- request path --------------------------------------------------

    def _route(self, fn_name: str, *args):
        """Round-robin one request over live replicas with overflow
        failover; Overloaded only when EVERY live replica rejects."""
        n = len(self.replicas)
        with self._rr_lock:
            start = self._rr
            self._rr = (self._rr + 1) % n
        last: Overloaded | None = None
        any_alive = False
        for k in range(n):
            r = self.replicas[(start + k) % n]
            if not r.alive:
                continue
            any_alive = True
            try:
                return getattr(r, fn_name)(*args)
            except Overloaded as e:
                last = e
        if not any_alive:
            raise Overloaded("no live replicas (all lost); the server "
                             "needs a restart or a good export")
        raise last if last is not None else Overloaded("rejected")

    def submit(self, x: np.ndarray) -> np.ndarray:
        """Route one eval request to a live replica."""
        if self.decode:
            raise ValueError("this server runs decode mode; use the "
                             "'generate' op (InferenceClient.generate)")
        return self._route("submit", x)

    def generate(self, prompt: np.ndarray,
                 max_new: int | None = None):
        """Route one token-generation request to a live decode
        replica; returns the generated token ids (int32) — or a
        :class:`~theanompi_tpu.decode.scheduler.MigratedStream` when
        the replica drained mid-stream (scale-down)."""
        if not self.decode:
            raise ValueError("this server runs eval mode; start it "
                             "with decode=True (tmlocal SERVE "
                             "--decode) for the generate op")
        out = self._route("generate", prompt, max_new)
        if not isinstance(out, (list, np.ndarray)):
            return out  # MigratedStream
        return np.asarray(out, np.int32)

    def generate_adopted(self, manifest: dict, k, v,
                         max_new: int | None = None):
        """Route one MIGRATED stream (decode/migrate.py: a prefill
        replica's pages + manifest) to a live decode replica, which
        adopts the pages and decodes from there.  A geometry mismatch
        raises the typed :class:`IncompatiblePages` straight through
        ``_route`` — a per-stream refusal, never a replica failure."""
        if not self.decode:
            raise ValueError("this server runs eval mode; start it "
                             "with decode=True (tmlocal SERVE "
                             "--decode) for the adopt op")
        out = self._route("generate_adopted", manifest,
                          np.asarray(k), np.asarray(v), max_new)
        if not isinstance(out, (list, np.ndarray)):
            return out  # MigratedStream
        return np.asarray(out, np.int32)

    def drain_migrate(self) -> int:
        """Scale-down hand-off: every decode replica stops admitting
        (Overloaded) and exports its live streams as MigratedStream
        payloads at the next step boundary (the autoscaler's decode
        scale-down path — docs/SERVING.md).  Returns the replica
        count told to drain."""
        if not self.decode:
            raise ValueError("drain_migrate is a decode-mode op")
        for r in self.replicas:
            r.drain_migrate()
        return len(self.replicas)

    # -- hot reload ----------------------------------------------------

    def check_reload(self) -> int:
        """One poll: load + swap if a newer version is published;
        returns the serving version either way.  Safe to call
        concurrently (watcher + the ``reload`` RPC)."""
        with self._reload_lock:
            newest = latest_export_version(self.export_dir)
            if newest is None or newest <= self.version:
                return self.version
            if newest == self._bad_newest:
                if self._bad_reason is not None:
                    # a REFUSED (not corrupt) publish: every reload of
                    # it re-raises the typed error from memory, so the
                    # refusal is observable however the poll race with
                    # the watcher went
                    raise IncompatibleExport(self._bad_reason)
                return self.version
            loaded = load_export(self.export_dir)
            if loaded.version <= self.version:
                # the newest manifest is on disk but its files did not
                # verify (restore_latest_verified fell back, possibly
                # to what we already serve).  Versions are immutable
                # (export_model refuses re-export), so retrying the
                # same corrupt version every poll is pure disk/CPU
                # churn — remember it and wait for a strictly newer
                # manifest to reset the skip.
                self._bad_newest = newest
                self._bad_reason = None
                return self.version
            reason = export_incompatibility(self._meta, loaded.meta)
            if reason is not None:
                # refusal, not a crash: the export verified but must
                # not be swapped into live replicas (different model /
                # sample shape / net dims / weight dtype / decode
                # capability).  Remember it like a corrupt newest so
                # the poll loop does not re-LOAD it every interval —
                # but keep the reason, so every reload of this version
                # still surfaces the typed error; a strictly newer
                # publish resets the skip.
                self._bad_newest = newest
                self._bad_reason = (f"refusing hot reload "
                                    f"v{self.version} -> "
                                    f"v{loaded.version}: {reason}")
                monitor.inc("serving/reload_refused_total")
                print(f"[serving] {self._bad_reason}", flush=True)
                raise IncompatibleExport(self._bad_reason)
            self._bad_newest = None
            self._bad_reason = None
            for r in self.replicas:
                r.swap(loaded.version, loaded.params,
                       loaded.model_state)
            self._meta = loaded.meta
            old, self.version = self.version, loaded.version
            monitor.set_gauge("serving/model_version", self.version)
            monitor.inc("serving/reloads_total")
            print(f"[serving] hot reload v{old} -> v{self.version} "
                  f"({len(self.replicas)} replicas, in-flight "
                  "requests kept)", flush=True)
            return self.version

    def check_draft_reload(self) -> int | None:
        """One poll of the DRAFT export dir (speculative decoding):
        load + swap a newer compatible draft into every replica;
        returns the serving draft version (None when speculation is
        off).  A draft whose dims/vocab no longer fit the live target
        raises the typed :class:`IncompatibleExport` — refused and
        REMEMBERED exactly like a refused target publish (no re-load
        churn, every reload re-raises from memory, the server keeps
        serving and keeps speculating on the old draft) until a
        strictly newer draft version supersedes it."""
        if not self.decode or self.draft_export_dir is None:
            return None
        with self._reload_lock:
            newest = latest_export_version(self.draft_export_dir)
            if newest is None or newest <= self.draft_version:
                return self.draft_version
            if newest == self._bad_draft_newest:
                if self._bad_draft_reason is not None:
                    raise IncompatibleExport(self._bad_draft_reason)
                return self.draft_version
            loaded = load_export(self.draft_export_dir)
            if loaded.version <= self.draft_version:
                # newest manifest failed verification; fell back —
                # remember like the target poll does
                self._bad_draft_newest = newest
                self._bad_draft_reason = None
                return self.draft_version
            # two anchors: the live TARGET (vocab/positional range —
            # the accept comparison) and the live DRAFT session (net
            # dims etc. — the new arrays must adopt into the compiled
            # draft programs, the same reason target hot reload
            # refuses a resized net; restart to change draft dims)
            reason = (draft_incompatibility(self._meta, loaded.meta)
                      or export_incompatibility(self._draft_meta,
                                                loaded.meta))
            if reason is not None:
                self._bad_draft_newest = newest
                self._bad_draft_reason = (
                    f"refusing draft hot reload v{self.draft_version} "
                    f"-> v{loaded.version}: {reason}")
                monitor.inc("serving/reload_refused_total")
                print(f"[serving] {self._bad_draft_reason}", flush=True)
                raise IncompatibleExport(self._bad_draft_reason)
            self._bad_draft_newest = None
            self._bad_draft_reason = None
            swapped = sum(1 for r in self.replicas
                          if r.swap_draft(loaded.version,
                                          loaded.params))
            if swapped == 0:
                # every replica downgraded to plain decode (failed
                # draft restarts): there is no draft session to swap
                # into, and claiming a reload would advertise a draft
                # version nobody serves — restart to re-enable
                print(f"[serving] draft v{loaded.version} published "
                      "but speculation is disabled on every replica "
                      "(failed draft restarts); not swapped — restart "
                      "the server to re-enable speculation",
                      flush=True)
                return self.draft_version
            self._draft_meta = loaded.meta
            old, self.draft_version = self.draft_version, loaded.version
            monitor.inc("serving/reloads_total")
            print(f"[serving] draft hot reload v{old} -> "
                  f"v{self.draft_version} ({swapped}/"
                  f"{len(self.replicas)} replicas speculating, "
                  "in-flight streams kept)", flush=True)
            return self.draft_version

    def _watch_reload(self) -> None:
        while not self._stop.wait(self.reload_poll_s):
            for check in (self.check_reload, self.check_draft_reload):
                try:
                    check()
                except IncompatibleExport:
                    # already printed once at refusal time; the
                    # remembered refusal re-raises every poll until
                    # superseded, and re-printing it each second is
                    # pure log spam
                    pass
                except Exception as e:
                    # a broken half-published export must not kill the
                    # watcher; next poll retries
                    print(f"[serving] reload check failed: "
                          f"{type(e).__name__}: {e}", flush=True)

    # -- introspection -------------------------------------------------

    def stats(self) -> dict:
        # TM101 regression: the serving version is hot-reload state —
        # replica stats AND the version are read under the reload lock
        # so a concurrent swap cannot pair a new version with stats
        # from the other side of it.  Cost: a stats() issued DURING a
        # reload blocks until the verified load finishes — truthful,
        # and only as long as the reload itself.
        with self._reload_lock:
            reps = [dict(r.batcher.stats(), restarts=r.restarts,
                         version=r.session.version)
                    for r in self.replicas]
            version = self.version
            draft_version = self.draft_version
        out = {
            "version": version,
            "decode": self.decode,
            "replicas": reps,
            "overloaded": sum(r.get("overloaded", 0) for r in reps),
            "live_replicas": sum(1 for r in self.replicas if r.alive),
        }
        if self.decode:
            # decode replicas account tokens/steps, not batches/rows
            drafted = sum((r.get("speculation") or {})
                          .get("draft_tokens", 0) for r in reps)
            accepted = sum((r.get("speculation") or {})
                           .get("accepted_draft_tokens", 0)
                           for r in reps)
            out.update(
                tokens=sum(r.get("tokens", 0) for r in reps),
                steps=sum(r.get("steps", 0) for r in reps),
                shared_steps=sum(r.get("shared_steps", 0)
                                 for r in reps),
                max_concurrent=max((r.get("max_concurrent", 0)
                                    for r in reps), default=0),
                draft_version=draft_version,
                draft_tokens=drafted,
                accepted_draft_tokens=accepted,
                accept_rate=accepted / drafted if drafted else None,
                prefix_cache_hits=sum(
                    (r.get("prefix_cache") or {}).get("hits", 0)
                    for r in reps),
            )
        else:
            out.update(
                batches=sum(r.get("batches", 0) for r in reps),
                rows=sum(r.get("rows", 0) for r in reps),
                max_occupancy=max((r.get("max_occupancy", 0)
                                   for r in reps), default=0),
            )
        return out

    # -- wire dispatch ---------------------------------------------------

    def rpc_max_workers(self) -> int:
        """Executor width for the RPC substrate: enough workers that
        every admissible request (the batchers' bounded queues + one
        executing batch per replica) can block in a handler
        concurrently, plus slack so O(1) ``Overloaded`` rejections
        never queue behind parked handlers."""
        n = len(self.replicas)
        if self.decode:
            per = max((getattr(r.batcher.policy, "max_pending", 32)
                       + getattr(r.session.cfg, "max_seqs", 8))
                      for r in self.replicas)
        else:
            per = self.policy.max_queue + self.policy.max_batch
        return n * per + 8

    @staticmethod
    def _wire_tokens(out):
        """Wire encoding for a generate/adopt result: a token array,
        or a drained stream's pages as a tagged tuple (the token ids
        can never collide with the tag — normal results are arrays)."""
        if isinstance(out, np.ndarray):
            return out
        # MigratedStream: partial tokens + manifest + pages
        return ("migrated", [int(t) for t in out.tokens], out.manifest,
                wire.RawArrays(np.asarray(out.k), np.asarray(out.v)))

    def handle(self, op: str, *args):
        if op == "infer":
            (x,) = args
            return self.submit(np.asarray(x))
        if op == "generate":
            prompt, max_new = args
            return self._wire_tokens(
                self.generate(np.asarray(prompt, np.int32),
                              None if max_new is None
                              else int(max_new)))
        if op == "adopt":
            # pages arrive as one RawArrays frame pair (decoded to a
            # plain (k, v) tuple by the wire) + the page manifest
            manifest, pages, max_new = args
            k, v = pages
            return self._wire_tokens(
                self.generate_adopted(manifest, k, v,
                                      None if max_new is None
                                      else int(max_new)))
        if op == "drain":
            return self.drain_migrate()
        if op == "stats":
            return self.stats()
        if op == "reload":
            # target first, then the draft poll — either refusal
            # surfaces as the typed IncompatibleExport (a successful
            # target swap is already committed when a draft refusal
            # raises; the next reload returns the new version)
            version = self.check_reload()
            self.check_draft_reload()
            return version
        if op == "ping":
            return "pong"
        raise ValueError(f"unknown op {op!r}")


class _ServingRpcHooks(rpc.RpcHooks):
    """The inference plane's seams into the shared RPC substrate
    (``parallel/rpc.py``): literal ``serving/*`` series names (the
    TM403/404 docs-coverage contract) and the ``serve_rpc`` fault
    site.  Migrating onto the substrate also bought this plane wire-v2
    framing — request/reply arrays now travel as zero-copy buffers
    instead of pickles — with clients unchanged
    (:class:`InferenceClient` always negotiated; the old loop just
    answered "unknown op")."""

    plane = "serving"

    def on_connect(self) -> None:
        monitor.add_gauge("serving/clients", 1.0)

    def on_disconnect(self) -> None:
        monitor.add_gauge("serving/clients", -1.0)

    def on_request(self, op: str, ms: float) -> None:
        monitor.inc("serving/requests_total", op=op)
        monitor.observe("serving/rpc_ms", ms, op=op)
        monitor.progress(phase="serving")

    def on_error(self, op: str) -> None:
        monitor.inc("serving/errors_total", op=op)

    def on_negotiate(self, opts) -> None:
        monitor.inc("serving/wire_negotiations_total",
                    compression=opts.compression, dtype=opts.dtype)

    def fire(self, op: str) -> None:
        # fault plane: 'raise' rejects this RPC (the client sees the
        # typed err), 'delay' adds latency — both exercised with the
        # server LIVE, which is the point
        faults.fire("serve_rpc", op=op)


def serve(server: InferenceServer, host: str = "0.0.0.0",
          port: int = DEFAULT_PORT,
          ready_event: threading.Event | None = None,
          stop_event: threading.Event | None = None,
          authkey: bytes | None = None,
          loop: str | None = None) -> None:
    """The shared RPC substrate over an :class:`InferenceServer` until
    a ``shutdown`` op or ``stop_event`` (``parallel/rpc.py``; same
    loops/knobs as every other plane).  The executor pool is sized by
    the plane's own admission bound — an ``infer``/``generate``
    handler legitimately blocks until its batch completes, and the
    batchers' bounded queues already cap how many can be in flight;
    past that bound requests get their O(1) typed ``Overloaded``."""
    from theanompi_tpu.parallel.service import _authkey

    if authkey is None:
        authkey = _authkey(generate=True)
    rpc.serve(server, host, port, ready_event=ready_event,
              stop_event=stop_event, authkey=authkey,
              hooks=_ServingRpcHooks(), loop=loop,
              max_workers=server.rpc_max_workers())


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------


from theanompi_tpu.parallel.service import ServiceClient, ServiceError


class InferenceClient(ServiceClient):
    """Wire client: transport failures reconnect-with-backoff
    (``infer`` is pure, so at-least-once is safe); server-side errors
    fail fast, with :class:`Overloaded` re-raised as its own type off
    the typed err-prefix (never retried by the transport — backoff
    or shed ABOVE the wire)."""

    def infer(self, x) -> np.ndarray:
        try:
            return self.call("infer", np.asarray(x))
        except ServiceError as e:
            if Overloaded.__name__ in str(e):
                raise Overloaded(str(e)) from None
            raise

    @staticmethod
    def _unwire_tokens(out):
        """Inverse of ``InferenceServer._wire_tokens``: token ids, or
        a drained stream's ``MigratedStream`` for the router to
        re-dispatch (frontdoor/router.py stitches the halves)."""
        if (isinstance(out, tuple) and len(out) == 4
                and out[0] == "migrated"):
            from theanompi_tpu.decode.scheduler import MigratedStream

            _, tokens, manifest, pages = out
            k, v = pages
            return MigratedStream([int(t) for t in tokens],
                                  manifest, k, v)
        return np.asarray(out, np.int32)

    def generate(self, prompt, max_new: int | None = None):
        """Greedy-decode up to ``max_new`` tokens after ``prompt`` on
        a decode-mode server; returns the generated token ids (int32),
        or a ``MigratedStream`` when the serving replica drained
        mid-stream (scale-down — the caller re-dispatches).
        At-least-once safe like ``infer``: generation is deterministic
        (greedy) given the export version, and a redelivered request
        only costs duplicate work, never duplicate side effects."""
        try:
            return self._unwire_tokens(
                self.call("generate",
                          np.asarray(prompt, np.int32),
                          None if max_new is None else int(max_new)))
        except ServiceError as e:
            if Overloaded.__name__ in str(e):
                raise Overloaded(str(e)) from None
            raise

    def adopt(self, manifest: dict, k, v,
              max_new: int | None = None) -> np.ndarray:
        """Ship one migrated stream (page manifest + KV pages) to a
        decode-mode server; returns its generated token ids, first
        token included.  The pages travel as one ``RawArrays`` frame
        pair — the raw uint8 path, no compression and no wire-dtype
        re-encode, because KV bytes must arrive EXACTLY as prefilled
        (byte-identity is pinned at the bench level).  Geometry
        mismatches re-raise the server's typed
        :class:`~theanompi_tpu.decode.migrate.IncompatiblePages`;
        admission rejections re-raise :class:`Overloaded` — the
        connection survives both."""
        try:
            return self._unwire_tokens(
                self.call("adopt", manifest, wire.RawArrays(k, v),
                          None if max_new is None else int(max_new)))
        except ServiceError as e:
            if Overloaded.__name__ in str(e):
                raise Overloaded(str(e)) from None
            if IncompatiblePages.__name__ in str(e):
                raise IncompatiblePages(str(e)) from None
            raise

    def drain_migrate(self) -> int:
        """Tell a decode server to drain: stop admitting, export live
        streams as MigratedStream payloads (scale-down hand-off)."""
        return int(self.call("drain"))

    def stats(self) -> dict:
        return self.call("stats")

    def reload(self) -> int:
        """Force an immediate export-dir poll; returns the serving
        version after it.  An incompatible published export re-raises
        the server's typed :class:`IncompatibleExport` refusal."""
        try:
            return int(self.call("reload"))
        except ServiceError as e:
            if IncompatibleExport.__name__ in str(e):
                raise IncompatibleExport(str(e)) from None
            raise

    def shutdown(self) -> None:
        self.call("shutdown")


# ---------------------------------------------------------------------------
# Entry point (the launcher's SERVE mode lands here)
# ---------------------------------------------------------------------------


def decode_opts_from_args(args) -> dict | None:
    """The ``--decode-*`` flags → ``InferenceServer(decode_opts=...)``
    dict — ONE translation shared by the launcher's SERVE rule and
    this module's CLI (identically-named flags in both parsers), so a
    new decode knob cannot silently exist in one entry point only."""
    if not args.decode:
        return None
    opts = {
        "page_size": args.decode_page_size,
        "pages_per_seq": args.decode_pages_per_seq,
        "max_seqs": args.decode_max_seqs,
        "max_pending": args.decode_max_pending,
        "prefix_cache": not args.decode_no_prefix_cache,
        "prefill_batch": args.decode_prefill_batch,
        "prefill_delay_ms": args.decode_prefill_delay_ms,
    }
    if args.decode_fleet_cache:
        opts["fleet_cache"] = args.decode_fleet_cache
    if args.decode_prefill_buckets:
        opts["prefill_buckets"] = tuple(
            int(b) for b in args.decode_prefill_buckets.split(","))
    if args.decode_draft_export_dir:
        opts["draft_export_dir"] = args.decode_draft_export_dir
        opts["speculate_k"] = args.decode_speculate_k
    return opts


def serve_main(export_dir: str, host: str = "0.0.0.0",
               port: int = DEFAULT_PORT, replicas: int = 1,
               max_batch: int = 8, max_delay_ms: float = 5.0,
               buckets: tuple[int, ...] | None = None,
               max_queue: int = 32, max_restarts: int = 2,
               reload_poll_s: float = 1.0, decode: bool = False,
               decode_opts: dict | None = None) -> int:
    # persistent compilation cache before any replica warms up: the
    # per-bucket eval programs compile once per (shape, flags) EVER,
    # not once per server restart — a hot-standby restart re-serves in
    # deserialization time (no flag/env -> no-op)
    from theanompi_tpu.utils.helper_funcs import enable_compilation_cache

    enable_compilation_cache()
    policy = BatchPolicy(max_batch=max_batch, max_delay_ms=max_delay_ms,
                         buckets=buckets, max_queue=max_queue)
    # serving telemetry mirrors the param service's: request-driven
    # progress, so the stall watchdog is off; name-suffixed files so a
    # co-located trainer's rank0 files survive
    with monitor.session(stall_after=float("inf"),
                         name=f"serve{os.getpid()}"):
        monitor.progress(phase="serving")
        server = InferenceServer(
            export_dir, replicas=replicas, policy=policy,
            max_restarts=max_restarts, reload_poll_s=reload_poll_s,
            decode=decode, decode_opts=decode_opts)
        server.start()
        if decode:
            r0 = server.replicas[0]
            s0 = r0.session
            spec = ("off" if r0.draft_session is None else
                    f"k={r0.batcher.policy.speculate_k} "
                    f"draft=v{r0.draft_session.version}")
            print(f"[serving] DECODE v{server.version} x{replicas} "
                  f"replicas on {host}:{port} "
                  f"(window={s0.window}, page_size={s0.cfg.page_size}, "
                  f"max_seqs={s0.cfg.max_seqs}, "
                  f"prefill_buckets={s0.prefill_buckets}, "
                  f"speculation={spec}, prefix_cache="
                  f"{'on' if s0.prefix_cache is not None else 'off'})",
                  flush=True)
        else:
            print(f"[serving] v{server.version} x{replicas} replicas "
                  f"on {host}:{port} (max_batch={max_batch}, "
                  f"max_delay={max_delay_ms}ms, "
                  f"buckets={server.policy.resolved_buckets()}, "
                  f"max_queue={max_queue})", flush=True)
        try:
            serve(server, host, port)
        finally:
            server.stop()
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="theanompi-tpu dynamic-batching inference server")
    ap.add_argument("--export-dir", required=True)
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=DEFAULT_PORT)
    ap.add_argument("--replicas", type=int, default=1)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--max-delay-ms", type=float, default=5.0)
    ap.add_argument("--buckets", default=None,
                    help="comma-separated padded batch sizes "
                         "(default: powers of two up to max-batch)")
    ap.add_argument("--max-queue", type=int, default=32)
    ap.add_argument("--max-restarts", type=int, default=2)
    ap.add_argument("--reload-poll-s", type=float, default=1.0)
    ap.add_argument("--decode", action="store_true",
                    help="autoregressive mode (theanompi_tpu/decode): "
                         "paged KV-cache + continuous batching; serves "
                         "the 'generate' op for TransformerLM exports")
    ap.add_argument("--decode-page-size", type=int, default=16)
    ap.add_argument("--decode-pages-per-seq", type=int, default=8)
    ap.add_argument("--decode-max-seqs", type=int, default=8)
    ap.add_argument("--decode-max-pending", type=int, default=32)
    ap.add_argument("--decode-prefill-buckets", default=None,
                    metavar="N,N,...",
                    help="padded prompt-length buckets (default powers "
                         "of two up to min(512, max_len))")
    ap.add_argument("--decode-draft-export-dir", default=None,
                    metavar="DIR",
                    help="speculative decoding: a small decode-capable "
                         "export that proposes tokens the target "
                         "verifies k-at-a-time in one bucketed step "
                         "(docs/SERVING.md 'Speculative decode'); "
                         "dims may differ, vocab must match")
    ap.add_argument("--decode-speculate-k", type=int, default=4,
                    help="draft tokens per speculative round (needs "
                         "--decode-draft-export-dir)")
    ap.add_argument("--decode-no-prefix-cache", action="store_true",
                    help="disable the cross-request prefix cache "
                         "(copy-on-write KV page sharing; on by "
                         "default — docs/SERVING.md 'Prefix cache')")
    ap.add_argument("--decode-prefill-batch", type=int, default=8,
                    help="max prompts coalesced into ONE batched "
                         "prefill program call per admission round "
                         "(1 = serial prefill, the pre-batching path "
                         "— docs/SERVING.md 'Batched prefill')")
    ap.add_argument("--decode-prefill-delay-ms", type=float,
                    default=2.0,
                    help="how long the oldest pending prompt may wait "
                         "for batch company before its prefill "
                         "launches regardless of occupancy")
    ap.add_argument("--decode-fleet-cache", default=None,
                    metavar="HOST:PORT",
                    help="fleet-wide prefix cache authority (a "
                         "prefill server's port): local prefix-cache "
                         "misses consult it, cold prefills register "
                         "their page-aligned prefixes — docs/"
                         "SERVING.md 'Fleet prefix cache'")
    ap.add_argument("--platform", default=None,
                    help="jax platform (e.g. 'cpu')")
    ap.add_argument("--compilation-cache-dir", default=None, metavar="DIR",
                    help="persistent XLA compilation cache: warmup "
                         "deserializes the per-bucket eval programs "
                         "instead of recompiling on every server "
                         "restart (also honors "
                         "THEANOMPI_TPU_COMPILATION_CACHE)")
    args = ap.parse_args(argv)
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    if args.compilation_cache_dir:
        import os

        os.environ["THEANOMPI_TPU_COMPILATION_CACHE"] = \
            args.compilation_cache_dir
    buckets = (tuple(int(b) for b in args.buckets.split(","))
               if args.buckets else None)
    decode_opts = decode_opts_from_args(args)
    return serve_main(args.export_dir, args.host, args.port,
                      replicas=args.replicas, max_batch=args.max_batch,
                      max_delay_ms=args.max_delay_ms, buckets=buckets,
                      max_queue=args.max_queue,
                      max_restarts=args.max_restarts,
                      reload_poll_s=args.reload_poll_s,
                      decode=args.decode, decode_opts=decode_opts)


if __name__ == "__main__":
    raise SystemExit(main())
