"""TM401–TM404 — site-coverage lint: code and docs cannot drift.

Two inventories, both extracted from the package source by ``ast``:

* **fault sites** — every ``faults.fire("<site>", coord=...)`` call
  site (the resilience injection plane, ``resilience/faults.py``);
* **metric series** — every ``monitor.inc/set_gauge/add_gauge/
  observe("<name>", ...)`` emission (including direct
  ``registry.<kind>("<name>", ...)`` calls inside the monitor package
  itself), with the label keys used at each call site.

Both are diffed against ``docs/OBSERVABILITY.md``: the metric catalog
table and the fault-site table (first-column backticked names).  Four
outcomes:

* TM401 — a site fires in code but is missing from the docs table;
* TM402 — the docs name a site nothing fires (stale docs, or a typo'd
  site string that silently never matches a fault plan — the worse
  failure, since an operator's plan then tests nothing);
* TM403 — a metric is emitted but undocumented;
* TM404 — a documented metric is never emitted (a dashboard built on
  it would silently flatline).

``tmlint --inventory`` prints both inventories as markdown rows — the
OBSERVABILITY.md tables are regenerated from that output, which is how
they started in sync.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterable

from theanompi_tpu.analysis.common import (
    Finding,
    SourceFile,
    const_str,
    dotted_name,
    make_key,
)

CHECK_SITE_UNDOC = "TM401"
CHECK_SITE_UNFIRED = "TM402"
CHECK_METRIC_UNDOC = "TM403"
CHECK_METRIC_UNEMITTED = "TM404"

_EMIT_METHODS = {"inc": "counter", "set_gauge": "gauge",
                 "add_gauge": "gauge", "observe": "histogram"}

#: modules excluded from the inventories: the checkers themselves, and
#: — for FIRE sites only — faults.py, whose ``fire`` definitions and
#: internal dispatch would otherwise read as call sites
_INTERNAL = ("analysis/",)
_FIRE_INTERNAL = _INTERNAL + ("resilience/faults.py",)

_BACKTICK_RE = re.compile(r"`([^`]+)`")


# ---------------------------------------------------------------------------
# Code inventories
# ---------------------------------------------------------------------------


class Emission:
    def __init__(self, name: str, kind: str, labels: tuple[str, ...],
                 path: str, line: int):
        self.name = name
        self.kind = kind
        self.labels = labels
        self.path = path
        self.line = line


class FireSite:
    def __init__(self, site: str, coords: tuple[str, ...],
                 path: str, line: int):
        self.site = site
        self.coords = coords
        self.path = path
        self.line = line


def collect_metrics(files: list[SourceFile]) -> list[Emission]:
    out: list[Emission] = []
    for src in files:
        if any(part in src.relpath for part in _INTERNAL):
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute):
                continue
            meth = node.func.attr
            if meth not in _EMIT_METHODS or not node.args:
                continue
            name = const_str(node.args[0])
            if name is None:
                continue
            # the receiver must look like the monitor facade or a
            # registry (self.registry / _state.registry / monitor) —
            # not, say, Counter.inc
            recv = dotted_name(node.func.value) or ""
            if not (recv == "monitor" or recv.endswith("registry")
                    or recv.endswith("_registry")):
                continue
            labels = tuple(sorted(kw.arg for kw in node.keywords
                                  if kw.arg is not None))
            out.append(Emission(name, _EMIT_METHODS[meth], labels,
                                src.relpath, node.lineno))
    return out


def collect_fires(files: list[SourceFile]) -> list[FireSite]:
    out: list[FireSite] = []
    for src in files:
        if any(part in src.relpath for part in _FIRE_INTERNAL):
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted_name(node.func) or ""
            if d.split(".")[-1] != "fire" or not node.args:
                continue
            site = const_str(node.args[0])
            if site is None:
                continue
            coords = tuple(sorted(kw.arg for kw in node.keywords
                                  if kw.arg is not None))
            out.append(FireSite(site, coords, src.relpath, node.lineno))
    return out


# ---------------------------------------------------------------------------
# Docs inventory
# ---------------------------------------------------------------------------


def _table_names(md_text: str, section_heading: str) -> dict[str, int]:
    """Backticked names from the first column of the table under
    ``section_heading`` -> line number."""
    out: dict[str, int] = {}
    in_section = False
    for i, line in enumerate(md_text.splitlines(), start=1):
        if line.startswith("#"):
            in_section = section_heading in line
            continue
        if not in_section or not line.lstrip().startswith("|"):
            continue
        cells = line.split("|")
        if len(cells) < 3:
            continue
        first = cells[1]
        if set(first.strip()) <= {"-", ":", " "}:
            continue  # separator row
        for name in _BACKTICK_RE.findall(first):
            out[name.strip()] = i
    return out


def docs_metrics(doc_path: str) -> dict[str, int]:
    with open(doc_path, encoding="utf-8") as f:
        return _table_names(f.read(), "Metric catalog")


def docs_sites(doc_path: str) -> dict[str, int]:
    with open(doc_path, encoding="utf-8") as f:
        return _table_names(f.read(), "Fault sites")


# ---------------------------------------------------------------------------
# The check
# ---------------------------------------------------------------------------


def run(files: list[SourceFile], doc_path: str,
        doc_relpath: str = "docs/OBSERVABILITY.md") -> list[Finding]:
    findings: list[Finding] = []
    if not os.path.exists(doc_path):
        findings.append(Finding(
            CHECK_METRIC_UNDOC, doc_relpath, 1,
            "docs/OBSERVABILITY.md is missing; the metric catalog and "
            "fault-site tables are the coverage contract",
            make_key(CHECK_METRIC_UNDOC, doc_relpath, "<missing>")))
        return findings

    emissions = collect_metrics(files)
    fires = collect_fires(files)
    doc_m = docs_metrics(doc_path)
    doc_s = docs_sites(doc_path)

    emitted: dict[str, list[Emission]] = {}
    for e in emissions:
        emitted.setdefault(e.name, []).append(e)
    fired: dict[str, list[FireSite]] = {}
    for f in fires:
        fired.setdefault(f.site, []).append(f)

    for name, es in sorted(emitted.items()):
        # an inline suppression on ANY emission of the name covers the
        # name (the suppression is about the metric, not one call
        # site — and must not depend on file-walk order)
        if name not in doc_m \
                and not any(_suppressed_line(files, e) for e in es):
            e = es[0]
            findings.append(Finding(
                CHECK_METRIC_UNDOC, e.path, e.line,
                f"metric '{name}' ({e.kind}) is emitted here but "
                f"missing from the {doc_relpath} metric catalog",
                make_key(CHECK_METRIC_UNDOC, name)))
    for name, line in sorted(doc_m.items()):
        if name not in emitted:
            findings.append(Finding(
                CHECK_METRIC_UNEMITTED, doc_relpath, line,
                f"documented metric '{name}' is never emitted by the "
                f"package (dashboards on it would flatline)",
                make_key(CHECK_METRIC_UNEMITTED, name)))
    for site, fs in sorted(fired.items()):
        if site not in doc_s \
                and not any(_suppressed_line(files, f) for f in fs):
            f = fs[0]
            findings.append(Finding(
                CHECK_SITE_UNDOC, f.path, f.line,
                f"fault site '{site}' fires here but is missing from "
                f"the {doc_relpath} fault-site table",
                make_key(CHECK_SITE_UNDOC, site)))
    for site, line in sorted(doc_s.items()):
        if site not in fired:
            findings.append(Finding(
                CHECK_SITE_UNFIRED, doc_relpath, line,
                f"documented fault site '{site}' never fires in the "
                f"package (a fault plan naming it tests nothing)",
                make_key(CHECK_SITE_UNFIRED, site)))
    return findings


def _suppressed_line(files: Iterable[SourceFile], item) -> bool:
    for src in files:
        if src.relpath == item.path:
            check = CHECK_METRIC_UNDOC if isinstance(item, Emission) \
                else CHECK_SITE_UNDOC
            return src.suppressed(item.line, check)
    return False


# ---------------------------------------------------------------------------
# Inventory rendering (the docs-regeneration seam)
# ---------------------------------------------------------------------------


def render_inventory(files: list[SourceFile]) -> str:
    """Markdown rows for both tables, grouped per series/site with the
    union of labels/coords and every source module."""
    emissions = collect_metrics(files)
    fires = collect_fires(files)
    lines = ["## metrics", "", "| Series | Kind | Labels | Source |",
             "|---|---|---|---|"]
    by_name: dict[str, list[Emission]] = {}
    for e in emissions:
        by_name.setdefault(e.name, []).append(e)
    for name in sorted(by_name):
        es = by_name[name]
        kinds = sorted({e.kind for e in es})
        labels = sorted({l for e in es for l in e.labels})
        paths = sorted({e.path for e in es})
        lines.append(f"| `{name}` | {', '.join(kinds)} | "
                     f"{', '.join(f'`{l}`' for l in labels) or '—'} | "
                     f"{', '.join(f'`{p}`' for p in paths)} |")
    lines += ["", "## fault sites", "",
              "| Site | Coords | Source |", "|---|---|---|"]
    by_site: dict[str, list[FireSite]] = {}
    for f in fires:
        by_site.setdefault(f.site, []).append(f)
    for site in sorted(by_site):
        fs = by_site[site]
        coords = sorted({c for f in fs for c in f.coords})
        paths = sorted({f.path for f in fs})
        lines.append(f"| `{site}` | "
                     f"{', '.join(f'`{c}`' for c in coords) or '—'} | "
                     f"{', '.join(f'`{p}`' for p in paths)} |")
    return "\n".join(lines) + "\n"
