"""Runtime lock-order detector for the threaded host plane.

Static passes (guarded_by.py) prove accesses happen *under* a lock;
they cannot prove two locks are always taken in the same *order*.  An
AB/BA inversion between, say, a batcher's condition and the reload
watcher's lock deadlocks only under exact interleaving — the kind of
bug that survives a thousand green CI runs and kills the first
production incident.

:class:`TrackedLock` wraps ``threading.Lock`` and maintains

* a **per-thread held stack** of lock *site names* (one name per
  construction site, e.g. ``"DynamicBatcher._lock"`` — instances share
  the name, because ordering discipline is defined per site, not per
  object);
* a **global acquisition-order graph**: acquiring B while holding A
  records the edge A→B.  Before recording, the graph is checked for a
  path B→…→A; if one exists, the new edge closes a cycle and
  :class:`LockOrderError` is raised **at acquire time, before
  blocking** — the test fails with the full cycle spelled out instead
  of hanging until the CI timeout.

Acquiring a lock object already held by the same thread with
``blocking=True`` raises immediately (``threading.Lock`` is not
reentrant — that IS the deadlock).  A non-blocking attempt on a held
lock is allowed through untracked, because
``threading.Condition._is_owned`` probes ownership exactly that way.
Re-acquire detection is per lock INSTANCE: two objects constructed at
the same site (two batcher replicas) share a graph node but nest
freely — the site-level graph deliberately records no same-site
self-edges, so opposite-order nesting of two same-site instances is
outside its reach.

Activation: :func:`make_lock` / :func:`make_condition` are the
construction seam used by ``_ExchangePipe``, ``DynamicBatcher``,
``WorkerSupervisor``, and ``InferenceServer``.  With
``THEANOMPI_TPU_LOCKCHECK=1`` (tier-1 sets it in ``tests/conftest.py``)
they return tracked objects; otherwise plain ``threading`` primitives
with zero overhead.  ``threading.Condition`` composes transparently:
its ``wait()`` releases/reacquires via the tracked ``acquire``/
``release``, so the held stack stays truthful across waits.
"""

from __future__ import annotations

import os
import threading

ENV_VAR = "THEANOMPI_TPU_LOCKCHECK"


class LockOrderError(RuntimeError):
    """A lock acquisition that would close an order cycle (deadlock
    potential), or a same-thread re-acquire of a non-reentrant site."""


class LockGraph:
    """Global site-level acquisition-order graph."""

    def __init__(self):
        self._mu = threading.Lock()
        #: edge A -> {B: "threadname"}: B was acquired while A held
        self._edges: dict[str, dict[str, str]] = {}

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()

    def edges(self) -> dict[str, set[str]]:
        with self._mu:
            return {a: set(bs) for a, bs in self._edges.items()}

    def note_acquire(self, name: str, held: tuple[str, ...]) -> None:
        """Record held->name edges; raise on a cycle BEFORE the caller
        blocks on the real lock."""
        if not held:
            return
        cycle: list[str] | None = None
        with self._mu:
            for h in held:
                if h == name:
                    continue  # same-site nesting is checked per-thread
                    # by TrackedLock (instances may differ)
                targets = self._edges.setdefault(h, {})
                if name in targets:
                    continue
                path = self._path(name, h)
                if path is not None:
                    cycle = [h] + path
                    break
                targets[name] = threading.current_thread().name
        if cycle is not None:
            # cycle is already closed: [h, name, ..., h]
            chain = " -> ".join(cycle)
            raise LockOrderError(
                f"lock-order cycle: acquiring '{name}' while holding "
                f"'{cycle[0]}' inverts the established order "
                f"{chain} (each '->' is an acquired-while-holding "
                f"edge recorded this run); two threads taking these "
                f"sites in opposite orders can deadlock")

    def _path(self, src: str, dst: str) -> list[str] | None:
        """DFS path src -> ... -> dst over recorded edges (caller holds
        self._mu)."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self._edges.get(node, {}):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None


#: the process-wide graph (tests may reset() it)
GRAPH = LockGraph()

_tls = threading.local()


def _held_stack() -> list[tuple[str, int]]:
    """Per-thread stack of (site name, lock instance id).  Edges in
    the graph are site-level, but re-acquire detection and release
    bookkeeping must be INSTANCE-level: two batcher replicas share the
    site name, and nesting their two distinct locks is legal."""
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


class TrackedLock:
    """``threading.Lock`` with held-stack + order-graph bookkeeping.
    Duck-compatible with ``threading.Condition``'s expectations."""

    def __init__(self, name: str, graph: LockGraph | None = None):
        self.name = name
        self._graph = graph or GRAPH
        self._lock = threading.Lock()

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        stack = _held_stack()
        if any(iid == id(self) for _, iid in stack):
            # THIS lock object is already held by this thread
            if blocking:
                raise LockOrderError(
                    f"same-thread re-acquire of non-reentrant lock "
                    f"site '{self.name}' (held: "
                    f"{[n for n, _ in stack]}) — this deadlocks a "
                    f"threading.Lock")
            # Condition._is_owned probes with acquire(False); an
            # already-held lock must simply fail the probe
            return self._lock.acquire(False)
        self._graph.note_acquire(self.name,
                                 tuple(n for n, _ in stack))
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            stack.append((self.name, id(self)))
        return ok

    def release(self) -> None:
        self._lock.release()
        stack = _held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][1] == id(self):
                del stack[i]
                break

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "TrackedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"TrackedLock({self.name!r}, locked={self.locked()})"


def enabled() -> bool:
    return os.environ.get(ENV_VAR, "") not in ("", "0", "false")


def make_lock(name: str):
    """The construction seam: a :class:`TrackedLock` under
    ``THEANOMPI_TPU_LOCKCHECK=1``, else a plain ``threading.Lock``."""
    if enabled():
        return TrackedLock(name)
    return threading.Lock()


def make_condition(lock=None, name: str = "condition"):
    """``threading.Condition`` over ``lock`` (tracked or plain).  With
    no lock given, the condition's internal lock follows the same
    enablement rule as :func:`make_lock`."""
    return threading.Condition(lock if lock is not None
                               else make_lock(name))
