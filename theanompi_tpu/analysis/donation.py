"""TM201 — use-after-donate lint for jitted call sites.

``jax.jit(f, donate_argnums=(0,))`` hands argument 0's buffers to XLA:
after the call, reading that array from Python is undefined behavior
(on TPU it is a crash or garbage; on CPU it often *silently works*,
which is why this bug class survives tier-1 — the exact class PR 3's
bench/queue donation opt-outs exist to dodge).

The pass has two phases:

1. **Registry** — scan the whole package for donating callables:

   * ``@partial(jax.jit, donate_argnums=(...))`` decorated defs
     (the exchanger's merge fns);
   * ``name = jax.jit(fn, donate_argnums=(...))`` assignments,
     including ``self.attr = jax.jit(...)`` (wgan, InferenceSession);
   * factory functions whose ``return jax.jit(..., donate_argnums=...)``
     makes every ``step = build_train_step(...)`` call site a donating
     callable too (the parallel/ step builders).

   ``donate_argnums=(0,) if donate else ()`` counts as donating — the
   lint must assume donation CAN happen.

2. **Dataflow** — per function body, in statement order: a call to a
   registered callable marks each *simple path* argument
   (``x``, ``model.state.params``) in a donated position as dead; any
   later read of the dead path (or an extension of it) is flagged;
   any store to the path or a prefix of it (``model.state = ...``)
   revives it.  Reads inside the donating statement itself are not
   flagged (Python evaluates them before the call).  ``if`` branches
   are treated as mutually exclusive (each analyzed on a copy of the
   incoming state; the fall-through state is the union), so the zoo's
   ``k>1 / a>1 / else`` step-dispatch pattern does not cross-poison.

Known limits (documented in docs/ANALYSIS.md): loop bodies are walked
once in place, so a loop that donates at the bottom and reads at the
top is only caught when the read follows the donate in source order;
donated arguments that are expressions (``f(g(x))``) are not tracked.
"""

from __future__ import annotations

import ast

from theanompi_tpu.analysis.common import (
    Finding,
    SourceFile,
    dotted_name,
    int_tuple,
    make_key,
)

CHECK_ID = "TM201"

_JIT_NAMES = {"jax.jit", "jit"}
_DONATE_KWARGS = ("donate_argnums", "static_argnums_donate")


# ---------------------------------------------------------------------------
# Phase 1: the donating-callable registry
# ---------------------------------------------------------------------------


def _kw_positions(kw: ast.keyword) -> tuple[int, ...] | None:
    """Donated positions from one ``donate_argnums=`` keyword — the
    ONE evaluation rule both the decorator and assignment paths share.
    Literal specs evaluate exactly (``()`` -> None: the explicit
    no-donate spec must not register); IfExp takes the union of its
    branches; a dynamic spec (a helper like ``_donate_argnums(...)``)
    falls back to ``(0, 1)`` — the canonical state+staged-batch
    donation of the bsp/zero/fsdp step builders, erring toward
    tracking."""
    pos = int_tuple(kw.value)
    if pos is not None:
        return pos or None
    return (0, 1)


def _donated_positions(call: ast.Call) -> tuple[int, ...] | None:
    """Donated positions of a ``jax.jit(...)`` call; None when the
    call does not donate (or we cannot tell it does)."""
    if (dotted_name(call.func) or "") not in _JIT_NAMES:
        return None
    for kw in call.keywords:
        if kw.arg in _DONATE_KWARGS:
            return _kw_positions(kw)
    return None


def _decorator_positions(fn: ast.FunctionDef) -> tuple[int, ...] | None:
    for dec in fn.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        callee = dotted_name(dec.func) or ""
        if callee.split(".")[-1] == "partial" and dec.args:
            if (dotted_name(dec.args[0]) or "") in _JIT_NAMES:
                for kw in dec.keywords:
                    if kw.arg in _DONATE_KWARGS:
                        return _kw_positions(kw)
        p = _donated_positions(dec)
        if p:
            return p
    return None


def build_registry(files: list[SourceFile]) -> dict[str, tuple[int, ...]]:
    """callable name (simple or ``self.attr``) -> donated positions.

    Keys are intentionally unqualified: the package imports these
    functions by name (``from ...exchanger import easgd_apply_delta``),
    and a same-name collision between a donating and non-donating
    callable is itself worth flagging loudly rather than missing.
    """
    registry: dict[str, tuple[int, ...]] = {}
    factories: dict[str, tuple[int, ...]] = {}
    for src in files:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.FunctionDef):
                pos = _decorator_positions(node)
                if pos:
                    registry[node.name] = pos
                # factory: returns a donating jax.jit wrapper
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Return) \
                            and isinstance(sub.value, ast.Call):
                        rpos = _donated_positions(sub.value)
                        if rpos:
                            factories[node.name] = rpos
            elif isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                pos = _donated_positions(node.value)
                if pos:
                    for tgt in node.targets:
                        d = dotted_name(tgt)
                        if d:
                            registry[d] = pos
    # second pass: assignments calling a factory
    for src in files:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                callee = (dotted_name(node.value.func) or "").split(".")[-1]
                if callee in factories:
                    for tgt in node.targets:
                        d = dotted_name(tgt)
                        if d:
                            registry[d] = factories[callee]
    return registry


# ---------------------------------------------------------------------------
# Phase 2: per-function linear dataflow
# ---------------------------------------------------------------------------


_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                ast.Lambda)


#: statements with nested statement lists — their HEADER expressions
#: are analyzed standalone and their bodies recursed, so no expression
#: is ever walked twice
_COMPOUND = (ast.If, ast.While, ast.For, ast.AsyncFor, ast.With,
             ast.AsyncWith, ast.Try)


def _walk_scope(node: ast.AST):
    """ast.walk pruned at nested scope boundaries."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, _SCOPE_NODES):
            continue
        yield child
        yield from _walk_scope(child)


def _loads_and_stores(stmt: ast.AST):
    loads: list[tuple[str, int]] = []
    stores: list[str] = []
    calls: list[ast.Call] = []
    nodes = [stmt] if isinstance(stmt, (ast.Name, ast.Attribute,
                                        ast.Call)) else []
    for node in nodes + list(_walk_scope(stmt)):
        if isinstance(node, ast.Call):
            calls.append(node)
        d = dotted_name(node) if isinstance(
            node, (ast.Name, ast.Attribute)) else None
        if d is None:
            continue
        ctx = getattr(node, "ctx", None)
        if isinstance(ctx, (ast.Store, ast.Del)):
            stores.append(d)
        elif isinstance(ctx, ast.Load):
            loads.append((d, node.lineno))
    return loads, stores, calls


def _covers(dead: str, path: str) -> bool:
    """True when a read of ``path`` touches the donated tree ``dead``
    (the path itself or anything under it)."""
    return path == dead or path.startswith(dead + ".")


def _revives(store: str, dead: str) -> bool:
    """A store to the path, a prefix, or a sub-path replaces the
    binding (or the container holding it) — the old buffers are no
    longer reachable through it."""
    return (store == dead or dead.startswith(store + ".")
            or store.startswith(dead + "."))


class _Flow:
    """Per-function dataflow state + the unit step shared by every
    block walk: ``dead`` maps a donated path to (callee, line)."""

    def __init__(self, src: SourceFile,
                 registry: dict[str, tuple[int, ...]], qual: str,
                 findings: list[Finding]):
        self.src = src
        self.registry = registry
        self.qual = qual
        self.findings = findings
        self.reported: set[str] = set()

    def unit(self, node: ast.AST, dead: dict) -> None:
        loads, stores, calls = _loads_and_stores(node)
        # 1. reads of already-dead paths (donations from PRIOR units
        # only — same-statement reads precede the call)
        for path, lineno in loads:
            for dpath, (callee, dline) in dead.items():
                if _covers(dpath, path) \
                        and not self.src.suppressed(lineno, CHECK_ID):
                    key = make_key(CHECK_ID, self.src.relpath,
                                   self.qual, dpath)
                    if key not in self.reported:
                        self.reported.add(key)
                        self.findings.append(Finding(
                            CHECK_ID, self.src.relpath, lineno,
                            f"'{path}' used after being donated to "
                            f"{callee}() at line {dline} "
                            f"(donate_argnums)", key))
        # 2. new donations (the call executes before any assignment of
        # its result, so donations register BEFORE stores revive —
        # ``x = f(x)`` with donated arg 0 leaves x alive)
        for call in calls:
            name = dotted_name(call.func)
            if name is None:
                continue
            pos = self.registry.get(name) \
                or self.registry.get(name.split(".")[-1])
            if not pos:
                continue
            for i in pos:
                if i < len(call.args):
                    d = dotted_name(call.args[i])
                    if d is not None:
                        dead[d] = (name, call.lineno)
        # 3. stores revive (a rebound name no longer reaches the
        # donated buffers)
        for store in stores:
            for dpath in [d for d in dead if _revives(store, d)]:
                del dead[dpath]

    def block(self, stmts: list[ast.stmt], dead: dict) -> None:
        """Walk one statement list, mutating ``dead`` in place.  If
        branches are MUTUALLY EXCLUSIVE: each runs on its own copy of
        the incoming state (a donation in one branch cannot kill a
        read in the other), and the fall-through state is the union of
        the branches' dead sets (the donation may have happened).
        Loop/with/try bodies stay linear, visited once in place."""
        for stmt in stmts:
            if isinstance(stmt, _SCOPE_NODES[:3]):
                continue  # nested scope: checked on its own walk
            if isinstance(stmt, ast.If):
                self.unit(stmt.test, dead)
                d_then = dict(dead)
                d_else = dict(dead)
                self.block(stmt.body, d_then)
                self.block(stmt.orelse, d_else)
                dead.clear()
                dead.update(d_else)
                dead.update(d_then)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self.unit(stmt.iter, dead)
                self.unit(stmt.target, dead)
                self.block(stmt.body, dead)
                self.block(stmt.orelse, dead)
            elif isinstance(stmt, ast.While):
                self.unit(stmt.test, dead)
                self.block(stmt.body, dead)
                self.block(stmt.orelse, dead)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self.unit(item.context_expr, dead)
                self.block(stmt.body, dead)
            elif isinstance(stmt, ast.Try):
                self.block(stmt.body, dead)
                for handler in stmt.handlers:
                    self.block(handler.body, dead)
                self.block(stmt.orelse, dead)
                self.block(stmt.finalbody, dead)
            else:
                self.unit(stmt, dead)


def check_function(src: SourceFile, fn: ast.FunctionDef,
                   registry: dict[str, tuple[int, ...]],
                   qual: str) -> list[Finding]:
    findings: list[Finding] = []
    flow = _Flow(src, registry, qual, findings)
    flow.block(fn.body, {})
    return findings


def run(files: list[SourceFile],
        registry: dict[str, tuple[int, ...]] | None = None
        ) -> list[Finding]:
    registry = registry if registry is not None else build_registry(files)
    out: list[Finding] = []
    for src in files:
        # walk every function (methods included), each as its own scope
        stack: list[tuple[ast.AST, str]] = [(src.tree, "")]
        while stack:
            node, prefix = stack.pop()
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    stack.append((child, f"{prefix}{child.name}."))
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    qual = f"{prefix}{child.name}"
                    out.extend(check_function(src, child, registry, qual))
                    stack.append((child, f"{qual}."))
                else:
                    stack.append((child, prefix))
    return out
