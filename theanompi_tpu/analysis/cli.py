"""``tmlint`` — the repo-native static checker CLI (docs/ANALYSIS.md).

Modes:

* default / ``--format json``: run every checker, print findings;
* ``--gate``: zero-NEW-findings gate against ``analysis/baseline.json``
  (exit 1 on any finding whose stable key is not baselined; stale
  baseline entries are warnings, not failures) — wired into
  ``tools/preflight.sh``;
* ``--write-baseline``: accept the current findings as the baseline
  (reasons already recorded for surviving keys are preserved);
* ``--inventory``: print the metric/fault-site inventories as markdown
  (the OBSERVABILITY.md tables are regenerated from this).

Pure stdlib + ``ast``: nothing in the checked package is imported, so
the gate runs in seconds on CPU with no jax initialization.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from theanompi_tpu.analysis import (
    donation,
    guarded_by,
    jit_hygiene,
    site_coverage,
)
from theanompi_tpu.analysis.common import (
    CHECK_IDS,
    Finding,
    iter_source_files,
    load_baseline,
    split_by_baseline,
    write_baseline,
)

#: checker name -> callable(files, doc_path) -> findings
_CHECKERS = ("guarded_by", "donation", "jit_hygiene", "site_coverage")


def find_repo_root(start: str | None = None) -> str:
    """Nearest ancestor of ``start``/cwd containing the
    ``theanompi_tpu`` package; falls back to the checkout this module
    itself was imported from (so ``tmlint`` works from any cwd)."""
    d = os.path.abspath(start or os.getcwd())
    while True:
        if os.path.isdir(os.path.join(d, "theanompi_tpu")):
            return d
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    own = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if os.path.isdir(os.path.join(own, "theanompi_tpu")):
        return own
    raise SystemExit(
        "tmlint: cannot find a theanompi_tpu package above "
        f"{start or os.getcwd()} (use --root)")


def run_checks(repo_root: str, checks: list[str] | None = None,
               package: str = "theanompi_tpu",
               doc_path: str | None = None) -> list[Finding]:
    """Run the selected checkers over ``<repo_root>/<package>``."""
    checks = checks or list(_CHECKERS)
    files = list(iter_source_files(
        os.path.join(repo_root, package), repo_root))
    doc = doc_path if doc_path is not None else os.path.join(
        repo_root, "docs", "OBSERVABILITY.md")
    findings: list[Finding] = []
    if "guarded_by" in checks:
        findings.extend(guarded_by.run(files))
    if "donation" in checks:
        findings.extend(donation.run(files))
    if "jit_hygiene" in checks:
        findings.extend(jit_hygiene.run(files))
    if "site_coverage" in checks:
        findings.extend(site_coverage.run(
            files, doc, os.path.relpath(doc, repo_root).replace(
                os.sep, "/")))
    findings.sort(key=lambda f: (f.path, f.line, f.check_id))
    return findings


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tmlint",
        description="theanompi-tpu static checker suite "
                    "(docs/ANALYSIS.md)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: nearest ancestor with a "
                         "theanompi_tpu package)")
    ap.add_argument("--checks", default=None,
                    help=f"comma-separated subset of "
                         f"{','.join(_CHECKERS)}")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--gate", action="store_true",
                    help="fail (exit 1) on findings not in the "
                         "baseline; stale baseline keys warn")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept current findings as the baseline")
    ap.add_argument("--baseline", default=None,
                    help="baseline path (default: "
                         "<root>/theanompi_tpu/analysis/baseline.json)")
    ap.add_argument("--inventory", action="store_true",
                    help="print the metric/fault-site inventory as "
                         "markdown and exit")
    args = ap.parse_args(argv)

    t0 = time.monotonic()
    root = os.path.abspath(args.root) if args.root else find_repo_root()
    baseline_path = args.baseline or os.path.join(
        root, "theanompi_tpu", "analysis", "baseline.json")

    if args.inventory:
        files = list(iter_source_files(
            os.path.join(root, "theanompi_tpu"), root))
        sys.stdout.write(site_coverage.render_inventory(files))
        return 0

    checks = (args.checks.split(",") if args.checks else None)
    if checks:
        unknown = set(checks) - set(_CHECKERS)
        if unknown:
            ap.error(f"unknown checks: {sorted(unknown)}")
    findings = run_checks(root, checks)

    if args.write_baseline:
        old = load_baseline(baseline_path)
        write_baseline(baseline_path, findings, reasons=old)
        print(f"tmlint: wrote {len({f.key for f in findings})} "
              f"suppression(s) to "
              f"{os.path.relpath(baseline_path, root)}")
        return 0

    baseline = load_baseline(baseline_path)
    new, stale = split_by_baseline(findings, baseline)
    dt = time.monotonic() - t0

    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_json() for f in findings],
            "new": [f.to_json() for f in new],
            "stale_baseline_keys": stale,
            "elapsed_s": round(dt, 3),
        }, indent=2))
    else:
        report = new if args.gate else findings
        for f in report:
            marker = "" if args.gate or f.key not in baseline \
                else " [baselined]"
            print(f.render() + marker)
        for key in stale:
            print(f"tmlint: warning: stale baseline entry '{key}' "
                  f"(no longer found; consider pruning)")
        by_id: dict[str, int] = {}
        for f in report:
            by_id[f.check_id] = by_id.get(f.check_id, 0) + 1
        summary = ", ".join(f"{cid} x{n} ({CHECK_IDS[cid]})"
                            for cid, n in sorted(by_id.items()))
        scope = "new " if args.gate else ""
        print(f"tmlint: {len(report)} {scope}finding(s)"
              + (f" [{summary}]" if summary else "")
              + f", {len(findings) - len(new)} baselined, "
                f"{dt:.1f}s")

    if args.gate and new:
        print("tmlint: GATE FAILED — fix the findings above or add a "
              "documented suppression to analysis/baseline.json",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
