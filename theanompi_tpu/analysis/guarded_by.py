"""TM101 — guarded-by lint for the threaded host plane.

Convention (docs/ANALYSIS.md): a class that shares mutable state
between threads declares each shared attribute at its ``__init__``
assignment with a trailing comment::

    self._q = deque()        # guarded_by: self._cond
    self._restarts = {}      # guarded_by: self._lock

The checker then flags every ``self.<attr>`` read or write of a
declared attribute that is not lexically inside a ``with self.<lock>:``
block for the matching lock (``threading.Condition(self._lock)`` makes
``self._cond`` and ``self._lock`` aliases — either guards both).

Escapes:

* ``__init__`` / ``__post_init__`` / ``__new__`` are exempt — the
  constructor publishes the object before any other thread can see it;
* a method whose ``def`` line carries ``# requires_lock: self.<lock>``
  is analyzed as if that lock were held on entry (for helpers that are
  documented called-with-lock-held, e.g. ``MetricsRegistry._get``);
* ``# lint: ok TM101`` on the access line suppresses inline;
* anything left that is judged a false positive belongs in
  ``analysis/baseline.json`` with a reason.

The pass is purely lexical: it does not chase calls, so a helper that
*sometimes* runs under the lock must either take the lock itself or be
annotated.  That is the point — "sometimes locked" is the bug class.
"""

from __future__ import annotations

import ast
import re

from theanompi_tpu.analysis.common import (
    Finding,
    SourceFile,
    dotted_name,
    make_key,
)

CHECK_ID = "TM101"

_DECL_RE = re.compile(r"#\s*guarded_by:\s*self\.(\w+)")
_REQUIRES_RE = re.compile(r"#\s*requires_lock:\s*self\.(\w+)")

_EXEMPT_METHODS = {"__init__", "__post_init__", "__new__"}

#: constructors that make one lock attribute an alias of another
#: (``self._cond = threading.Condition(self._lock)``)
_ALIAS_CALLS = ("Condition", "make_condition")


def _alias_groups(cls: ast.ClassDef) -> dict[str, str]:
    """attr name -> canonical lock name (union of Condition aliases)."""
    canon: dict[str, str] = {}
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        if not (isinstance(node.value, ast.Call)):
            continue
        callee = dotted_name(node.value.func) or ""
        if callee.split(".")[-1] not in _ALIAS_CALLS:
            continue
        if not node.value.args:
            continue
        src = dotted_name(node.value.args[0])
        if src is None or not src.startswith("self."):
            continue
        src_attr = src.split(".", 1)[1]
        for tgt in node.targets:
            t = dotted_name(tgt)
            if t is not None and t.startswith("self."):
                tgt_attr = t.split(".", 1)[1]
                root = canon.get(src_attr, src_attr)
                canon[tgt_attr] = root
                canon.setdefault(src_attr, root)
    return canon


def _declared_guards(cls: ast.ClassDef, src: SourceFile,
                     canon: dict[str, str]) -> dict[str, str]:
    """Declared attr -> canonical guard name, from the trailing
    ``# guarded_by:`` comments on ``self.X = ...`` assignments."""
    guards: dict[str, str] = {}
    for node in ast.walk(cls):
        targets: list[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            targets = [node.target]
        else:
            continue
        m = _DECL_RE.search(src.line(node.lineno)) \
            or _DECL_RE.search(src.line(getattr(node, "end_lineno",
                                                node.lineno)))
        if not m:
            continue
        lock = canon.get(m.group(1), m.group(1))
        for tgt in targets:
            d = dotted_name(tgt)
            if d is not None and d.startswith("self.") \
                    and d.count(".") == 1:
                guards[d.split(".", 1)[1]] = lock
    return guards


class _MethodChecker(ast.NodeVisitor):
    """Walk one method body tracking the lexically-held lock set."""

    def __init__(self, src: SourceFile, cls_name: str, method: str,
                 guards: dict[str, str], canon: dict[str, str],
                 held0: frozenset[str], findings: list[Finding]):
        self.src = src
        self.cls_name = cls_name
        self.method = method
        self.guards = guards
        self.canon = canon
        self.held = held0
        self.findings = findings
        self._reported: set[str] = set()

    def visit_With(self, node: ast.With) -> None:
        entered: set[str] = set()
        for item in node.items:
            d = dotted_name(item.context_expr)
            if d is not None and d.startswith("self.") \
                    and d.count(".") == 1:
                attr = d.split(".", 1)[1]
                entered.add(self.canon.get(attr, attr))
            # context exprs themselves (and optional vars) still get
            # visited for guarded-attr reads
            self.visit(item.context_expr)
        prev = self.held
        self.held = self.held | frozenset(entered)
        for stmt in node.body:
            self.visit(stmt)
        self.held = prev

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if (isinstance(node.value, ast.Name) and node.value.id == "self"
                and node.attr in self.guards):
            lock = self.guards[node.attr]
            if lock not in self.held \
                    and not self.src.suppressed(node.lineno, CHECK_ID):
                kind = {ast.Store: "write", ast.Del: "delete"}.get(
                    type(node.ctx), "read")
                key = make_key(CHECK_ID, self.src.relpath,
                               f"{self.cls_name}.{self.method}",
                               node.attr)
                if key not in self._reported:
                    self._reported.add(key)
                    self.findings.append(Finding(
                        CHECK_ID, self.src.relpath, node.lineno,
                        f"{kind} of {self.cls_name}.{node.attr} "
                        f"(guarded_by self.{lock}) outside "
                        f"'with self.{lock}:'", key))
        self.generic_visit(node)


def check_file(src: SourceFile) -> list[Finding]:
    findings: list[Finding] = []
    for cls in [n for n in ast.walk(src.tree)
                if isinstance(n, ast.ClassDef)]:
        canon = _alias_groups(cls)
        guards = _declared_guards(cls, src, canon)
        if not guards:
            continue
        for meth in cls.body:
            if not isinstance(meth, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if meth.name in _EXEMPT_METHODS:
                continue
            held = set()
            m = _REQUIRES_RE.search(src.line(meth.lineno))
            if m:
                held.add(canon.get(m.group(1), m.group(1)))
            checker = _MethodChecker(src, cls.name, meth.name, guards,
                                     canon, frozenset(held), findings)
            for stmt in meth.body:
                checker.visit(stmt)
    return findings


def run(files: list[SourceFile]) -> list[Finding]:
    out: list[Finding] = []
    for src in files:
        out.extend(check_file(src))
    return out
