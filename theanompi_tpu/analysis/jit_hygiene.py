"""TM301/TM302 — jit-hygiene and pickle-reachability lints.

**TM301 (host sync in a jit-reachable function).**  A function traced
by ``jax.jit`` / ``shard_map`` must not synchronize with the host:
``.item()``, ``np.asarray``/``np.array`` on device values,
``jax.device_get``, and ``float()``/``int()``/``bool()`` coercions of
traced values either fail under tracing or — worse — silently constant-
fold a runtime value at trace time.  The pass builds a per-module call
graph, roots it at everything handed to ``jax.jit`` / ``shard_map`` /
``pallas_call`` (decorators, wrapper assignments, builder returns) and
flags host-sync calls in any reachable function.  Scalar coercions of
shape-like expressions (``int(x.shape[0])``, ``len(...)``) are static
under tracing and are not flagged.

**TM302 (unguarded pickle decode).**  ``pickle.loads``/``pickle.load``
executes arbitrary code from the payload.  PR 5's wire v2 pinned
``allow_pickle=False`` for frames the server decodes; this check pins
it *structurally*: every pickle decode in the package must sit in a
function that first checks an ``allow_pickle`` flag and raises when it
is off (the ``_decode_node`` pattern), or carry a baseline suppression
with a reason (e.g. trusted local dataset files).  ``np.load(...,
allow_pickle=True)`` is flagged the same way.
"""

from __future__ import annotations

import ast

from theanompi_tpu.analysis.common import (
    Finding,
    SourceFile,
    call_name,
    dotted_name,
    make_key,
)

CHECK_HOST_SYNC = "TM301"
CHECK_PICKLE = "TM302"

#: callables that wrap a function into a traced program
_TRACER_WRAPPERS = {"jit", "shard_map", "pallas_call", "pmap", "vmap",
                    "grad", "value_and_grad"}
#: of those, the ones that actually root a hot path (vmap/grad alone
#: run eagerly; they still matter when the result is jitted, which the
#: wrapper-of-wrapper scan below catches via the outer jit)
_ROOT_WRAPPERS = {"jit", "shard_map", "pallas_call", "pmap"}

#: dotted call names that force a host sync
_HOST_SYNC_CALLS = {"np.asarray", "np.array", "numpy.asarray",
                    "numpy.array", "jax.device_get", "np.copy"}
#: method names on any object that force a host sync
_HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready"}

_SCALAR_COERCIONS = {"float", "int", "bool"}


def _is_shape_like(node: ast.AST) -> bool:
    """Static-under-tracing expressions: anything touching ``.shape``,
    ``.ndim``, ``.size``, ``len()``, or plain constants/arithmetic on
    them."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in (
                "shape", "ndim", "size", "itemsize", "dtype"):
            return True
        if isinstance(sub, ast.Call):
            n = (dotted_name(sub.func) or "").split(".")[-1]
            if n in ("len", "axis_size", "psum_scatter"):
                return True
    return all(isinstance(s, (ast.Constant, ast.BinOp, ast.UnaryOp,
                              ast.operator, ast.unaryop, ast.expr_context))
               for s in ast.walk(node)) if isinstance(
                   node, (ast.Constant, ast.BinOp, ast.UnaryOp)) else False


# ---------------------------------------------------------------------------
# Call graph per module
# ---------------------------------------------------------------------------


class _Scope:
    """One function in the module graph."""

    def __init__(self, qual: str, node: ast.FunctionDef,
                 cls: str | None):
        self.qual = qual
        self.node = node
        self.cls = cls
        self.calls: set[str] = set()       # plain names called
        self.self_calls: set[str] = set()  # self.<m>() method calls


def _collect_scopes(src: SourceFile) -> dict[str, list[_Scope]]:
    """name -> scopes with that (unqualified) name in this module."""
    scopes: dict[str, list[_Scope]] = {}

    def visit(node: ast.AST, prefix: str, cls: str | None):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}{child.name}.", child.name)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                sc = _Scope(f"{prefix}{child.name}", child, cls)
                scopes.setdefault(child.name, []).append(sc)
                for sub in ast.walk(child):
                    if isinstance(sub, ast.Call):
                        d = dotted_name(sub.func)
                        if d is None:
                            continue
                        if "." not in d:
                            sc.calls.add(d)
                        elif d.startswith("self.") and d.count(".") == 1:
                            sc.self_calls.add(d.split(".", 1)[1])
                visit(child, f"{prefix}{child.name}.", cls)
            else:
                visit(child, prefix, cls)

    visit(src.tree, "", None)
    return scopes


def _root_names(src: SourceFile) -> set[str]:
    """Unqualified names of functions handed to a tracing wrapper."""
    roots: set[str] = set()
    for node in ast.walk(src.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                name = (dotted_name(target) or "").split(".")[-1]
                if name in _ROOT_WRAPPERS:
                    roots.add(node.name)
                elif name == "partial" and isinstance(dec, ast.Call) \
                        and dec.args:
                    inner = (dotted_name(dec.args[0]) or "").split(".")[-1]
                    if inner in _ROOT_WRAPPERS:
                        roots.add(node.name)
        elif isinstance(node, ast.Call):
            name = (call_name(node) or "").split(".")[-1]
            if name in _ROOT_WRAPPERS and node.args:
                ref = dotted_name(node.args[0])
                if ref is not None:
                    roots.add(ref.split(".")[-1])
    return roots


def _reachable(scopes: dict[str, list[_Scope]],
               roots: set[str]) -> list[_Scope]:
    seen: set[str] = set()
    work = [s for name in roots for s in scopes.get(name, [])]
    out: list[_Scope] = []
    while work:
        sc = work.pop()
        if sc.qual in seen:
            continue
        seen.add(sc.qual)
        out.append(sc)
        for callee in sc.calls | sc.self_calls:
            for nxt in scopes.get(callee, []):
                # self.m() resolves within the same class only
                if callee in sc.self_calls and nxt.cls != sc.cls \
                        and callee not in sc.calls:
                    continue
                work.append(nxt)
    return out


# ---------------------------------------------------------------------------
# TM301
# ---------------------------------------------------------------------------


def check_host_sync(src: SourceFile) -> list[Finding]:
    scopes = _collect_scopes(src)
    roots = _root_names(src)
    if not roots:
        return []
    findings: list[Finding] = []
    reported: set[str] = set()
    for sc in _reachable(scopes, roots):
        for node in ast.walk(sc.node):
            if not isinstance(node, ast.Call):
                continue
            d = dotted_name(node.func)
            label = None
            if d in _HOST_SYNC_CALLS:
                label = f"{d}()"
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _HOST_SYNC_METHODS \
                    and not node.args:
                label = f".{node.func.attr}()"
            elif d in _SCALAR_COERCIONS and node.args \
                    and not isinstance(node.args[0], ast.Constant) \
                    and not _is_shape_like(node.args[0]):
                label = f"{d}()"
            if label is None \
                    or src.suppressed(node.lineno, CHECK_HOST_SYNC):
                continue
            key = make_key(CHECK_HOST_SYNC, src.relpath, sc.qual, label)
            if key in reported:
                continue
            reported.add(key)
            findings.append(Finding(
                CHECK_HOST_SYNC, src.relpath, node.lineno,
                f"host-sync {label} inside '{sc.qual}', which is "
                f"reachable from a jax.jit/shard_map hot path", key))
    return findings


# ---------------------------------------------------------------------------
# TM302
# ---------------------------------------------------------------------------


def _has_allow_pickle_guard(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.If):
            test_names = {dotted_name(s) or getattr(s, "attr", "")
                          for s in ast.walk(node.test)
                          if isinstance(s, (ast.Name, ast.Attribute))}
            if any("allow_pickle" in (n or "") for n in test_names):
                if any(isinstance(s, ast.Raise)
                       for s in ast.walk(node)):
                    return True
    return False


def check_pickle(src: SourceFile) -> list[Finding]:
    findings: list[Finding] = []
    # map each pickle decode to its innermost enclosing function
    def visit(node: ast.AST, fn_stack: list[ast.AST], qual: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visit(child, fn_stack + [child], f"{qual}{child.name}.")
                continue
            if isinstance(child, ast.ClassDef):
                visit(child, fn_stack, f"{qual}{child.name}.")
                continue
            if isinstance(child, ast.Call):
                d = dotted_name(child.func) or ""
                flagged = None
                if d in ("pickle.loads", "pickle.load",
                         "cPickle.loads", "cPickle.load"):
                    flagged = d
                elif d.endswith(("np.load", "numpy.load")) or d == "np.load":
                    for kw in child.keywords:
                        if kw.arg == "allow_pickle" \
                                and isinstance(kw.value, ast.Constant) \
                                and kw.value.value is True:
                            flagged = f"{d}(allow_pickle=True)"
                if flagged and not src.suppressed(child.lineno,
                                                 CHECK_PICKLE):
                    guarded = any(_has_allow_pickle_guard(fn)
                                  for fn in fn_stack)
                    if not guarded:
                        scope = qual.rstrip(".") or "<module>"
                        key = make_key(CHECK_PICKLE, src.relpath, scope)
                        if not any(f.key == key for f in findings):
                            findings.append(Finding(
                                CHECK_PICKLE, src.relpath, child.lineno,
                                f"{flagged} in '{scope}' without an "
                                f"allow_pickle guard (wire-v2 servers "
                                f"decode with allow_pickle=False; "
                                f"arbitrary-code-execution surface)",
                                key))
            visit(child, fn_stack, qual)

    visit(src.tree, [], "")
    return findings


def run(files: list[SourceFile]) -> list[Finding]:
    out: list[Finding] = []
    for src in files:
        out.extend(check_host_sync(src))
        out.extend(check_pickle(src))
    return out
