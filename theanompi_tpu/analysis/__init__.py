"""theanompi_tpu.analysis — repo-native correctness tooling.

Two planes (docs/ANALYSIS.md is the operator's reference):

* **static** — the ``tmlint`` checker suite (pure stdlib ``ast``; no
  jax import, no network): guarded-by lint (TM101), use-after-donate
  lint (TM201), jit-hygiene + pickle-reachability lints (TM301/TM302),
  and the docs/instrumentation site-coverage lint (TM401–TM404), all
  gated on zero NEW findings vs ``analysis/baseline.json``;
* **runtime** — ``lockgraph``: an instrumented :class:`TrackedLock` +
  global acquisition-order graph that raises on order cycles (deadlock
  potential), swapped into the threaded host plane under
  ``THEANOMPI_TPU_LOCKCHECK=1`` (tier-1 sets it).

The static plane deliberately does not import the checked code —
checkers parse source, so ``tmlint --gate`` runs in seconds on a cold
CPU box and cannot be wedged by a broken device runtime.
"""

from theanompi_tpu.analysis.common import (
    CHECK_IDS,
    Finding,
    load_baseline,
    split_by_baseline,
    write_baseline,
)
from theanompi_tpu.analysis.lockgraph import (
    GRAPH,
    LockGraph,
    LockOrderError,
    TrackedLock,
    make_condition,
    make_lock,
)

__all__ = [
    "CHECK_IDS", "Finding", "GRAPH", "LockGraph", "LockOrderError",
    "TrackedLock", "load_baseline", "make_condition", "make_lock",
    "split_by_baseline", "write_baseline",
]
