"""Shared plumbing for the repo-native static checker suite.

Every checker (docs/ANALYSIS.md is the catalog) is a pure-stdlib
``ast`` pass over the package source — no imports of the checked code,
no network, no pip.  They share three pieces of plumbing:

* :class:`SourceFile` — parsed module + raw lines (``ast`` drops
  comments, and the guarded-by/suppression conventions live in
  comments, so checkers need both views);
* :class:`Finding` — one diagnostic with a **stable key** that
  deliberately excludes the line number, so a finding keeps its
  identity across unrelated edits and the baseline file does not churn;
* the **baseline** (``analysis/baseline.json``): the committed set of
  accepted finding keys.  The gate is *zero new findings*, not zero
  findings — a judged false positive is suppressed there with a
  ``reason`` instead of contorting the code.

Inline suppression: a line ending in ``# lint: ok`` (optionally
``# lint: ok TM101``) is skipped by every checker (or just the named
check).  Prefer the baseline for anything that needs a recorded
reason.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Iterable, Iterator

#: check IDs -> one-line summaries (the catalog; docs/ANALYSIS.md
#: carries the long form)
CHECK_IDS = {
    "TM101": "guarded_by attribute accessed outside its lock",
    "TM201": "array used after being passed in a donated position",
    "TM301": "host-sync call inside a jit-reachable function",
    "TM302": "pickle decode without an allow_pickle guard",
    "TM401": "fault site fired in code but not documented",
    "TM402": "fault site documented but never fired",
    "TM403": "metric emitted in code but not documented",
    "TM404": "metric documented but never emitted",
}

_SUPPRESS_RE = re.compile(r"#\s*lint:\s*ok(?:\s+(?P<ids>[A-Z0-9, ]+))?")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic.  ``key`` is the stable identity used by the
    baseline; ``line`` is presentation only."""

    check_id: str
    path: str  # repo-relative, '/'-separated
    line: int
    message: str
    key: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.check_id} {self.message}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def make_key(check_id: str, *parts: str) -> str:
    return ":".join((check_id,) + tuple(str(p) for p in parts))


class SourceFile:
    """One parsed module: ast + raw lines + suppression map."""

    def __init__(self, abspath: str, relpath: str):
        self.abspath = abspath
        self.relpath = relpath.replace(os.sep, "/")
        with open(abspath, encoding="utf-8") as f:
            self.source = f.read()
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=relpath)

    def line(self, lineno: int) -> str:
        """1-based physical line ('' when out of range)."""
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def suppressed(self, lineno: int, check_id: str) -> bool:
        """True when ``lineno`` (or the line above it) carries an
        inline ``# lint: ok [IDs]`` matching ``check_id``."""
        for ln in (lineno, lineno - 1):
            m = _SUPPRESS_RE.search(self.line(ln))
            if m:
                ids = m.group("ids")
                if not ids:
                    return True
                if check_id in {s.strip() for s in ids.split(",")}:
                    return True
        return False


def iter_source_files(package_root: str,
                      repo_root: str | None = None,
                      exclude: Iterable[str] = ()) -> Iterator[SourceFile]:
    """Yield every ``.py`` file under ``package_root`` as a
    :class:`SourceFile` with paths relative to ``repo_root``.  Files
    that fail to parse are skipped (the interpreter will complain
    louder than a linter ever could)."""
    repo_root = repo_root or os.path.dirname(package_root)
    exclude = tuple(exclude)
    for dirpath, dirnames, filenames in os.walk(package_root):
        dirnames[:] = sorted(d for d in dirnames
                             if d != "__pycache__")
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            abspath = os.path.join(dirpath, fn)
            rel = os.path.relpath(abspath, repo_root)
            if any(part in rel.replace(os.sep, "/") for part in exclude):
                continue
            try:
                yield SourceFile(abspath, rel)
            except (SyntaxError, UnicodeDecodeError):
                continue


# ---------------------------------------------------------------------------
# AST helpers shared by the checkers
# ---------------------------------------------------------------------------


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain rooted at a Name; None for
    anything dynamic (calls, subscripts, literals)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> str | None:
    """Dotted name of a call's callee (``np.asarray``, ``self.f``)."""
    return dotted_name(node.func)


def const_str(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def int_tuple(node: ast.AST) -> tuple[int, ...] | None:
    """Evaluate a literal int / tuple-of-ints AST node; IfExp takes
    the UNION of both branches (``donate_argnums=(0,) if donate else
    ()`` — whichever way the flag goes, the lint must assume donation
    CAN happen).  None when not statically evaluable."""
    if isinstance(node, ast.IfExp):
        a = int_tuple(node.body)
        b = int_tuple(node.orelse)
        if a is None or b is None:
            return None
        return tuple(sorted(set(a) | set(b)))
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
            else:
                return None
        return tuple(out)
    return None


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------


def load_baseline(path: str) -> dict[str, str]:
    """``{finding_key: reason}`` from ``analysis/baseline.json``;
    empty when the file is absent."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    out: dict[str, str] = {}
    for entry in data.get("suppressions", []):
        out[str(entry["key"])] = str(entry.get("reason", ""))
    return out


def write_baseline(path: str, findings: Iterable[Finding],
                   reasons: dict[str, str] | None = None) -> None:
    """Write every finding's key as a suppression, preserving reasons
    already recorded for keys that persist."""
    reasons = reasons or {}
    entries = []
    seen: set[str] = set()
    for f in findings:
        if f.key in seen:
            continue
        seen.add(f.key)
        entries.append({
            "key": f.key,
            "reason": reasons.get(
                f.key, f"baselined: {f.path}:{f.line} {f.message}"),
        })
    entries.sort(key=lambda e: e["key"])
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "suppressions": entries}, f, indent=2)
        f.write("\n")


def split_by_baseline(findings: list[Finding], baseline: dict[str, str]
                      ) -> tuple[list[Finding], list[str]]:
    """(new_findings, stale_baseline_keys)."""
    live_keys = {f.key for f in findings}
    new = [f for f in findings if f.key not in baseline]
    stale = sorted(k for k in baseline if k not in live_keys)
    return new, stale
