"""theanompi_tpu — a TPU-native distributed training framework.

A from-scratch rebuild of the capabilities of Theano-MPI
(saadmahboob/Theano-MPI; arXiv:1605.08325): data-parallel CNN training
under four parallel rules — synchronous BSP plus asynchronous EASGD,
ASGD and GOSGD — over a model zoo (Cifar10 CNN, AlexNet, GoogLeNet,
VGG16, ResNet-50, Wasserstein GAN), a parallel ImageNet input pipeline,
per-epoch checkpoint/resume, a calc/comm/wait recorder, and
``tmlauncher``/``tmlocal`` entry points.

It is NOT a port.  Where the reference ran one OS process per GPU with
explicit mpi4py/NCCL exchangers (reference layout:
``theanompi/lib/exchanger.py``, ``theanompi/lib/base.py`` — see
SURVEY.md §1–§2; the reference mount was empty so no file:line cites
are possible), this framework is idiomatic JAX/XLA:

* BSP gradient exchange is ``jax.lax.psum`` over a named ``data`` mesh
  axis inside a single jitted SPMD step (ICI collectives scheduled by
  XLA), not a post-step MPI/NCCL call.
* The async rules (EASGD/ASGD/GOSGD) keep their process/actor topology,
  but parameter traffic rides XLA host<->device transfers and (multi-
  host) DCN instead of GPUDirect/mpi4py.
* No CUDA, no mpi4py anywhere in the build.

Public API parity surface (reference ``theanompi/__init__.py``):

    from theanompi_tpu import BSP
    rule = BSP()
    rule.init(devices=..., modelfile='theanompi_tpu.models.cifar10',
              modelclass='Cifar10_model')
    rule.wait()
"""

# jax version shims (installs jax.shard_map on the 0.4.x line) — must
# run before any submodule traces a step; importing the parent package
# happens before any submodule import, so this covers every entry path
from theanompi_tpu import compat as _compat  # noqa: F401

__version__ = "0.1.0"

__all__ = ["BSP", "EASGD", "ASGD", "GOSGD", "__version__"]

_RULES = ("BSP", "EASGD", "ASGD", "GOSGD")


def __getattr__(name):
    # Lazy so that `import theanompi_tpu.parallel` doesn't pull in the
    # whole rule/model stack (and so partial builds stay importable).
    if name in _RULES:
        try:
            import theanompi_tpu.rules as _rules
        except ImportError as e:
            raise AttributeError(
                f"rule {name!r} is unavailable: theanompi_tpu.rules failed "
                f"to import ({e})"
            ) from e
        return getattr(_rules, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
