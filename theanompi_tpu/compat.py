"""JAX version compatibility shims.

The framework targets the modern ``jax.shard_map`` entry point; on
older jax (< 0.5, e.g. the 0.4.x line this image ships) the same
function lives at ``jax.experimental.shard_map.shard_map`` with an
identical call signature for the subset used here (``f, mesh,
in_specs, out_specs``).  Importing this module (done by the package
``__init__``) installs the alias once, so every ``jax.shard_map`` call
site works on both lines without per-module guards.
"""

from __future__ import annotations

import jax

def _accepts_check_vma(fn) -> bool:
    import inspect

    try:
        return "check_vma" in inspect.signature(fn).parameters
    except (TypeError, ValueError):  # C callable / no signature
        return True  # assume modern; the wrapper would be a no-op


_resolved = getattr(jax, "shard_map", None)
if _resolved is None:
    try:
        from jax.experimental.shard_map import shard_map as _resolved
    except ImportError:  # pragma: no cover - very old jax; leave as-is
        _resolved = None

if _resolved is not None and not _accepts_check_vma(_resolved):
    import functools

    _inner = _resolved

    @functools.wraps(_inner)
    def _compat_shard_map(*args, **kwargs):
        # the replication-check kwarg was renamed check_rep ->
        # check_vma when shard_map graduated; accept the new name on
        # any line that still spells it check_rep (whether shard_map
        # lives at jax.shard_map or jax.experimental.shard_map)
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _inner(*args, **kwargs)

    _resolved = _compat_shard_map

if _resolved is not None and getattr(jax, "shard_map", None) is not _resolved:
    jax.shard_map = _resolved


if not hasattr(jax.lax, "axis_size"):
    def _axis_size(axis_name):
        # pre-axis_size idiom: the size of a named axis is the psum of
        # 1 over it (constant-folded by XLA inside shard_map bodies)
        return jax.lax.psum(1, axis_name)

    jax.lax.axis_size = _axis_size
