"""Trace context — the cross-process identity that stitches one fleet
into one timeline.

A trace context is three fields: ``trace_id`` (16 hex chars, shared by
every span of one logical request), the caller's ``span_id`` (the
parent link), and a ``sampled`` flag.  It rides wire v2 as an envelope
op (``wire.TRACE_OP``) that is only sent after both peers granted
``trace`` in the ``wire_hello`` — legacy peers never see it and
degrade silently, exactly like compression/dtype negotiation.

This module is deliberately standalone (stdlib imports only): it is
imported by ``monitor/spans.py`` at module load and by the wire/rpc
layers at call time, so it must sit at the bottom of the import graph.
``spans``/``export`` are resolved lazily at the few call sites that
need them.

Enablement contract (mirrors the monitor facade and ``faults.py``):
tracing is OFF unless ``THEANOMPI_TPU_TRACE`` is set truthy — when
off, ``enabled()`` is one attribute read, ``inject()``/``capture()``
return ``None``, ``attach_wire(...)`` is a no-op context manager, and
spans never allocate ids: the hot path and the local monitor stream
are byte-identical to a build without this module (pinned by
``tests/test_trace.py::test_disabled_mode_byte_identity``).

Sampling: ``THEANOMPI_TPU_TRACE_SAMPLE`` (default 1.0) rolls once at
the trace ROOT; children and remote continuations inherit the
decision, so a trace is always complete-or-absent — never a partial
tree.  Unsampled spans still propagate ids (cheap) but skip export.
"""

from __future__ import annotations

import contextlib
import os
import threading

ENV_VAR = "THEANOMPI_TPU_TRACE"
SAMPLE_ENV_VAR = "THEANOMPI_TPU_TRACE_SAMPLE"
#: address (host:port) of the telemetry collector; consumed by
#: monitor/export.py but defined here so launcher/export/collector
#: agree on one spelling
COLLECTOR_ENV_VAR = "THEANOMPI_TPU_COLLECTOR"

_TRUTHY = ("1", "true", "yes", "on")


class _TraceState:
    """Module state in one bag, swap-able for tests (same pattern as
    the monitor facade's ``_State``)."""

    def __init__(self):
        self.enabled = False
        self.sample = 1.0


_state = _TraceState()
_local = threading.local()


def enabled() -> bool:
    return _state.enabled


def set_enabled(on: bool, sample: float | None = None) -> None:
    """Explicit switch (tests, launcher).  ``sample`` clamps to
    [0, 1]."""
    _state.enabled = bool(on)
    if sample is not None:
        _state.sample = min(1.0, max(0.0, float(sample)))


def activate_from_env() -> None:
    """Re-read the env switches.  Called from ``monitor._activate`` so
    a monkeypatched/exported env var takes effect at session start, not
    only at import time."""
    raw = (os.environ.get(ENV_VAR) or "").strip().lower()
    _state.enabled = raw in _TRUTHY
    try:
        _state.sample = min(1.0, max(0.0, float(
            os.environ.get(SAMPLE_ENV_VAR, "") or 1.0)))
    except ValueError:
        _state.sample = 1.0


def new_id() -> str:
    """64 random bits as 16 hex chars — fork-safe (``os.urandom``, no
    inherited PRNG state) and collision-safe at fleet scale."""
    return os.urandom(8).hex()


def _roll_sample() -> bool:
    s = _state.sample
    if s >= 1.0:
        return True
    if s <= 0.0:
        return False
    return int.from_bytes(os.urandom(2), "big") < int(s * 65536.0)


# ---------------------------------------------------------------------------
# Span linkage (called from spans.Span.__enter__/__exit__)
# ---------------------------------------------------------------------------


def begin(parent) -> tuple[str, str, str | None, bool]:
    """Ids for a span that is entering: ``(trace_id, span_id,
    parent_id, sampled)``.  Parent resolution order: the enclosing
    span on this thread's stack, else the thread's attached remote
    context (an RPC caller on another process), else a fresh root."""
    if parent is not None and getattr(parent, "trace_id", None):
        return parent.trace_id, new_id(), parent.span_id, parent.sampled
    rem = getattr(_local, "remote", None)
    if rem is not None:
        return rem[0], new_id(), rem[1], rem[2]
    return new_id(), new_id(), None, _roll_sample()


def record_span(span, dur_s: float, err: bool) -> None:
    """Ship one finished span to the exporter (no-op when no exporter
    is running or the trace was not sampled).  The record carries BOTH
    clocks — ``t_wall`` for cross-process merging (after collector
    offset correction) and ``t_mono`` for in-process interval math —
    plus thread identity; pid/role/rank are stamped once per batch by
    the exporter."""
    if not span.sampled:
        return
    from theanompi_tpu.monitor import export as _export

    _export.emit({
        "event": "span",
        "trace": span.trace_id,
        "span": span.span_id,
        "parent": span.parent_id,
        "name": span.full_name,
        "labels": dict(span.labels),
        "t_wall": span.t_wall,
        "t_mono": span.t0,
        "dur_s": dur_s,
        "thread": span.thread,
        "err": bool(err),
    })


# ---------------------------------------------------------------------------
# Wire form
# ---------------------------------------------------------------------------


def inject() -> dict | None:
    """The wire-form context for an outgoing RPC: the currently-open
    span on this thread (its own id becomes the server side's parent),
    else the thread's attached remote context (pass-through for
    proxy hops that open no span of their own).  ``None`` when tracing
    is off or nothing is open — callers send a plain message then."""
    if not _state.enabled:
        return None
    from theanompi_tpu.monitor import spans as _spans

    cur = _spans.current_span()
    if cur is not None and getattr(cur, "trace_id", None):
        return {"t": cur.trace_id, "s": cur.span_id,
                "x": 1 if cur.sampled else 0}
    rem = getattr(_local, "remote", None)
    if rem is not None:
        return {"t": rem[0], "s": rem[1], "x": 1 if rem[2] else 0}
    return None


#: cross-thread handoff uses the same derivation as cross-process
#: injection — capture in the submitting thread, attach in the worker
capture = inject


@contextlib.contextmanager
def attach_wire(ctx: dict | None):
    """Attach a wire-form context as this thread's remote parent for
    the duration of the block; spans opened inside become children of
    the caller's span.  Tolerant of ``None``/malformed input (a hostile
    or buggy peer must not break dispatch) and an exact no-op when
    tracing is disabled."""
    if not _state.enabled or not isinstance(ctx, dict):
        yield
        return
    t, s = ctx.get("t"), ctx.get("s")
    if not (isinstance(t, str) and isinstance(s, str)
            and 0 < len(t) <= 32 and 0 < len(s) <= 32):
        yield
        return
    prev = getattr(_local, "remote", None)
    _local.remote = (t, s, bool(ctx.get("x", 1)))
    try:
        yield
    finally:
        _local.remote = prev


def reset_for_tests() -> None:
    global _state
    _state = _TraceState()
    if hasattr(_local, "remote"):
        _local.remote = None
