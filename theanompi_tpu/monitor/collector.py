"""Telemetry collector — the fleet's single merged timeline.

One collector process per run sits behind the SAME substrate as every
other service (``rpc.serve``, HMAC handshake, wire-v2 framing — a
telemetry channel is still an authenticated channel; see
"collector trust model" in docs/OBSERVABILITY.md): every fleet
process's :class:`monitor.export.Exporter` ships span/metric event
batches to it, and the collector appends them — stamped with the
sender's identity (pid, role, rank) and estimated clock offset — to
ONE rotating ``fleet.jsonl`` under the run dir.  ``tools/traces.py``
and ``tools/tmtop.py`` consume that file.

Clock-offset protocol: ``collector_hello`` answers with the
collector's wall/mono clocks; the exporter measures the RPC round
trip and derives ``offset_s`` (midpoint model, see export.py).  The
offset rides every subsequent export batch and is merged into each
event record here, so consumers can map every process's wall stamps
onto the collector's clock without trusting fleet-wide NTP.

Supervision: :class:`CollectorProcess` spawns and watches the real
subprocess exactly like ``ShardProcessGroup`` watches shards —
restart-on-death with a budget (``monitor/collector_restarts_total``).
A dead collector never hurts the fleet: exporters degrade to their
local event files and count ``monitor/export_errors_total``.

Ops: ``ping`` | ``collector_hello`` (clock sample + identity log) |
``collector_export(meta, events)`` | ``collector_stats``.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time

from theanompi_tpu import monitor
from theanompi_tpu.analysis.lockgraph import make_lock
from theanompi_tpu.monitor import trace as _trace
from theanompi_tpu.monitor.export import RotatingJsonlWriter

FLEET_FILE = "fleet.jsonl"

#: identity keys an export batch's meta may carry into merged records
_META_KEYS = ("pid", "role", "rank", "offset_s", "rtt_s")


class TelemetryCollector:
    """``handle(op, *args)`` duck type for ``rpc.serve``."""

    #: hello/stats answer from the control pool so a flood of export
    #: batches can never starve the clock handshake
    RPC_CONTROL_OPS = frozenset({"collector_hello", "collector_stats"})

    def __init__(self, run_dir: str):
        os.makedirs(run_dir, exist_ok=True)
        self.run_dir = run_dir
        self.path = os.path.join(run_dir, FLEET_FILE)
        self._writer = RotatingJsonlWriter(self.path)
        self._lock = make_lock("TelemetryCollector._lock")
        self.n_events = 0        # guarded_by: self._lock
        self.n_batches = 0       # guarded_by: self._lock
        self.senders: dict = {}  # guarded_by: self._lock

    def handle(self, op: str, *args):
        if op == "ping":
            return "pong"
        if op == "collector_hello":
            meta = args[0] if args and isinstance(args[0], dict) else {}
            with self._lock:
                self.senders[(meta.get("pid"), meta.get("role"))] = \
                    time.time()
            return {"t_wall": time.time(), "t_mono": time.monotonic()}
        if op == "collector_export":
            if len(args) != 2:
                raise ValueError("collector_export wants (meta, events)")
            return self._ingest(args[0], args[1])
        if op == "collector_stats":
            return self.stats()
        raise ValueError(f"unknown op {op!r}")

    def _ingest(self, meta, events) -> int:
        if not isinstance(meta, dict) or not isinstance(events, list):
            raise ValueError("malformed export batch")
        ident = {k: meta[k] for k in _META_KEYS if k in meta}
        recs = [{**ev, **ident} for ev in events
                if isinstance(ev, dict)]
        self._writer.write_events(recs)
        with self._lock:
            self.n_events += len(recs)
            self.n_batches += 1
            self.senders[(meta.get("pid"), meta.get("role"))] = \
                time.time()
        monitor.inc("monitor/collector_events_total", len(recs),
                    role=str(meta.get("role")))
        monitor.inc("monitor/collector_batches_total")
        return len(recs)

    def stats(self) -> dict:
        with self._lock:
            return {"events": self.n_events, "batches": self.n_batches,
                    "senders": len(self.senders), "path": self.path,
                    "rotations": self._writer.rotations}


def serve_collector(host: str, port: int, run_dir: str,
                    ready_event: threading.Event | None = None,
                    stop_event: threading.Event | None = None,
                    authkey: bytes | None = None) -> None:
    from theanompi_tpu.parallel import rpc
    from theanompi_tpu.parallel.service import _authkey

    class _CollectorRpcHooks(rpc.RpcHooks):
        plane = "collector"

    rpc.serve(TelemetryCollector(run_dir), host, port,
              ready_event=ready_event, stop_event=stop_event,
              authkey=authkey if authkey is not None else _authkey(),
              hooks=_CollectorRpcHooks())


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="theanompi-tpu telemetry collector — merged fleet "
                    "JSONL behind the authenticated RPC substrate "
                    "(docs/OBSERVABILITY.md 'Distributed tracing')")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--dir", required=True,
                    help="run dir; fleet.jsonl is written here")
    args = ap.parse_args(argv)
    # the collector must never export to ITSELF: its own rpc_handle
    # spans shipping through its own exporter would amplify every
    # batch into more batches, forever.  It keeps a local monitor
    # session (its service/* and collector_* series) with tracing and
    # collector shipping stripped.
    os.environ.pop(_trace.COLLECTOR_ENV_VAR, None)
    os.environ.pop(_trace.ENV_VAR, None)
    print(f"[collector] listening on {args.host}:{args.port}, "
          f"fleet file under {args.dir}", flush=True)
    with monitor.session(stall_after=float("inf"),
                         name=f"collector{os.getpid()}"):
        monitor.progress(phase="serving")
        serve_collector(args.host, args.port, args.dir)
    return 0


class CollectorProcess:
    """Spawn + supervise the collector subprocess (launcher seam,
    mirroring ``ShardProcessGroup``): restart-on-death with a budget,
    TCP-probe readiness, terminate-then-kill stop.  Exports
    ``THEANOMPI_TPU_COLLECTOR`` so every child the launcher forks
    afterwards ships its telemetry here."""

    def __init__(self, run_dir: str, host: str = "127.0.0.1",
                 max_restarts: int = 3, ready_timeout_s: float = 60.0):
        from theanompi_tpu.parallel.service import _authkey
        from theanompi_tpu.parallel.shards import _free_port

        _authkey(generate=True)  # ensure + export the shared key
        self.run_dir = run_dir
        self.host = host
        self.port = _free_port()
        self.max_restarts = int(max_restarts)
        self._lock = make_lock("CollectorProcess._lock")
        self._stopping = threading.Event()
        self._proc = self._spawn()      # guarded_by: self._lock
        self.restarts = 0               # guarded_by: self._lock
        self._wait_ready(ready_timeout_s)
        os.environ[_trace.COLLECTOR_ENV_VAR] = self.addr
        self._watcher = threading.Thread(
            target=self._watch, daemon=True, name="collector-watcher")
        self._watcher.start()

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    def _spawn(self) -> subprocess.Popen:
        env = dict(os.environ)
        # the collector does no array math — never let it claim a chip,
        # and never let it ship to itself (main() strips these too;
        # belt and braces for custom entrypoints)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop(_trace.COLLECTOR_ENV_VAR, None)
        cmd = [sys.executable, "-m", "theanompi_tpu.monitor.collector",
               "--host", self.host, "--port", str(self.port),
               "--dir", self.run_dir]
        return subprocess.Popen(cmd, env=env)

    def _wait_ready(self, timeout_s: float) -> None:
        from theanompi_tpu.parallel.service import ServiceClient

        deadline = time.monotonic() + timeout_s
        while True:
            c = None
            try:
                c = ServiceClient(self.addr)
                c.call("ping")
                return
            except Exception:
                with self._lock:
                    rc = self._proc.poll()
                if rc is not None:
                    raise RuntimeError(
                        f"collector died during startup (rc={rc})")
                if time.monotonic() > deadline:
                    self.stop()
                    raise RuntimeError(
                        f"collector at {self.addr} never came up "
                        f"within {timeout_s}s")
                time.sleep(0.2)
            finally:
                if c is not None:
                    c.close()

    def _watch(self) -> None:
        while not self._stopping.wait(0.5):
            with self._lock:
                proc = self._proc
            if proc.poll() is None or self._stopping.is_set():
                continue
            with self._lock:
                if self.restarts >= self.max_restarts:
                    continue  # budget spent: exporters degrade local
                self.restarts += 1
                n = self.restarts
                self._proc = self._spawn()
            print(f"[collector] died (rc={proc.returncode}); "
                  f"relaunched on port {self.port} "
                  f"({n}/{self.max_restarts})",
                  file=sys.stderr, flush=True)
            monitor.inc("monitor/collector_restarts_total")

    def stats(self) -> dict | None:
        """Live collector stats (None while it is down)."""
        from theanompi_tpu.parallel.service import ServiceClient

        c = None
        try:
            c = ServiceClient(self.addr)
            return c.call("collector_stats")
        except Exception:
            return None
        finally:
            if c is not None:
                c.close()

    def stop(self) -> None:
        self._stopping.set()
        if getattr(self, "_watcher", None) is not None \
                and self._watcher.is_alive():
            self._watcher.join(timeout=5)
        if os.environ.get(_trace.COLLECTOR_ENV_VAR) == self.addr:
            del os.environ[_trace.COLLECTOR_ENV_VAR]
        with self._lock:
            proc = self._proc
        if proc.poll() is None:
            proc.terminate()
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=5)

    def __enter__(self) -> "CollectorProcess":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


def read_fleet(path: str) -> list[dict]:
    """All records of a fleet JSONL (rotated files first, oldest to
    newest) — the consumers' loader."""
    out: list[dict] = []
    rotated = []
    i = 1
    while os.path.exists(f"{path}.{i}"):
        rotated.append(f"{path}.{i}")
        i += 1
    for p in [*reversed(rotated), path]:
        try:
            with open(p, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        continue  # torn tail line mid-write
        except OSError:
            continue
    return out


if __name__ == "__main__":
    sys.exit(main())
