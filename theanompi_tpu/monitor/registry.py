"""Metrics registry — counters, gauges, streaming histograms.

The reference framework's only instrumentation was the Recorder's wall
timers and printed epoch lines (Theano-MPI §4 measured its calc/comm
breakdowns exactly that way); everything else was ``print(...,
flush=True)``.  This registry is the structured replacement: a
process-wide, thread-safe store of labeled series that every layer
(rule loops, the parameter service, the exchanger, bench probes) writes
into, snapshot-able as JSONL and as a Prometheus-style text dump.

Design constraints, in order:

1. **Strict no-op when disabled.**  The hot path (one observation per
   training step) must cost a single attribute check when monitoring is
   off.  That gate lives in the facade (``theanompi_tpu/monitor``);
   the registry itself always works — tests and the postmortem hook use
   a bare registry directly.
2. **Thread-safe.**  The async rules run one worker thread per device
   and the service runs one handler thread per connection; all of them
   share one registry.  One lock per registry, held only for O(1)
   dict/deque work — never around I/O.
3. **Bounded memory.**  Histograms are streaming: exact count/sum/
   min/max plus a fixed-size ring of recent observations for the
   p50/p95/p99 estimates.  A week-long run holds the same few KB per
   series as a 5-step smoke.

Series are keyed by ``(name, sorted(labels))`` so ``rpc_ms{op=a}`` and
``rpc_ms{op=b}`` are isolated series under one logical name.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from collections import deque
from typing import Any, Iterable

#: ring size for histogram percentile estimation — large enough that
#: p99 over a training epoch is meaningful, small enough to be noise
#: in memory (8 KB of floats per series)
HISTOGRAM_RING = 1024

LabelKey = tuple[tuple[str, str], ...]


def _label_key(labels: dict[str, Any]) -> LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def atomic_write_text(path: str, text: str) -> None:
    """Write-then-rename publication, shared by every monitor file
    writer (snapshot, heartbeat, postmortem).  The tmp name carries
    pid AND thread id: the heartbeat thread and a same-process caller
    (flush(), stop(), finalize) must never truncate each other's
    half-written tmp file."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)


class Counter:
    """Monotonic counter (events, bytes, errors)."""

    kind = "counter"

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        self.value += amount

    def state(self) -> dict:
        return {"value": self.value}


class Gauge:
    """Last-write-wins instantaneous value (connected clients, bytes
    per exchange, current LR)."""

    kind = "gauge"

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, value: float) -> None:
        self.value = float(value)

    def add(self, delta: float) -> None:
        self.value += delta

    def state(self) -> dict:
        return {"value": self.value}


class Histogram:
    """Streaming histogram: exact count/sum/min/max, percentile
    estimates (p50/p95/p99) from a ring of the most recent
    ``HISTOGRAM_RING`` observations.

    Percentile edges: an empty histogram reports ``None`` percentiles;
    a single observation reports that value for every percentile
    (nearest-rank on a 1-element sample)."""

    kind = "histogram"

    __slots__ = ("count", "sum", "min", "max", "_ring")

    PERCENTILES = (50.0, 95.0, 99.0)

    def __init__(self, ring: int = HISTOGRAM_RING):
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._ring: deque[float] = deque(maxlen=ring)

    def observe(self, value: float) -> None:
        v = float(value)
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        self._ring.append(v)

    def percentile(self, q: float) -> float | None:
        """Nearest-rank percentile over the recent-observation ring.
        ``q`` in [0, 100].  None when empty."""
        if not self._ring:
            return None
        data = sorted(self._ring)
        # nearest-rank: ceil(q/100 * n), 1-indexed, clamped to [1, n]
        rank = max(1, min(len(data), math.ceil(q / 100.0 * len(data))))
        return data[rank - 1]

    def state(self) -> dict:
        out = {
            "count": self.count,
            "sum": round(self.sum, 6),
            "min": None if self.count == 0 else self.min,
            "max": None if self.count == 0 else self.max,
            "mean": None if self.count == 0 else self.sum / self.count,
        }
        for q in self.PERCENTILES:
            out[f"p{q:g}"] = self.percentile(q)
        return out


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricsRegistry:
    """Process-wide store of labeled metric series.

    ``write_count`` counts every mutation — the no-op contract of the
    disabled facade is tested as "a full rule session leaves the global
    registry's write_count at zero"."""

    def __init__(self):
        self._lock = threading.Lock()
        self._series: dict[tuple[str, LabelKey], Any] = {}  # guarded_by: self._lock
        self._kinds: dict[str, str] = {}                    # guarded_by: self._lock
        self.write_count = 0                                # guarded_by: self._lock
        self.created_at = time.time()

    # -- series access -------------------------------------------------

    def _get(self, kind: str, name: str, labels: dict[str, Any]):  # requires_lock: self._lock
        declared = self._kinds.setdefault(name, kind)
        if declared != kind:
            raise TypeError(
                f"metric {name!r} already registered as {declared}, "
                f"cannot use as {kind}")
        key = (name, _label_key(labels))
        series = self._series.get(key)
        if series is None:
            series = self._series[key] = _KINDS[kind]()
        return series

    def inc(self, name: str, amount: float = 1.0, /, **labels) -> None:
        with self._lock:
            self._get("counter", name, labels).inc(amount)
            self.write_count += 1

    def set_gauge(self, name: str, value: float, /, **labels) -> None:
        with self._lock:
            self._get("gauge", name, labels).set(value)
            self.write_count += 1

    def add_gauge(self, name: str, delta: float, /, **labels) -> None:
        with self._lock:
            self._get("gauge", name, labels).add(delta)
            self.write_count += 1

    def observe(self, name: str, value: float, /, **labels) -> None:
        with self._lock:
            self._get("histogram", name, labels).observe(value)
            self.write_count += 1

    # -- reads ---------------------------------------------------------

    def get(self, name: str, /, **labels):
        """The raw series object (None if absent) — for tests and the
        watchdog's own reads; mutating it bypasses write_count."""
        with self._lock:
            return self._series.get((name, _label_key(labels)))

    def value(self, name: str, /, **labels) -> float | None:
        s = self.get(name, **labels)
        return None if s is None or not hasattr(s, "value") else s.value

    def series_names(self) -> set[str]:
        with self._lock:
            return {name for name, _ in self._series}

    # -- snapshots -----------------------------------------------------

    def snapshot(self) -> list[dict]:
        """One dict per series: name, kind, labels, state.  Taken under
        the lock (consistent point-in-time view), JSON-ready."""
        now = time.time()
        with self._lock:
            items = sorted(self._series.items(),
                           key=lambda kv: (kv[0][0], kv[0][1]))
            return [
                {"ts": now, "name": name, "kind": series.kind,
                 "labels": dict(lk), **series.state()}
                for (name, lk), series in items
            ]

    def write_jsonl(self, path: str) -> str:
        """Atomically (re)write the snapshot as JSONL — one series per
        line.  Overwrites: the file is the LATEST state, not an append
        log (watchdogs and the preflight smoke read it whole; history
        lives in the Recorder's per-epoch JSONL)."""
        snap = self.snapshot()
        atomic_write_text(path, "".join(json.dumps(rec) + "\n"
                                        for rec in snap))
        return path

    def to_prometheus(self) -> str:
        """Prometheus text exposition (counters/gauges as-is;
        histograms as summary-style quantile lines + _count/_sum)."""
        lines: list[str] = []
        seen_types: set[str] = set()
        for rec in self.snapshot():
            pname = _prom_name(rec["name"])
            if pname not in seen_types:
                ptype = {"counter": "counter", "gauge": "gauge",
                         "histogram": "summary"}[rec["kind"]]
                lines.append(f"# TYPE {pname} {ptype}")
                seen_types.add(pname)
            labels = rec["labels"]
            if rec["kind"] == "histogram":
                lines.append(f"{pname}_count{_prom_labels(labels)} "
                             f"{rec['count']}")
                lines.append(f"{pname}_sum{_prom_labels(labels)} "
                             f"{rec['sum']}")
                for q in (50, 95, 99):
                    v = rec[f"p{q}"]
                    if v is not None:
                        ql = dict(labels, quantile=f"0.{q}")
                        lines.append(f"{pname}{_prom_labels(ql)} {v}")
            else:
                lines.append(f"{pname}{_prom_labels(labels)} "
                             f"{rec['value']}")
        return "\n".join(lines) + ("\n" if lines else "")


def _prom_name(name: str) -> str:
    """``service/rpc_ms`` -> ``theanompi_service_rpc_ms`` (slashes and
    dots are series namespacing here, underscores on the wire)."""
    safe = "".join(c if c.isalnum() or c == "_" else "_" for c in name)
    return f"theanompi_{safe}"


def _prom_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""

    def esc(v: str) -> str:
        # exposition-format escaping: one unescaped quote in a label
        # value (e.g. a client-supplied op name) would invalidate the
        # whole dump for a Prometheus parser
        return (str(v).replace("\\", "\\\\").replace('"', '\\"')
                .replace("\n", "\\n"))

    inner = ",".join(f'{k}="{esc(v)}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def tree_bytes(tree: Any) -> int:
    """Total byte size of a pytree of arrays (numpy, jax, or abstract
    tracers — anything exposing ``.size``/``.dtype``).  Used by the
    exchanger's bytes counters and the service client's wire
    accounting; non-array leaves count 0."""
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree.leaves(tree):
        size = getattr(leaf, "size", None)
        dtype = getattr(leaf, "dtype", None)
        if size is not None and dtype is not None:
            total += int(size) * np.dtype(dtype).itemsize
        elif isinstance(leaf, (bytes, bytearray)):
            total += len(leaf)
    return total


def tree_dtypes(tree: Any) -> str:
    """Sorted comma-joined dtype set of a pytree — the ``dtype`` label
    for exchange counters (one label value per exchange call, not one
    series per leaf)."""
    import jax

    names: set[str] = set()
    for leaf in jax.tree.leaves(tree):
        dt = getattr(leaf, "dtype", None)
        if dt is not None:
            names.add(str(dt))
    return ",".join(sorted(names)) or "none"
