"""Event export — the bounded, drop-counting bridge from one process's
monitor to the fleet collector.

Two pieces:

* :class:`RotatingJsonlWriter` — an append-only JSONL writer with
  size-based rotation (``THEANOMPI_TPU_MONITOR_MAX_BYTES``, keep-N
  files) so week-long runs cannot fill the disk; the rotation itself
  is counted (``monitor/rotations_total``).  The registry snapshot
  files are overwrite-in-place and never grow — rotation exists for
  the two APPENDING streams this PR introduces: the local span-event
  JSONL and the collector's merged fleet JSONL.
* :class:`Exporter` — a background thread (name family
  ``monitor-export-*``) fed by :func:`emit` from span exit.  The hot
  path only appends to a bounded deque under a lock: a full buffer
  **drops and counts** (``monitor/export_dropped_total``), it never
  blocks.  The thread drains batches to the local events file and —
  when ``THEANOMPI_TPU_COLLECTOR`` names a collector — ships them over
  the ordinary ``ServiceClient``/HMAC/wire-v2 stack.  A dead collector
  degrades to local-only (``monitor/export_errors_total``, with
  reconnect backoff); it never fails a caller.

Clock-offset model: at the export handshake the exporter calls
``collector_hello`` and assumes the collector stamped its wall clock
at the midpoint of the RPC round trip; ``offset_s = server_t_wall -
(client_t_wall_now - rtt/2)`` maps this process's wall timestamps onto
the collector's clock.  The offset (and the rtt that bounds its error)
ride every export batch, so ``tools/traces.py`` can align spans from
processes whose wall clocks disagree.

The exporter is started/stopped by the monitor session
(``monitor._activate``/``_finalize``) only when tracing or a collector
address is configured — otherwise :func:`emit` is one global read and
a ``None`` check, preserving the disabled no-op contract.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from theanompi_tpu.analysis.lockgraph import make_lock
from theanompi_tpu.monitor import trace as _trace

MAX_BYTES_ENV = "THEANOMPI_TPU_MONITOR_MAX_BYTES"
KEEP_ENV = "THEANOMPI_TPU_MONITOR_KEEP"
BUFFER_ENV = "THEANOMPI_TPU_EXPORT_BUFFER"
FLUSH_ENV = "THEANOMPI_TPU_EXPORT_FLUSH_S"
METRICS_ENV = "THEANOMPI_TPU_EXPORT_METRICS_S"

#: the process-wide exporter, None unless a monitor session started
#: one.  Read unlocked on the emit fast path (attribute read of a
#: module global is atomic); swapped only under the monitor session
#: lock.
_exporter: "Exporter | None" = None


def set_exporter(ex: "Exporter | None") -> None:
    global _exporter
    _exporter = ex


def emit(event: dict) -> None:
    """Hand one event to the running exporter; silently dropped when
    none is running (tracing without a session, or export disabled)."""
    ex = _exporter
    if ex is not None:
        ex.emit(event)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class RotatingJsonlWriter:
    """Append JSON lines to ``path``; when the file would exceed
    ``max_bytes``, shift ``path -> path.1 -> ... -> path.keep`` (the
    oldest falls off) and start fresh.  Thread-safe; write failures
    are swallowed (telemetry must never take down the workload) after
    counting via the monitor facade."""

    def __init__(self, path: str, max_bytes: int | None = None,
                 keep: int | None = None):
        self.path = path
        self.max_bytes = max_bytes if max_bytes is not None \
            else _env_int(MAX_BYTES_ENV, 64 << 20)
        self.keep = keep if keep is not None else _env_int(KEEP_ENV, 3)
        self._lock = make_lock("RotatingJsonlWriter._lock")
        self._size = -1          # guarded_by: self._lock
        self.rotations = 0       # guarded_by: self._lock

    def write_lines(self, lines: list[str]) -> None:
        if not lines:
            return
        blob = "".join(line + "\n" for line in lines)
        data = blob.encode("utf-8")
        with self._lock:
            try:
                if self._size < 0:  # first write: pick up existing size
                    try:
                        self._size = os.path.getsize(self.path)
                    except OSError:
                        self._size = 0
                if self.max_bytes > 0 \
                        and self._size + len(data) > self.max_bytes \
                        and self._size > 0:
                    self._rotate_locked()
                with open(self.path, "ab") as f:
                    f.write(data)
                self._size += len(data)
            except OSError:
                return

    def write_events(self, events: list[dict]) -> None:
        self.write_lines([json.dumps(ev, default=str, sort_keys=True)
                          for ev in events])

    def _rotate_locked(self) -> None:  # requires_lock: self._lock
        from theanompi_tpu import monitor

        for i in range(self.keep - 1, 0, -1):
            src = f"{self.path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i + 1}")
        if self.keep > 0:
            os.replace(self.path, f"{self.path}.1")
        else:
            os.remove(self.path)
        self._size = 0
        self.rotations += 1
        monitor.inc("monitor/rotations_total",
                    file=os.path.basename(self.path))


class Exporter:
    """Bounded background shipper for span/metric events.  See module
    docstring for the contract; the one invariant everything else
    hangs off: :meth:`emit` is O(1), lock-append-or-drop, and can
    never raise into a hot path."""

    def __init__(self, run_dir: str, suffix: str, rank: int, registry,
                 collector: str | None = None,
                 capacity: int | None = None,
                 flush_s: float | None = None,
                 metrics_every_s: float | None = None):
        self.run_dir = run_dir
        self.suffix = suffix
        self.collector = collector
        self._registry = registry
        self._cap = capacity if capacity is not None \
            else _env_int(BUFFER_ENV, 4096)
        self._flush_s = flush_s if flush_s is not None \
            else _env_float(FLUSH_ENV, 0.5)
        self._metrics_s = metrics_every_s if metrics_every_s is not None \
            else _env_float(METRICS_ENV, 2.0)
        self._meta = {"pid": os.getpid(), "role": suffix,
                      "rank": int(rank)}
        self._writer = RotatingJsonlWriter(
            os.path.join(run_dir, f"events_{suffix}.jsonl"))
        self._lock = make_lock("Exporter._lock")
        self._buf: deque = deque()   # guarded_by: self._lock
        self.dropped = 0             # guarded_by: self._lock
        self._wake = threading.Event()
        self._stop = threading.Event()
        # exporter-thread-private shipping state (single-threaded, no
        # lock): the client, its clock offset, and reconnect backoff
        self._client = None
        self._offset_s: float | None = None
        self._rtt_s: float | None = None
        self._next_connect = 0.0
        self._next_metrics = 0.0
        self._thread: threading.Thread | None = None

    # -- hot path ----------------------------------------------------

    def emit(self, event: dict) -> None:
        with self._lock:
            if len(self._buf) >= self._cap:
                self.dropped += 1
                self._registry.inc("monitor/export_dropped_total")
                return
            self._buf.append(event)
        self._wake.set()

    # -- lifecycle ---------------------------------------------------

    def start(self) -> "Exporter":
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"monitor-export-{self.suffix}")
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        client, self._client = self._client, None
        if client is not None:
            try:
                client.close()
            except Exception:
                pass

    # -- exporter thread ---------------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self._flush_s)
            self._wake.clear()
            self._flush_once()
        self._flush_once()  # final drain so short sessions lose nothing

    def _flush_once(self) -> None:
        with self._lock:
            batch = list(self._buf)
            self._buf.clear()
        self._registry.set_gauge("monitor/export_buffer",
                                 float(len(batch)))
        now = time.monotonic()
        ship = list(batch)
        if self.collector and now >= self._next_metrics:
            self._next_metrics = now + self._metrics_s
            ship.append({"event": "metrics", "t_wall": time.time(),
                         "t_mono": now,
                         "snapshot": self._registry.snapshot()})
        if batch:
            # local file gets identity merged per line; the collector
            # path ships identity once per batch instead
            self._writer.write_events(
                [{**ev, **self._meta} for ev in batch])
        if ship and self.collector:
            self._ship(ship)

    def _ship(self, events: list[dict]) -> None:
        client = self._ensure_client()
        if client is None:
            return
        meta = dict(self._meta)
        if self._offset_s is not None:
            meta["offset_s"] = self._offset_s
            meta["rtt_s"] = self._rtt_s
        try:
            client.call("collector_export", meta, events)
            self._registry.inc("monitor/export_batches_total")
        except Exception:
            self._registry.inc("monitor/export_errors_total")
            self._drop_client()

    def _ensure_client(self):
        if self._client is not None:
            return self._client
        if time.monotonic() < self._next_connect:
            return None
        # lazy import: monitor must not pull the service/rpc stack in
        # at import time (service imports monitor, not vice versa)
        try:
            from theanompi_tpu.parallel.service import ServiceClient
            from theanompi_tpu.resilience.retry import RetryPolicy

            client = ServiceClient(
                str(self.collector),
                retry=RetryPolicy(max_attempts=1, deadline_s=5.0,
                                  name="export"))
            t0 = time.monotonic()
            reply = client.call("collector_hello", dict(self._meta))
            rtt = time.monotonic() - t0
            # midpoint model: the collector stamped its wall clock
            # roughly rtt/2 ago
            self._offset_s = float(reply["t_wall"]) \
                - (time.time() - rtt / 2.0)
            self._rtt_s = rtt
            self._client = client
            return client
        except Exception:
            self._registry.inc("monitor/export_errors_total")
            self._next_connect = time.monotonic() + 2.0
            return None

    def _drop_client(self) -> None:
        client, self._client = self._client, None
        self._next_connect = time.monotonic() + 2.0
        if client is not None:
            try:
                client.close()
            except Exception:
                pass

    def stats(self) -> dict:
        with self._lock:
            return {"buffered": len(self._buf), "dropped": self.dropped,
                    "offset_s": self._offset_s, "rtt_s": self._rtt_s,
                    "collector": self.collector}


def maybe_start(run_dir: str, suffix: str, rank: int,
                registry) -> "Exporter | None":
    """Session hook: start an exporter iff tracing is on or a
    collector is configured (either alone is useful — local-only trace
    files, or metrics-only fleet shipping)."""
    collector = os.environ.get(_trace.COLLECTOR_ENV_VAR) or None
    if not (_trace.enabled() or collector):
        return None
    ex = Exporter(run_dir, suffix, rank, registry,
                  collector=collector).start()
    set_exporter(ex)
    return ex
