"""Span tracing — nested wall-clock spans that line up with XLA traces.

A span is a named wall-clock interval around a phase of work
(``with span("comm/psum"): ...``).  Three things happen per span:

1. **Honest timing.**  Under jit the step call returns before the
   device finishes (async dispatch), so a naive wall timer measures
   dispatch, not compute.  A span can *fence* on a device array or
   pytree at exit (``fence=...``) via the same ``device_fence`` the
   Recorder uses — truthful on the axon plugin too, which returns
   early from ``block_until_ready`` (utils/recorder.py).
2. **XLA alignment.**  Each span enters a
   ``jax.profiler.TraceAnnotation``, so when a StepProfiler capture is
   active the span shows up as a named region in the TensorBoard/xprof
   timeline — host spans and HLO ops on one ruler.
3. **Registry feed.**  On exit the duration lands in the registry
   histogram ``span_ms{name=...}`` (count + sum there give per-section
   totals; p50/p95/p99 give the distribution).

Nesting is tracked per-thread; the full name of a nested span is
``parent/child`` so ``with span("epoch"): with span("val")`` emits
``epoch/val``.  Open spans are globally visible (`open_spans()`) so
the postmortem dump can say exactly which phase a crash or hang was
inside — the r04 bench spent 240 s wedged in device init with no such
signal.
"""

from __future__ import annotations

import threading
import time
from typing import Any

from theanompi_tpu.monitor import trace as _trace

_local = threading.local()

#: all currently-open spans across threads: id(span) -> Span.  The
#: postmortem hook reads this; entries are tiny and removed on exit.
_open: dict[int, "Span"] = {}
_open_lock = threading.Lock()


def _stack() -> list["Span"]:
    st = getattr(_local, "stack", None)
    if st is None:
        st = _local.stack = []
    return st


def _fence(tree: Any) -> None:
    # lazy import: utils.recorder imports the monitor facade, which
    # imports this module — resolving device_fence at call time keeps
    # the import graph acyclic
    from theanompi_tpu.utils.recorder import device_fence

    device_fence(tree)


class Span:
    """One timed interval.  Use via ``monitor.span(...)`` (the facade
    returns a no-op when monitoring is disabled) or directly in tests.

    ``registry=None`` times and nests but records nowhere — the bench
    uses that mode when it only wants TraceAnnotation alignment."""

    __slots__ = ("name", "full_name", "labels", "fence_on", "registry",
                 "t0", "t_wall", "thread", "_annotation", "_annotate",
                 "trace_id", "span_id", "parent_id", "sampled")

    def __init__(self, name: str, registry=None, fence: Any = None,
                 annotate: bool = True, **labels):
        self.name = name
        self.full_name = name  # finalized on __enter__ from the stack
        self.labels = labels
        self.fence_on = fence
        self.registry = registry
        self.t0 = 0.0
        self.t_wall = 0.0
        self.thread = threading.current_thread().name
        self._annotate = annotate
        self._annotation = None
        # trace linkage — ids stay None unless tracing is enabled at
        # __enter__, so the disabled path allocates nothing
        self.trace_id: str | None = None
        self.span_id: str | None = None
        self.parent_id: str | None = None
        self.sampled = False

    def __enter__(self) -> "Span":
        # t0 must be set before the span becomes globally visible, or
        # a concurrent open_spans()/postmortem snapshot would compute
        # age from 0.0 (host-uptime-sized garbage)
        self.t0 = time.monotonic()
        st = _stack()
        if st:
            self.full_name = f"{st[-1].full_name}/{self.name}"
        if _trace.enabled():
            (self.trace_id, self.span_id,
             self.parent_id, self.sampled) = _trace.begin(
                st[-1] if st else None)
        st.append(self)
        with _open_lock:
            _open[id(self)] = self
        if self._annotate:
            try:
                import jax

                self._annotation = jax.profiler.TraceAnnotation(
                    self.full_name)
                self._annotation.__enter__()
            except Exception:
                # annotation is best-effort alignment; a failure here
                # must not abort __enter__ AFTER the span registered
                # itself in _open/_stack (the with-statement would
                # never run __exit__, leaking a ghost open span)
                self._annotation = None
        # re-stamp after annotation setup so its cost (first jax
        # import can be slow) isn't charged to the timed block; the
        # wall stamp pairs with the SAME instant so merged timelines
        # and in-process interval math describe one interval
        self.t0 = time.monotonic()
        self.t_wall = time.time()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        try:
            if self.fence_on is not None and exc_type is None:
                _fence(self.fence_on)
        finally:
            dt = time.monotonic() - self.t0
            if self._annotation is not None:
                try:
                    self._annotation.__exit__(exc_type, exc, tb)
                except Exception:
                    # profiler teardown racing an open span (e.g.
                    # StepProfiler.stop() on the crash path) must not
                    # skip the stack/_open cleanup below or mask the
                    # body's exception
                    pass
                self._annotation = None
            st = _stack()
            if st and st[-1] is self:
                st.pop()
            else:  # exited out of order (shouldn't happen) — scrub
                try:
                    st.remove(self)
                except ValueError:
                    pass
            with _open_lock:
                _open.pop(id(self), None)
            if self.registry is not None:
                self.registry.observe("span_ms", dt * 1e3,
                                      name=self.full_name, **self.labels)
                if exc_type is not None:
                    self.registry.inc("span_errors_total",
                                      name=self.full_name)
            if self.trace_id is not None:
                _trace.record_span(self, dt, exc_type is not None)

    @property
    def age_s(self) -> float:
        return time.monotonic() - self.t0


class _NullSpan:
    """The disabled fast path: a shared, reentrant, stateless no-op."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return None


NULL_SPAN = _NullSpan()


def current_span() -> Span | None:
    st = getattr(_local, "stack", None)
    return st[-1] if st else None


def open_spans() -> list[dict]:
    """Snapshot of every open span in the process (all threads),
    oldest first — the postmortem's "where was everyone" view."""
    with _open_lock:
        spans = list(_open.values())
    spans.sort(key=lambda s: s.t0)
    out = []
    for s in spans:
        d = {"name": s.full_name, "thread": s.thread,
             "age_s": round(s.age_s, 3), "labels": s.labels}
        if s.trace_id is not None:  # only under tracing — the
            # disabled-mode snapshot stays byte-identical to pre-trace
            d["trace"] = s.trace_id
            d["span"] = s.span_id
        out.append(d)
    return out
